"""Device fault survival (ISSUE 15): seeded accelerator chaos, containment,
sampled shadow verification, and the quarantine/canary health ladder.

The oracle throughout is the PR-era byte-equivalence discipline: whatever
the device plane does — raise, stall, corrupt — the record stream must stay
byte-identical to the sequential engine's, because every defense layer ends
in "the host result wins".
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

from zeebe_tpu.engine import kernel_backend as kb
from zeebe_tpu.engine.device_health import (
    HEALTHY,
    QUARANTINED,
    SUSPECT,
    DeviceDefenseCfg,
    DeviceHealth,
    defense_cfg_from_env,
    reset_shared_device_health,
    shared_device_health,
)
from zeebe_tpu.models.bpmn import Bpmn
from zeebe_tpu.testing import EngineHarness
from zeebe_tpu.testing.chaos_device import (
    FAULT_CLASSES,
    DeviceChaosController,
    DeviceChaosError,
    DeviceFaultPlan,
    format_spec,
    maybe_install_from_env,
    parse_spec,
)


@pytest.fixture(autouse=True)
def _clean_device_plane():
    """Every test starts HEALTHY with no chaos installed, and cannot leak
    its posture into later tests (the ladder is process-wide)."""
    kb.install_device_chaos(None)
    reset_shared_device_health()
    yield
    kb.install_device_chaos(None)
    reset_shared_device_health()


def one_task(pid="one_task"):
    return (
        Bpmn.create_executable_process(pid)
        .start_event("start")
        .service_task("task", job_type="work")
        .end_event("end")
        .done()
    )


def log_fingerprint(harness):
    out = []
    for logged in harness.stream.new_reader(1):
        rec = logged.record
        out.append((
            logged.position, logged.source_position, logged.processed,
            rec.key, rec.record_type.name, rec.value_type.name,
            int(rec.intent),
            rec.rejection_type.name if rec.is_rejection else "",
            dict(rec.value) if rec.value else {},
        ))
    return out


def drive_scenario(h, instances=6):
    h.deploy(one_task())
    for i in range(instances):
        h.create_instance("one_task", request_id=10 + i)
    for job in h.activate_jobs("work", max_jobs=100):
        h.complete_job(job["key"])


def sequential_fingerprint():
    h = EngineHarness(use_kernel_backend=False)
    try:
        drive_scenario(h)
        return log_fingerprint(h)
    finally:
        h.close()


# ---------------------------------------------------------------------------
# spec + controller units


class TestChaosDeviceSpec:
    def test_round_trip(self):
        plan = DeviceFaultPlan(seed=7, compile_fail_p=0.01,
                               dispatch_fail_p=0.02, stall_p=0.03,
                               stall_ms=450, chunk_fail_p=0.04,
                               corrupt_p=0.05, flips=2)
        assert parse_spec(format_spec(plan)) == plan

    def test_defaults_round_trip(self):
        assert parse_spec(format_spec(DeviceFaultPlan())) == DeviceFaultPlan()

    def test_configured_classes(self):
        assert DeviceFaultPlan().configured_classes() == []
        plan = DeviceFaultPlan(compile_fail_p=0.1, corrupt_p=0.1)
        assert plan.configured_classes() == ["compile_fail", "corrupt"]
        assert set(DeviceFaultPlan(
            compile_fail_p=1, dispatch_fail_p=1, stall_p=1, chunk_fail_p=1,
            corrupt_p=1).configured_classes()) == set(FAULT_CLASSES)

    def test_seeded_member_streams(self):
        a1 = DeviceChaosController(DeviceFaultPlan(seed=3), "worker-0")
        a2 = DeviceChaosController(DeviceFaultPlan(seed=3), "worker-0")
        b = DeviceChaosController(DeviceFaultPlan(seed=3), "worker-1")
        s1 = [a1.rng.random() for _ in range(32)]
        s2 = [a2.rng.random() for _ in range(32)]
        s3 = [b.rng.random() for _ in range(32)]
        assert s1 == s2
        assert s1 != s3

    def test_env_install_and_disarm(self, tmp_path):
        plan = DeviceFaultPlan(seed=1, dispatch_fail_p=1.0)
        disarm = tmp_path / "disarm"
        env = {"ZEEBE_CHAOS_DEVICE": format_spec(plan),
               "ZEEBE_CHAOS_DEVICE_DISARMFILE": str(disarm)}
        controller = maybe_install_from_env("worker-0", str(tmp_path), env)
        assert controller is not None
        assert kb.device_chaos() is controller
        assert controller.counts_file and controller.ledger_file
        assert shared_device_health().evidence_file is not None
        with pytest.raises(DeviceChaosError):
            controller.dispatch_fault()
        disarm.write_text("x")
        controller.tick()
        assert not controller.armed
        controller.dispatch_fault()  # disarmed: no raise
        assert maybe_install_from_env("worker-0", None, {}) is None

    def test_corrupt_rows_ledger_and_caught(self, tmp_path):
        controller = DeviceChaosController(
            DeviceFaultPlan(seed=5, corrupt_p=1.0, flips=3), "worker-0")
        controller.ledger_file = str(tmp_path / "ledger.jsonl")
        rows = np.zeros((4, 10), np.int32)
        token = controller.corrupt_rows(rows, chunk_index=0)
        assert token == 1
        assert np.count_nonzero(rows) > 0  # bits actually flipped
        controller.note_caught(token, "shadow")
        lines = [json.loads(line) for line in Path(
            controller.ledger_file).read_text().splitlines()]
        assert [e["kind"] for e in lines] == ["inject", "caught"]
        assert lines[0]["seq"] == lines[1]["seq"] == 1
        assert lines[1]["how"] == "shadow"
        assert controller.counts["corrupt"] == 1
        assert controller.counts["corrupt_caught"] == 1


# ---------------------------------------------------------------------------
# the health ladder (fake clock — deterministic)


def ladder(cfg=None, start_ms=1_000_000.0):
    clock = {"now": start_ms / 1000.0}
    cfg = cfg or DeviceDefenseCfg(quarantine_faults=3, fault_window_ms=10_000,
                                  suspect_clear_ms=5_000,
                                  canary_interval_ms=1_000,
                                  canary_successes=2)
    health = DeviceHealth(cfg, clock=lambda: clock["now"])
    return health, clock


class TestDeviceHealthLadder:
    def test_first_fault_latches_suspect(self):
        health, _ = ladder()
        assert health.state == HEALTHY
        health.note_fault("device-dispatch-error")
        assert health.state == SUSPECT
        assert health.faults["device-dispatch-error"] == 1

    def test_faults_in_window_quarantine(self):
        health, clock = ladder()
        for _ in range(3):
            health.note_fault("device-wedged")
            clock["now"] += 0.1
        assert health.state == QUARANTINED
        targets = [t["to"] for t in health.transitions]
        assert targets == [SUSPECT, QUARANTINED]

    def test_spread_out_faults_stay_suspect(self):
        health, clock = ladder()
        for _ in range(4):
            health.note_fault("device-wedged")
            clock["now"] += 11.0  # past the 10s window each time
        assert health.state == SUSPECT

    def test_quiet_window_clears_suspect(self):
        health, clock = ladder()
        health.note_fault("shadow-mismatch")
        health.note_group_ok()
        assert health.state == SUSPECT  # too soon
        clock["now"] += 6.0
        health.note_group_ok()
        assert health.state == HEALTHY

    def test_canary_cycle_recovers_quarantine(self):
        health, clock = ladder()
        for _ in range(3):
            health.note_fault("device-dispatch-error")
        assert health.state == QUARANTINED
        assert health.canary_due()
        assert not health.canary_due()  # interval not elapsed
        health.note_canary(False)       # failed canary resets the streak
        clock["now"] += 1.1
        assert health.canary_due()
        health.note_canary(True)
        assert health.state == QUARANTINED  # needs 2 consecutive
        clock["now"] += 1.1
        assert health.canary_due()
        health.note_canary(True)
        assert health.state == HEALTHY
        targets = [t["to"] for t in health.transitions]
        assert targets == [SUSPECT, QUARANTINED, HEALTHY]
        assert "canary" in health.transitions[-1]["reason"]

    def test_transitions_reach_flight_sink_and_evidence(self, tmp_path):
        health, _ = ladder()
        events = []

        class Flight:
            # mirrors FlightRecorder.record(partition_id, kind, **detail)
            def record(self, partition_id, kind, **fields):
                events.append((partition_id, kind, fields))

        health.flight_sink = (Flight(), 1)
        health.evidence_file = str(tmp_path / "health.jsonl")
        health.note_fault("device-wedged", detail="probe")
        kinds = [k for _pid, k, _f in events]
        assert "device_fault" in kinds
        fault = next(f for _p, k, f in events if k == "device_fault")
        assert fault["faultKind"] == "device-wedged"
        assert "control_adjust" in kinds
        assert "device_health" in kinds
        adjust = next(f for _p, k, f in events if k == "control_adjust")
        assert adjust["controller"] == "device-health"
        assert adjust["before"] == HEALTHY and adjust["after"] == SUSPECT
        lines = [json.loads(line) for line in Path(
            health.evidence_file).read_text().splitlines()]
        assert lines[0]["to"] == SUSPECT

    def test_cfg_binds_from_env(self):
        cfg = defense_cfg_from_env({
            "ZEEBE_BROKER_DEVICE_DISPATCHTIMEOUTMS": "1500",
            "ZEEBE_BROKER_DEVICE_SHADOWSAMPLERATE": "0.5",
            "ZEEBE_BROKER_DEVICE_QUARANTINEFAULTS": "9",
            "ZEEBE_BROKER_DEVICE_CANARYINTERVALMS": "250",
        })
        assert cfg.dispatch_timeout_ms == 1500
        assert cfg.shadow_sample_rate == 0.5
        assert cfg.quarantine_faults == 9
        assert cfg.canary_interval_ms == 250
        # malformed values fall back to defaults, never raise
        cfg = defense_cfg_from_env(
            {"ZEEBE_BROKER_DEVICE_SHADOWSAMPLERATE": "lots"})
        assert cfg.shadow_sample_rate == DeviceDefenseCfg().shadow_sample_rate

    def test_status_block(self):
        health, _ = ladder()
        health.note_shadow_check()
        health.note_shadow_mismatch()
        status = health.status()
        assert status["state"] == SUSPECT
        assert status["shadowChecks"] == 1
        assert status["shadowMismatches"] == 1
        assert status["lastTransition"]["to"] == SUSPECT


# ---------------------------------------------------------------------------
# containment at the dispatch seam (end to end, byte parity)


class TestContainment:
    def test_dispatch_exception_contained_byte_identical(self):
        """Every dispatch raises → every group host re-executes in the same
        pump pass; the log is byte-identical to the sequential engine and
        the pump never sees the exception."""
        shared_device_health()  # construct before the backend binds cfg
        kb.install_device_chaos(DeviceChaosController(
            DeviceFaultPlan(seed=1, dispatch_fail_p=1.0), "t"))
        h = EngineHarness(use_kernel_backend=True)
        try:
            drive_scenario(h)
            fingerprint = log_fingerprint(h)
            acct = h.kernel_backend.accounting
            assert acct.reasons["device-dispatch-error"] > 0
            assert not acct.unregistered
            assert acct.kernel_records == 0  # nothing rode the device
            assert h.kernel_backend.health.state in (SUSPECT, QUARANTINED)
        finally:
            h.close()
        assert fingerprint == sequential_fingerprint()

    def test_watchdog_converts_stall_to_typed_wedge(self):
        """A chaos stall longer than the dispatch deadline is contained as
        `device-wedged` — the pump waits only the deadline, not the stall."""
        health = shared_device_health()
        health.cfg.dispatch_timeout_ms = 120
        kb.install_device_chaos(DeviceChaosController(
            DeviceFaultPlan(seed=1, stall_p=1.0, stall_ms=600), "t"))
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(one_task())
            h.create_instance("one_task", request_id=10)
            acct = h.kernel_backend.accounting
            assert acct.reasons["device-wedged"] > 0
            assert not acct.unregistered
            fingerprint = log_fingerprint(h)
        finally:
            h.close()
        seq = EngineHarness(use_kernel_backend=False)
        try:
            seq.deploy(one_task())
            seq.create_instance("one_task", request_id=10)
            assert fingerprint == log_fingerprint(seq)
        finally:
            seq.close()

    def test_finish_group_exception_cannot_poison_pump(self):
        """Satellite pin (PR 13 note_group_success seam): a backend that
        raises mid-finish_group falls back to sequential host execution
        with byte parity, exactly-once accounting (the rolled-back group
        is never counted kernel), and a surviving pump."""
        shared_device_health()
        h = EngineHarness(use_kernel_backend=True)
        try:
            backend = h.kernel_backend
            real_finish = backend.finish_group
            boom = {"left": 2}

            def flaky_finish(pg, make_builder):
                if boom["left"] > 0:
                    boom["left"] -= 1
                    raise RuntimeError("fake backend exploded mid-group")
                return real_finish(pg, make_builder)

            backend.finish_group = flaky_finish
            drive_scenario(h)
            fingerprint = log_fingerprint(h)
            acct = backend.accounting
            # both explosions fell back, typed; each head re-executed on
            # the host exactly once
            assert acct.reasons["group-error"] == 2
            # exactly-once: kernel+host notes cover the routed heads with
            # no double count from the rolled-back groups
            assert backend.groups_processed == 0 or acct.kernel_records >= 0
            from zeebe_tpu.stream.processor import Phase

            assert h.processor.phase == Phase.PROCESSING  # pump survived
        finally:
            h.close()
        assert fingerprint == sequential_fingerprint()

    def test_watchdog_thread_count_flat_across_50_wedges(self):
        """Satellite pin (ISSUE 20): the dispatch watchdog reuses a
        bounded worker pool, so 50 seeded wedges leave the process thread
        count flat once each wedge resolves. The old per-call daemon
        thread leaked one thread per expired dispatch — exactly the trend
        the fleet auditor's thread_count detector would flag."""
        import random
        import threading
        import time

        rng = random.Random(20)
        baseline = threading.active_count()
        cap = kb._WatchdogPool.MAX_IDLE
        for _ in range(50):
            gate = threading.Event()
            with pytest.raises(kb.DeviceWedgedError):
                kb._watchdog_call(gate.wait, 0.002 + rng.random() * 0.004)
            gate.set()  # un-wedge: the pooled worker must re-idle itself
            deadline = time.time() + 5.0
            while time.time() < deadline:
                with kb._WATCHDOG_POOL._lock:
                    if kb._WATCHDOG_POOL._idle:
                        break
                time.sleep(0.001)
            assert threading.active_count() <= baseline + cap
        # serial wedges reuse pooled workers: no 50-thread residue
        assert threading.active_count() <= baseline + cap
        # and the pool still serves the happy path after all that abuse
        assert kb._watchdog_call(lambda: 42, 1.0) == 42


# ---------------------------------------------------------------------------
# shadow verification (detection) + quarantine routing + canary recovery


class TestShadowVerification:
    def test_corruption_caught_before_commit(self, tmp_path):
        """Every group corrupt + every group shadow-verified → every ledger
        injection has a caught line, the host oracle's result commits, and
        the log stays byte-identical to the sequential engine."""
        health = shared_device_health()
        health.cfg.shadow_sample_rate = 1.0
        controller = DeviceChaosController(
            DeviceFaultPlan(seed=2, corrupt_p=1.0, flips=4), "t")
        controller.ledger_file = str(tmp_path / "ledger.jsonl")
        kb.install_device_chaos(controller)
        h = EngineHarness(use_kernel_backend=True)
        try:
            drive_scenario(h)
            fingerprint = log_fingerprint(h)
            backend = h.kernel_backend
            assert backend.health.shadow_checks > 0
            assert backend.health.shadow_mismatches > 0
            assert backend.shadow_quarantined > 0
        finally:
            h.close()
        assert fingerprint == sequential_fingerprint()
        lines = [json.loads(line) for line in Path(
            controller.ledger_file).read_text().splitlines()]
        injected = {e["seq"] for e in lines if e["kind"] == "inject"}
        caught = {e["seq"] for e in lines if e["kind"] == "caught"}
        assert injected
        assert injected == caught  # nothing corrupt ever reached the log

    def test_clean_groups_verify_without_mismatch(self):
        health = shared_device_health()
        health.cfg.shadow_sample_rate = 1.0
        h = EngineHarness(use_kernel_backend=True)
        try:
            drive_scenario(h)
            backend = h.kernel_backend
            assert backend.health.shadow_checks > 0
            assert backend.health.shadow_mismatches == 0
            assert backend.health.state == HEALTHY
            assert backend.accounting.kernel_records > 0
        finally:
            h.close()

    def test_sampling_rate_zero_never_shadows(self):
        health = shared_device_health()
        health.cfg.shadow_sample_rate = 0.0
        h = EngineHarness(use_kernel_backend=True)
        try:
            drive_scenario(h)
            assert h.kernel_backend.health.shadow_checks == 0
        finally:
            h.close()

    def test_sampled_stream_is_deterministic(self):
        health = shared_device_health()
        health.cfg.shadow_sample_rate = 0.4
        h1 = EngineHarness(use_kernel_backend=True)
        try:
            decisions1 = [h1.kernel_backend._shadow_sampled()
                          for _ in range(64)]
        finally:
            h1.close()
        reset_shared_device_health()
        health = shared_device_health()
        health.cfg.shadow_sample_rate = 0.4
        h2 = EngineHarness(use_kernel_backend=True)
        try:
            decisions2 = [h2.kernel_backend._shadow_sampled()
                          for _ in range(64)]
        finally:
            h2.close()
        assert decisions1 == decisions2
        assert any(decisions1) and not all(decisions1)


class TestQuarantineLadderEndToEnd:
    def test_full_cycle_quarantine_reroute_canary_recovery(self):
        """The acceptance cycle on a live engine: faults escalate to
        QUARANTINED (groups host-route with typed accounting), the chaos
        plane goes quiet, canaries re-prove the device, kernel routing
        resumes — and the whole ride is byte-identical to sequential."""
        health = shared_device_health()
        health.cfg.quarantine_faults = 2
        health.cfg.fault_window_ms = 600_000
        # phase A: no canary slots — every quarantined pass must REROUTE
        health.cfg.canary_interval_ms = 600_000
        health.cfg.canary_successes = 2
        controller = DeviceChaosController(
            DeviceFaultPlan(seed=4, dispatch_fail_p=1.0), "t")
        kb.install_device_chaos(controller)
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(one_task())
            # phase A: every dispatch fails → SUSPECT then QUARANTINED
            for i in range(4):
                h.create_instance("one_task", request_id=10 + i)
            backend = h.kernel_backend
            assert backend.health.state == QUARANTINED
            assert backend.accounting.reasons["device-quarantined"] > 0
            assert backend.health.host_reroutes > 0
            # phase B: device honest again → canaries (forced shadow)
            # re-prove it within two groups
            controller.armed = False
            health.cfg.canary_interval_ms = 0  # every pass may canary now
            for i in range(4):
                h.create_instance("one_task", request_id=20 + i)
            assert backend.health.state == HEALTHY
            targets = [t["to"] for t in backend.health.transitions]
            assert targets == [SUSPECT, QUARANTINED, HEALTHY]
            # phase C: kernel routing is live again
            before = backend.accounting.kernel_records
            for i in range(2):
                h.create_instance("one_task", request_id=30 + i)
            assert backend.accounting.kernel_records > before
            for job in h.activate_jobs("work", max_jobs=100):
                h.complete_job(job["key"])
            fingerprint = log_fingerprint(h)
        finally:
            h.close()
        seq = EngineHarness(use_kernel_backend=False)
        try:
            seq.deploy(one_task())
            for i in range(4):
                seq.create_instance("one_task", request_id=10 + i)
            for i in range(4):
                seq.create_instance("one_task", request_id=20 + i)
            for i in range(2):
                seq.create_instance("one_task", request_id=30 + i)
            for job in seq.activate_jobs("work", max_jobs=100):
                seq.complete_job(job["key"])
            assert fingerprint == log_fingerprint(seq)
        finally:
            seq.close()


    def test_failed_canary_counted_exactly_once(self):
        """A canary whose shadow oracle raises is one failed canary, not
        two: _verify_steps abandons the group and finish_group's decline
        branch is the single seam that notes the outcome."""
        health = shared_device_health()
        health.cfg.quarantine_faults = 2
        health.cfg.fault_window_ms = 600_000
        health.cfg.canary_interval_ms = 600_000
        controller = DeviceChaosController(
            DeviceFaultPlan(seed=4, dispatch_fail_p=1.0), "t")
        kb.install_device_chaos(controller)
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(one_task())
            for i in range(4):
                h.create_instance("one_task", request_id=10 + i)
            backend = h.kernel_backend
            assert backend.health.state == QUARANTINED
            controller.armed = False
            health.cfg.canary_interval_ms = 0

            def broken_oracle(pg):
                raise RuntimeError("oracle lost the device")

            backend._shadow_execute = broken_oracle
            before = backend.health.canary_attempts
            h.create_instance("one_task", request_id=20)
            assert backend.health.canary_attempts == before + 1
            assert backend.health.canary_verified == 0
            assert backend.health.state == QUARANTINED  # streak reset
        finally:
            h.close()

    def test_canary_pins_accelerator_past_quarantine_host_bias(self):
        """On a router-enabled broker the quarantine posture routes every
        ordinary group host-ward (route_threshold_s=+inf), but the canary
        pins the SUSPECT accelerator — a canary the router re-routed to
        the host would byte-match the host oracle by construction."""
        from zeebe_tpu.utils.device_link import BackendRouter

        router = BackendRouter()
        router._measured = True
        router.enabled = True
        accel, host = object(), object()
        router._accel, router._host = accel, host
        router.link_put_s = router.link_get_s = 1e-4
        router.route_threshold_s = float("inf")  # the quarantine posture
        bucket = ("fp", 4, 8)
        router._host_ema[bucket] = 0.5
        assert router.choose(bucket) is host  # ordinary traffic host-routes
        assert router.accel_device() is accel  # the canary's pin

    def test_accel_device_none_when_routing_disabled(self):
        from zeebe_tpu.utils.device_link import BackendRouter

        router = BackendRouter()
        router._measured = True  # host-default process: routing disabled
        assert router.accel_device() is None


class TestCorruptionAccountingWaiver:
    def test_surviving_life_gets_no_waiver(self):
        """An uncaught inject in the tail of a life that SURVIVED to
        teardown is a violation — the process had every chance to report
        the catch; only verifiably dead lives may waive their final
        moments (SIGKILL mid-group)."""
        from zeebe_tpu.testing.device_chaos import check_corruption_accounting

        entries = [{"kind": "inject", "seq": 1, "member": "w0", "pid": 11,
                    "atMs": 1000.0}]
        violations, stats = check_corruption_accounting(
            entries, dead_pids=set())
        assert len(violations) == 1 and "never caught" in violations[0]
        assert stats["waivedByDeath"] == 0
        violations, stats = check_corruption_accounting(
            entries, dead_pids={11})
        assert violations == []
        assert stats["waivedByDeath"] == 1

    def test_waiver_is_tail_only_even_for_dead_lives(self):
        from zeebe_tpu.testing.device_chaos import check_corruption_accounting

        entries = [
            {"kind": "inject", "seq": 1, "member": "w0", "pid": 11,
             "atMs": 1000.0},
            {"kind": "inject", "seq": 2, "member": "w0", "pid": 11,
             "atMs": 9000.0},
            {"kind": "caught", "seq": 2, "member": "w0", "pid": 11,
             "how": "shadow", "atMs": 9001.0},
        ]
        # seq 1 sits mid-life: even a dead life cannot waive it
        violations, stats = check_corruption_accounting(
            entries, dead_pids={11})
        assert len(violations) == 1 and "seq 1" in violations[0]
        assert stats == {"injected": 2, "caughtShadow": 1,
                         "caughtContained": 0, "waivedByDeath": 0}


# ---------------------------------------------------------------------------
# observability surfaces


class TestDeviceObservability:
    def test_kernel_wave_event_carries_device_health(self):
        shared_device_health()
        h = EngineHarness(use_kernel_backend=True)
        try:
            events = []
            h.processor.wave_listener = events.append
            drive_scenario(h, instances=3)
            assert events, "no kernel_wave event emitted"
            event = events[0]
            assert event["deviceHealth"] == HEALTHY
            assert "shadowChecks" in event and "shadowMismatches" in event
        finally:
            h.close()

    def test_device_status_block(self):
        shared_device_health()
        h = EngineHarness(use_kernel_backend=True)
        try:
            drive_scenario(h, instances=2)
            status = h.kernel_backend.device_status()
            assert status["state"] == HEALTHY
            assert set(status) >= {"faults", "shadowChecks",
                                   "shadowMismatches", "hostReroutes",
                                   "canaries", "shadowQuarantinedGroups"}
        finally:
            h.close()

    def test_routing_controller_biases_on_device_state(self):
        from zeebe_tpu.control.controllers import RoutingController

        controller = RoutingController(actuators=[])
        knob = RoutingController.KNOB
        value, reason = controller.decide(
            {"compileMissPerSec": 0.0, "deviceHealthState": 1.0},
            {knob: 0.0})[knob]
        assert value == float("inf") and "SUSPECT" in reason
        value, reason = controller.decide(
            {"compileMissPerSec": 0.0, "deviceHealthState": 2.0},
            {knob: 0.0})[knob]
        assert value == float("inf") and "QUARANTINED" in reason
        value, _reason = controller.decide(
            {"compileMissPerSec": 0.0, "deviceHealthState": 0.0},
            {knob: 0.0})[knob]
        assert value == 0.0

    def test_routing_signals_stale_without_compile_telemetry(self):
        """The always-registered (and always-fresh) health gauge must not
        masquerade as a live compile signal: no compile telemetry + a
        HEALTHY ladder reads STALE (the actuator walks the knob back to
        its static posture), while a SUSPECT ladder still actuates."""
        from zeebe_tpu.control import RoutingController, SignalReader
        from zeebe_tpu.observability.timeseries import TimeSeriesStore
        from zeebe_tpu.testing import ControlledClock

        clock = ControlledClock()
        controller = RoutingController(actuators=[])

        def reader(*series):
            store = TimeSeriesStore()
            for name, labels, value in series:
                store.append(name, labels, "gauge", clock.millis, value)
            return SignalReader(store, clock)

        # healthy ladder, no compile series at all → stale, not a
        # fabricated compileMissPerSec=0.0 actuation
        assert controller.read_signals(
            reader(("zeebe_device_health_state", "", 0.0))) is None
        # SUSPECT ladder alone is a live signal (host-ward bias)
        sig = controller.read_signals(
            reader(("zeebe_device_health_state", "", 1.0)))
        assert sig is not None and sig["deviceHealthState"] == 1.0
        assert controller.decide(
            sig, {controller.KNOB: 0.0})[controller.KNOB][0] == float("inf")

    def test_host_side_canary_decline_is_not_a_failed_canary(self):
        """A canary group declined HOST-side (geometry-bounds: the probe
        never reached the device) must not reset the recovery streak or
        burn the interval slot — only device-probing failures
        (device-dispatch-error / device-wedged) count as failed canaries."""
        from types import SimpleNamespace

        health = shared_device_health()
        health.cfg.quarantine_faults = 2
        health.cfg.fault_window_ms = 600_000
        health.cfg.canary_interval_ms = 3_600_000
        health.cfg.canary_successes = 3
        health.note_fault("device-dispatch-error")
        health.note_fault("device-dispatch-error")
        assert health.state == QUARANTINED
        assert health.canary_due()       # claim the hour slot
        health.note_canary(True)         # verified streak: 1 of 3
        h = EngineHarness(use_kernel_backend=True)
        try:
            backend = h.kernel_backend
            pg = kb._PendingGroup([SimpleNamespace(
                cmd=None,
                inst=SimpleNamespace(info=SimpleNamespace(
                    exe=SimpleNamespace(process_id="one_task"))))])
            pg.canary = True
            pg.failed = True
            pg.fail_reason = "geometry-bounds"
            attempts = health.canary_attempts
            streak = health._canary_streak
            assert backend.finish_group(pg, lambda: None) == ([], [])
            assert health.canary_attempts == attempts  # not counted failed
            assert health._canary_streak == streak     # streak survives
            assert health.canary_due()                 # slot released
        finally:
            h.close()

    def test_declined_canary_releases_its_slot(self):
        """A canary slot claimed by a group that never dispatched (the
        head was not kernel-admittable) is un-claimed — the next
        admittable pass probes immediately instead of waiting out an
        interval the device never saw."""
        health = shared_device_health()
        health.cfg.quarantine_faults = 2
        health.cfg.fault_window_ms = 600_000
        # one canary per hour: burning the slot would stall recovery
        health.cfg.canary_interval_ms = 3_600_000
        health.cfg.canary_successes = 1
        controller = DeviceChaosController(
            DeviceFaultPlan(seed=6, dispatch_fail_p=1.0), "t")
        kb.install_device_chaos(controller)
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(one_task())
            for i in range(2):
                h.create_instance("one_task", request_id=10 + i)
            backend = h.kernel_backend
            assert backend.health.state == QUARANTINED
            controller.armed = False  # device honest again
            # a non-admittable head claims (then must release) the slot
            h.deploy(one_task("other_def"))
            # the very next admittable group must canary and recover
            h.create_instance("one_task", request_id=11)
            assert backend.health.state == HEALTHY
            assert backend.health.canary_verified == 1
        finally:
            h.close()


# ---------------------------------------------------------------------------
# chaos_common (the extracted shared machinery)


class TestChaosCommon:
    def test_member_rng_matches_legacy_derivation(self):
        import random
        import zlib

        from zeebe_tpu.testing.chaos_common import member_rng

        legacy = random.Random(9 ^ zlib.crc32(b"worker-2"))
        shared = member_rng(9, "worker-2")
        assert [legacy.random() for _ in range(16)] == \
               [shared.random() for _ in range(16)]

    def test_sum_counts_files_and_ledger_reader(self, tmp_path):
        from zeebe_tpu.testing.chaos_common import (
            read_jsonl_ledgers,
            sum_counts_files,
        )

        (tmp_path / "a.json").write_text(
            json.dumps({"member": "w0", "eio": 2, "torn": 1}))
        (tmp_path / "b.json").write_text(
            json.dumps({"member": "w1", "eio": 3}))
        (tmp_path / "broken.json").write_text("{torn")
        totals = sum_counts_files(sorted(tmp_path.glob("*.json")))
        assert totals == {"eio": 5, "torn": 1}
        ledger = tmp_path / "l.jsonl"
        ledger.write_text('{"kind":"inject","seq":1}\n{"kind":"ca')
        rows = read_jsonl_ledgers([ledger])
        assert rows == [{"kind": "inject", "seq": 1}]  # torn tail skipped

    def test_counts_snapshot_throttles_and_is_atomic(self, tmp_path):
        from zeebe_tpu.testing.chaos_common import CountsSnapshot

        snap = CountsSnapshot("w0")
        snap.counts_file = str(tmp_path / "counts.json")
        snap.maybe_dump({"eio": 1})
        first = json.loads(Path(snap.counts_file).read_text())
        assert first == {"member": "w0", "eio": 1}
        snap.maybe_dump({"eio": 2})  # throttled: unchanged on disk
        assert json.loads(Path(snap.counts_file).read_text()) == first
        snap._last_dump = 0.0
        snap.maybe_dump({"eio": 2})
        assert json.loads(Path(snap.counts_file).read_text())["eio"] == 2
