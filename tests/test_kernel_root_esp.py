"""Definitions with ROOT-level event sub-processes ride the kernel
(VERDICT r4 weak 7: every eligibility escape caps device residency).

The ESP bodies stay host-side, but creation/runs/completion of the main
flow execute on device: the creation materializer opens the start
subscriptions via the sequential behavior verbatim, reconstruction counts
them as root wait state, and process completion closes them. Byte parity
against the sequential engine is the oracle, as everywhere.
"""

from __future__ import annotations

from zeebe_tpu.models.bpmn import Bpmn, transform
from zeebe_tpu.protocol.intent import ProcessInstanceIntent as PI
from zeebe_tpu.testing import EngineHarness

from tests.test_kernel_backend import assert_equivalent, drive_jobs


def esp_message_def(pid="esp_msg"):
    return (
        Bpmn.create_executable_process(pid)
        .start_event("s")
        .service_task("work", job_type="w")
        .end_event("e")
        .event_sub_process("esp")
        .message_start_event("ms", "alarm", correlation_key="=key")
        .service_task("handle", job_type="h")
        .end_event("esp_e")
        .sub_process_done()
        .done()
    )


def esp_timer_def(pid="esp_timer"):
    return (
        Bpmn.create_executable_process(pid)
        .start_event("s")
        .service_task("work", job_type="w")
        .end_event("e")
        .event_sub_process("esp")
        .timer_start_event("ts", duration="PT2H")
        .end_event("esp_e")
        .sub_process_done()
        .done()
    )


def esp_error_def(pid="esp_err"):
    return (
        Bpmn.create_executable_process(pid)
        .start_event("s")
        .service_task("work", job_type="w")
        .end_event("e")
        .event_sub_process("esp")
        .error_start_event("es", error_code="OOPS")
        .end_event("esp_e")
        .sub_process_done()
        .done()
    )


class TestRootEspEligibility:
    def test_definitions_are_kernel_eligible(self):
        from zeebe_tpu.engine.kernel_backend import KernelRegistry

        for mk in (esp_message_def, esp_timer_def, esp_error_def):
            exe = transform(mk())
            reg = KernelRegistry()
            info = reg._build_info(1, exe, None, 0)
            assert info is not None, mk.__name__
        # cycle-timer ESP starts stay sequential end to end
        cyc = (
            Bpmn.create_executable_process("esp_cyc")
            .start_event("s").service_task("t", job_type="w").end_event("e")
            .event_sub_process("esp")
            .timer_start_event("ts", cycle="R/PT1H")
            .end_event("ee").sub_process_done().done()
        )
        assert KernelRegistry()._build_info(1, transform(cyc), None, 0) is None

    def test_kernel_path_actually_rides(self):
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(esp_message_def())
            for i in range(8):
                h.create_instance("esp_msg", {"key": f"k-{i}"},
                                  request_id=10 + i)
            for job in h.activate_jobs("w", max_jobs=20):
                h.complete_job(job["key"])
            k = getattr(h, "kernel", None) or getattr(h, "kernel_backend", None)
            assert k.commands_processed >= 16, (
                k.commands_processed, dict(k.fallback_reasons))
        finally:
            h.close()


class TestRootEspParity:
    def test_message_esp_untriggered_byte_parity(self):
        def scenario(h):
            h.deploy(esp_message_def())
            for i in range(6):
                h.create_instance("esp_msg", {"key": f"k-{i}"},
                                  request_id=20 + i)
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_timer_esp_untriggered_byte_parity(self):
        def scenario(h):
            h.deploy(esp_timer_def())
            for i in range(6):
                h.create_instance("esp_timer", {"n": i}, request_id=40 + i)
            drive_jobs(h, "w")

        assert_equivalent(scenario, clock_start=1_700_000_000_000)

    def test_error_esp_triggered_byte_parity(self):
        def scenario(h):
            h.deploy(esp_error_def())
            h.create_instance("esp_err", request_id=60)
            h.create_instance("esp_err", request_id=61)
            jobs = h.activate_jobs("w", max_jobs=5)
            # one instance throws into the ESP, the other completes
            h.write_command(_throw(jobs[0]["key"], "OOPS"), request_id=62)
            h.complete_job(jobs[1]["key"])

        assert_equivalent(scenario)

    def test_message_esp_triggered_byte_parity(self):
        def scenario(h):
            h.deploy(esp_message_def())
            h.create_instance("esp_msg", {"key": "hot"}, request_id=70)
            h.create_instance("esp_msg", {"key": "cold"}, request_id=71)
            # trigger the ESP on ONE instance; its interrupting start kills
            # the main-flow task, the other instance completes normally
            h.publish_message("alarm", "hot", variables={"why": "x"})
            drive_jobs(h, "h")
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_esp_instance_completes_and_closes_subscriptions(self):
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(esp_message_def())
            pi = h.create_instance("esp_msg", {"key": "z"}, request_id=90)
            for job in h.activate_jobs("w"):
                h.complete_job(job["key"])
            assert (
                h.exporter.process_instance_records()
                .with_element_id("esp_msg")
                .with_intent(PI.ELEMENT_COMPLETED)
                .exists()
            )
            # subscription closed with the instance
            with h.db.transaction():
                subs = h.engine.state.process_message_subscriptions.subscriptions_of(pi)
            assert subs == []
        finally:
            h.close()


def _throw(job_key: int, code: str):
    from zeebe_tpu.protocol import ValueType, command
    from zeebe_tpu.protocol.intent import JobIntent

    return command(ValueType.JOB, JobIntent.THROW_ERROR,
                   {"errorCode": code, "errorMessage": ""}, key=job_key)


def esp_signal_def(pid="esp_sig"):
    return (
        Bpmn.create_executable_process(pid)
        .start_event("s")
        .service_task("work", job_type="w")
        .end_event("e")
        .event_sub_process("esp")
        .signal_start_event("ss", "red_alert")
        .end_event("esp_e")
        .sub_process_done()
        .done()
    )


class TestRootEspSignalAndTimerTrigger:
    def test_signal_esp_definition_eligible_and_rides(self):
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(esp_signal_def())
            for i in range(6):
                h.create_instance("esp_sig", {"n": i}, request_id=10 + i)
            k = getattr(h, "kernel", None) or getattr(h, "kernel_backend", None)
            assert k.commands_processed >= 6, dict(k.fallback_reasons)
            # reconstruction counts the signal subscription as root wait
            # state: the job resume still rides the kernel
            before = k.commands_processed
            for job in h.activate_jobs("w", max_jobs=10):
                h.complete_job(job["key"])
            assert k.commands_processed > before, dict(k.fallback_reasons)
        finally:
            h.close()

    def test_signal_esp_untriggered_byte_parity(self):
        def scenario(h):
            h.deploy(esp_signal_def())
            for i in range(5):
                h.create_instance("esp_sig", {"n": i}, request_id=30 + i)
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_signal_esp_triggered_byte_parity(self):
        def scenario(h):
            h.deploy(esp_signal_def())
            h.create_instance("esp_sig", request_id=50)
            h.broadcast_signal("red_alert")
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_timer_esp_triggered_byte_parity(self):
        def scenario(h):
            h.deploy(esp_timer_def())
            h.create_instance("esp_timer", request_id=70)  # ESP fires at 2h
            h.create_instance("esp_timer", request_id=71)
            jobs = h.activate_jobs("w", max_jobs=5)
            h.complete_job(jobs[0]["key"])  # one completes before the timer
            h.advance_time(2 * 3600 * 1000 + 1)  # the other's ESP interrupts
            drive_jobs(h, "w")

        assert_equivalent(scenario, clock_start=1_700_000_000_000)
