"""Continuous profiling plane (ISSUE 5): always-on folded-stack profiler,
XLA compile telemetry, device captures, and alert-triggered capture.

Covers: continuous-profiler window retention/eviction, folded-stack format
round-trips through a speedscope-style collapsed-stack parser, compile-seam
counters firing on a forced recompile, the device-capture endpoint's
single-flight guard (second POST → 409), and the alert-triggered profile
landing in the flight dump.
"""

from __future__ import annotations

import json
import threading
import time
import types
import urllib.error
import urllib.request

import pytest

from zeebe_tpu.observability.profiler import (
    AlertProfileCapture,
    CaptureInFlight,
    ContinuousProfiler,
    DeviceTraceCapture,
    fold_stacks,
    folded_text,
    observe_compile,
    sample_device_memory,
    sample_threads,
)


class FakeClock:
    def __init__(self, start: int = 1_000_000) -> None:
        self.now = start

    def __call__(self) -> int:
        return self.now

    def advance(self, ms: int) -> None:
        self.now += ms


def parse_folded(text: str) -> dict[tuple[str, ...], int]:
    """A speedscope-style collapsed-stack parser: each line is
    ``frame;frame;...;frame <count>`` — the round-trip oracle for the folded
    output format."""
    out: dict[tuple[str, ...], int] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        stack, _, weight = line.rpartition(" ")
        assert stack, f"no stack part in {line!r}"
        frames = tuple(stack.split(";"))
        assert all(frames), f"empty frame in {line!r}"
        out[frames] = out.get(frames, 0) + int(weight)
    return out


# ---------------------------------------------------------------------------
# stack sampling & folding


class TestStackSampling:
    def test_sample_threads_sees_current_thread_frames(self):
        [(name, frames)] = [
            (n, f) for n, f in sample_threads()
            if n == threading.current_thread().name
        ]
        assert any("test_profiler.py:" in frame for frame in frames)
        # root-first: this function sits nearer the leaf end than the root,
        # and the true leaf is the sampler itself
        assert frames[-1] == "profiler.py:sample_threads"
        assert any("test_sample_threads_sees_current_thread_frames" in f
                   for f in frames[-3:])

    def test_exclude_idents(self):
        own = threading.get_ident()
        names = [n for n, _ in sample_threads(exclude_idents=(own,))]
        assert threading.current_thread().name not in names

    def test_fold_and_round_trip_through_parser(self):
        stacks = [("worker", ["a.py:f", "b.py:g"]),
                  ("worker", ["a.py:f", "b.py:g"]),
                  ("pump", ["c.py:h"]),
                  ("idle", [])]
        folded = fold_stacks(stacks)
        assert folded == {"worker;a.py:f;b.py:g": 2, "pump;c.py:h": 1,
                          "idle": 1}
        parsed = parse_folded(folded_text(folded))
        assert parsed == {("worker", "a.py:f", "b.py:g"): 2,
                          ("pump", "c.py:h"): 1, ("idle",): 1}
        assert sum(parsed.values()) == len(stacks)

    def test_folded_text_orders_heaviest_first(self):
        text = folded_text({"a;b": 1, "c;d": 9, "e": 3})
        assert [line.rsplit(" ", 1)[0] for line in text.splitlines()] == \
            ["c;d", "e", "a;b"]


# ---------------------------------------------------------------------------
# continuous profiler


class TestContinuousProfiler:
    def make(self, clock: FakeClock, **kw) -> ContinuousProfiler:
        kw.setdefault("window_ms", 1000)
        kw.setdefault("max_windows", 3)
        return ContinuousProfiler(clock_millis=clock, **kw)

    def test_windows_bucket_by_clock(self):
        clock = FakeClock(10_000)
        prof = self.make(clock)
        prof.sample_now()
        clock.advance(100)
        prof.sample_now()
        clock.advance(1000)  # next bucket
        prof.sample_now()
        windows = prof.windows()
        assert [w["startMs"] for w in windows] == [10_000, 11_000]
        assert windows[0]["samples"] == 2 and windows[1]["samples"] == 1
        assert prof.samples_taken == 3
        # every window holds non-empty folded stacks of live threads
        assert all(w["stacks"] for w in windows)

    def test_whole_window_eviction_beyond_max_windows(self):
        clock = FakeClock(0)
        prof = self.make(clock, max_windows=3)
        for _ in range(5):
            prof.sample_now()
            clock.advance(1000)
        windows = prof.windows()
        assert len(windows) == 3
        # the OLDEST windows fell off whole; the newest survive
        assert [w["startMs"] for w in windows] == [2000, 3000, 4000]

    def test_since_filter_and_aggregate(self):
        clock = FakeClock(0)
        prof = self.make(clock)
        prof.sample_now()
        clock.advance(1000)
        prof.sample_now()
        assert len(prof.windows(since_ms=1000)) == 1
        total = sum(prof.aggregate().values())
        late = sum(prof.aggregate(since_ms=1000).values())
        assert 0 < late < total

    def test_folded_output_parses(self):
        clock = FakeClock(0)
        prof = self.make(clock)
        for _ in range(3):
            prof.sample_now()
        parsed = parse_folded(prof.folded())
        assert parsed
        # this test function is on the sampled main thread's stack
        assert any(
            any("test_profiler.py:" in frame for frame in frames)
            for frames in parsed
        )

    def test_hot_frames_and_top_stacks(self):
        clock = FakeClock(0)
        prof = self.make(clock)
        for _ in range(4):
            prof.sample_now()
        hot = prof.hot_frames(top=5)
        assert hot and hot[0]["samples"] >= 1
        assert all(set(h) == {"frame", "samples", "pct"} for h in hot)
        top = prof.top_stacks(top=2)
        assert len(top) <= 2 and top[0]["samples"] >= top[-1]["samples"]

    def test_thread_loop_samples_and_reports_achieved_rate(self):
        prof = ContinuousProfiler(hz=100.0, window_ms=60_000)
        prof.start()
        try:
            deadline = time.monotonic() + 3.0
            while prof.samples_taken < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            prof.stop()
        assert prof.samples_taken >= 5
        assert prof.achieved_hz > 0
        assert prof.folded()  # non-empty folded stacks from a live run

    def test_snapshot_summary_is_bounded(self):
        clock = FakeClock(0)
        prof = self.make(clock)
        prof.sample_now()
        summary = prof.snapshot_summary(top=2)
        assert summary["samples"] == 1 and summary["windows"] == 1
        assert len(summary["topStacks"]) <= 2

    def test_hz_zero_never_starts_a_thread(self):
        prof = ContinuousProfiler(hz=0)
        prof.start()
        assert prof._thread is None
        prof.stop()


# ---------------------------------------------------------------------------
# XLA compile telemetry


def _compile_counts() -> dict[str, int]:
    from zeebe_tpu.utils.metrics import REGISTRY

    out = {"hit": 0, "miss": 0}
    metric = REGISTRY._metrics.get("zeebe_xla_compiles_total")
    if metric is not None:
        for child in metric._children_snapshot():
            out[child.label_values[0]] = int(child.value)
    return out


class TestCompileTelemetry:
    def test_observe_compile_classifies_hit_and_miss(self):
        from zeebe_tpu.utils.metrics import REGISTRY

        before = _compile_counts()
        assert observe_compile("I8xT32", 0.02) == "hit"
        assert observe_compile("I8xT32", 5.0) == "miss"
        after = _compile_counts()
        assert after["hit"] == before["hit"] + 1
        assert after["miss"] == before["miss"] + 1
        hist = REGISTRY._metrics.get("zeebe_xla_compile_seconds")
        buckets = {c.label_values[0] for c in hist._children_snapshot()}
        assert "I8xT32" in buckets

    def test_compile_seam_fires_on_first_dispatch_and_forced_recompile(self):
        """The kernel backend's first dispatch per geometry is timed into
        the telemetry; deploying a second definition recompiles the shared
        table set (new content fingerprint → new compile key), so the next
        group dispatch counts again — the forced-recompile scenario."""
        from zeebe_tpu.models.bpmn import Bpmn
        from zeebe_tpu.testing import EngineHarness

        def model(pid, task):
            return (Bpmn.create_executable_process(pid)
                    .start_event("s").service_task(task, job_type=f"w_{pid}")
                    .end_event("e").done())

        h = EngineHarness(use_kernel_backend=True)
        try:
            before = _compile_counts()
            h.deploy(model("prof_a", "t1"))
            h.create_instance("prof_a")
            mid = _compile_counts()
            assert sum(mid.values()) == sum(before.values()) + 1, \
                "first group dispatch must be timed exactly once"
            # same geometry again: tracing-cache hit, no new observation
            h.create_instance("prof_a")
            assert _compile_counts() == mid
            seen_before = set(h.kernel_backend._compiles_seen)
            # forced recompile: a second deployment changes the shared table
            # set, so the same shape bucket is a NEW program
            h.deploy(model("prof_b", "t2"))
            h.create_instance("prof_b")
            after = _compile_counts()
            assert sum(after.values()) == sum(mid.values()) + 1
            assert set(h.kernel_backend._compiles_seen) != seen_before
        finally:
            h.close()


# ---------------------------------------------------------------------------
# device memory telemetry


class TestDeviceMemory:
    def test_stats_map_into_gauges(self):
        from zeebe_tpu.utils.metrics import REGISTRY

        fake = types.SimpleNamespace(
            platform="tpu", id=3,
            memory_stats=lambda: {"bytes_in_use": 1024, "bytes_limit": 4096})
        assert sample_device_memory([fake]) == 2
        gauge = REGISTRY._metrics.get("zeebe_device_memory_bytes")
        values = {c.label_values: c.value
                  for c in gauge._children_snapshot()}
        assert values[("tpu:3", "in_use")] == 1024.0
        assert values[("tpu:3", "limit")] == 4096.0

    def test_statless_and_raising_devices_are_skipped(self):
        no_stats = types.SimpleNamespace(platform="cpu", id=0,
                                         memory_stats=lambda: None)

        def boom():
            raise NotImplementedError

        raising = types.SimpleNamespace(platform="cpu", id=1,
                                        memory_stats=boom)
        assert sample_device_memory([no_stats, raising]) == 0


# ---------------------------------------------------------------------------
# alert-triggered capture


class RecorderStub:
    def __init__(self) -> None:
        self.events: list[tuple[int, str, dict]] = []

    def record(self, partition_id, kind, **detail):
        self.events.append((partition_id, kind, detail))


class TestAlertProfileCapture:
    def test_capture_records_profile_event_throttled_per_rule(self):
        clock = FakeClock(0)
        recorder = RecorderStub()
        capture = AlertProfileCapture(recorder, profiler=None,
                                      min_interval_ms=30_000,
                                      clock_millis=clock)
        assert capture.on_firing("exporter_lag", '{node="b0"}')
        assert not capture.on_firing("exporter_lag")  # throttled
        assert capture.on_firing("journal_flush_slow")  # other rule passes
        clock.advance(31_000)
        assert capture.on_firing("exporter_lag")  # throttle window elapsed
        kinds = [(k, d["rule"]) for _, k, d in recorder.events]
        assert kinds == [("profile", "exporter_lag"),
                         ("profile", "journal_flush_slow"),
                         ("profile", "exporter_lag")]
        # without a continuous profiler the capture is one instant snapshot
        _, _, detail = recorder.events[0]
        assert detail["source"] == "instant" and detail["stacks"]

    def test_capture_prefers_continuous_profiler_aggregate(self):
        clock = FakeClock(50_000)
        prof = ContinuousProfiler(window_ms=10_000, clock_millis=clock)
        prof.sample_now()
        recorder = RecorderStub()
        capture = AlertProfileCapture(recorder, profiler=prof,
                                      clock_millis=clock)
        assert capture.on_firing("xla_recompile_storm")
        _, _, detail = recorder.events[0]
        assert detail["source"] == "continuous" and detail["stacks"]


# ---------------------------------------------------------------------------
# device trace capture (single-flight)


class TestDeviceTraceCapture:
    def test_single_flight_then_reusable(self, tmp_path):
        started: list[str] = []
        stopped: list[bool] = []
        capture = DeviceTraceCapture(
            tmp_path, start_fn=started.append,
            stop_fn=lambda: stopped.append(True))
        trace_dir = capture.start(seconds=30.0)
        assert trace_dir.exists() and started == [str(trace_dir)]
        with pytest.raises(CaptureInFlight):
            capture.start(seconds=1.0)
        capture.cancel()  # end early; the slot frees
        assert stopped == [True] and capture.active_dir is None
        second = capture.start(seconds=0.01)
        capture.wait()
        assert second != trace_dir and capture.captures_taken == 2

    def test_failing_stop_still_releases_slot(self, tmp_path):
        def bad_stop():
            raise RuntimeError("no trace in progress")

        capture = DeviceTraceCapture(tmp_path, start_fn=lambda d: None,
                                     stop_fn=bad_stop)
        capture.start(seconds=0.01)
        capture.wait()
        assert capture.active_dir is None
        capture.start(seconds=0.01)
        capture.wait()


# ---------------------------------------------------------------------------
# management endpoints


def _http_get(port: int, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
        return resp.status, resp.read().decode()


def _http_post(port: int, path: str):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.read().decode()


class TestManagementProfileEndpoints:
    def test_one_shot_profile_folded_format(self):
        from zeebe_tpu.broker.management import ManagementServer

        server = ManagementServer(broker=None)
        server.start()
        try:
            status, body = _http_get(
                server.port, "/profile?seconds=0.2&format=folded")
            assert status == 200
            parsed = parse_folded(body)
            assert parsed and sum(parsed.values()) >= 1
        finally:
            server.stop()

    def test_one_shot_profile_reports_achieved_rate(self):
        from zeebe_tpu.broker.management import sample_profile

        result = sample_profile(0.2, hz=50.0)
        assert result["achievedHz"] > 0
        # deadline pacing: the achieved rate lands near the request instead
        # of undershooting by the per-tick work (generous floor for slow CI)
        assert result["achievedHz"] >= 20.0

    def test_one_shot_profile_names_threads_spawned_mid_window(self):
        from zeebe_tpu.broker.management import sample_profile

        release = threading.Event()

        def late_work():
            release.wait(5)

        late = threading.Thread(target=late_work, name="late-spawned-thread")
        spawner = threading.Timer(0.1, late.start)
        spawner.start()
        try:
            result = sample_profile(0.5, hz=100.0)
        finally:
            release.set()
            spawner.join()
            late.join()
        assert "late-spawned-thread" in result["threads"]

    def test_continuous_endpoint_serves_windows_and_folded(self):
        from zeebe_tpu.broker.management import ManagementServer

        clock = FakeClock(0)
        prof = ContinuousProfiler(window_ms=1000, clock_millis=clock)
        prof.sample_now()
        clock.advance(1000)
        prof.sample_now()
        broker = types.SimpleNamespace(profiler=prof)
        server = ManagementServer(broker=broker)
        server.start()
        try:
            status, body = _http_get(server.port, "/profile/continuous")
            assert status == 200
            payload = json.loads(body)
            assert payload["samples"] == 2 and len(payload["windows"]) == 2
            status, body = _http_get(
                server.port, "/profile/continuous?format=folded&since=1000")
            assert status == 200 and parse_folded(body)
            with pytest.raises(urllib.error.HTTPError) as err:
                _http_get(server.port, "/profile/continuous?since=abc")
            assert err.value.code == 400
        finally:
            server.stop()

    def test_continuous_endpoint_404_when_disabled(self):
        from zeebe_tpu.broker.management import ManagementServer

        server = ManagementServer(
            broker=types.SimpleNamespace(profiler=None))
        server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _http_get(server.port, "/profile/continuous")
            assert err.value.code == 404
        finally:
            server.stop()

    def test_device_capture_endpoint_is_single_flight(self, tmp_path):
        from zeebe_tpu.broker.management import ManagementServer

        capture = DeviceTraceCapture(tmp_path, start_fn=lambda d: None,
                                     stop_fn=lambda: None)
        broker = types.SimpleNamespace(device_capture=capture)
        server = ManagementServer(broker=broker)
        server.start()
        try:
            status, body = _http_post(server.port,
                                      "/profile/device?seconds=20")
            assert status == 202
            payload = json.loads(body)
            assert "jax-trace-" in payload["traceDir"]
            with pytest.raises(urllib.error.HTTPError) as err:
                _http_post(server.port, "/profile/device?seconds=1")
            assert err.value.code == 409  # second POST while in flight
            with pytest.raises(urllib.error.HTTPError) as err:
                _http_post(server.port, "/profile/device?seconds=abc")
            assert err.value.code == 400
        finally:
            capture.cancel()
            server.stop()


# ---------------------------------------------------------------------------
# broker integration: profiler plane on a live broker


class StallableExporter:
    stalled = True

    def configure(self, context):
        self.context = context

    def open(self, controller):
        self.controller = controller

    def export(self, record):
        if StallableExporter.stalled:
            raise RuntimeError("sink unavailable")
        self.controller.update_last_exported_position(record.position)

    def close(self):
        pass


class TestBrokerProfilingPlane:
    def test_profiling_disabled_leaves_no_plane(self, tmp_path):
        from zeebe_tpu.broker.broker import Broker, BrokerCfg
        from zeebe_tpu.cluster.messaging import LoopbackNetwork

        net = LoopbackNetwork()
        broker = Broker(
            BrokerCfg(node_id="broker-0", profiling_hz=0),
            net.join("broker-0"), directory=tmp_path / "b0")
        try:
            assert broker.profiler is None
            broker.pump()  # the disabled path is one is-None check
        finally:
            broker.close()

    def test_env_knob_binds(self):
        from zeebe_tpu.broker.config import load_broker_cfg

        cfg = load_broker_cfg(env={"ZEEBE_BROKER_PROFILING_HZ": "7.5"})
        assert cfg.base.profiling_hz == 7.5
        cfg = load_broker_cfg(env={"ZEEBE_BROKER_PROFILING_HZ": "0"})
        assert cfg.base.profiling_hz == 0

    def test_alert_fire_attaches_profile_to_flight_dump(self, tmp_path):
        """Acceptance: a forced alert (stalled exporter) leaves a flight
        dump containing an attached profile snapshot — both the
        alert-triggered capture event in the rings and the continuous
        profiler's summary in the dump context."""
        from zeebe_tpu.broker.broker import InProcessCluster
        from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
        from zeebe_tpu.protocol import ValueType, command
        from zeebe_tpu.protocol.intent import (
            DeploymentIntent,
            ProcessInstanceCreationIntent,
        )

        StallableExporter.stalled = True
        cluster = InProcessCluster(
            broker_count=1, partition_count=1, replication_factor=1,
            directory=tmp_path / "cluster",
            exporters_factory=lambda: {"stallable": StallableExporter()})
        try:
            cluster.await_leaders()
            broker = cluster.brokers["broker-0"]
            assert broker.profiler is not None  # on by default (~19 Hz)
            model = (Bpmn.create_executable_process("prof_alert")
                     .start_event("s").end_event("e").done())
            cluster.write_command(1, command(
                ValueType.DEPLOYMENT, DeploymentIntent.CREATE,
                {"resources": [{"resourceName": "p.bpmn",
                                "resource": to_bpmn_xml(model)}]}))
            create = command(
                ValueType.PROCESS_INSTANCE_CREATION,
                ProcessInstanceCreationIntent.CREATE,
                {"bpmnProcessId": "prof_alert", "version": -1,
                 "variables": {}})
            leader = cluster.leader(1)
            for _ in range(16):
                leader.write_commands([create] * 10)
                cluster.run(100)
            cluster.run(6000)  # controlled time ≫ the 5s for-duration
            assert any(a["rule"] == "exporter_lag"
                       for a in broker.alerts.firing())
            ring = broker.flight_recorder.snapshot()["partitions"]["0"]
            profiles = [e for e in ring if e["kind"] == "profile"]
            assert profiles, "firing alert did not capture a profile"
            assert profiles[0]["rule"] == "exporter_lag"
            assert profiles[0]["stacks"]
            # the dump itself carries the continuous profiler's summary
            path = broker.flight_recorder.dump("test-profile", force=True)
            payload = json.loads(path.read_text())
            assert "profile" in payload
            assert any(e["kind"] == "profile"
                       for e in payload["partitions"]["0"])
        finally:
            StallableExporter.stalled = False
            cluster.close()

    def test_continuous_endpoint_on_live_broker_is_attributable(self,
                                                                tmp_path):
        """Acceptance: GET /profile/continuous?format=folded on a live
        broker returns non-empty folded stacks whose frames point into the
        codebase (thread name root + file:function frames)."""
        from zeebe_tpu.broker.broker import Broker, BrokerCfg
        from zeebe_tpu.broker.management import ManagementServer
        from zeebe_tpu.cluster.messaging import LoopbackNetwork

        net = LoopbackNetwork()
        broker = Broker(BrokerCfg(node_id="broker-0", profiling_hz=50),
                        net.join("broker-0"), directory=tmp_path / "b0")
        server = ManagementServer(broker)
        server.start()
        try:
            deadline = time.monotonic() + 5
            while broker.profiler.samples_taken < 3 \
                    and time.monotonic() < deadline:
                broker.pump()
                time.sleep(0.02)
            status, body = _http_get(
                server.port, "/profile/continuous?format=folded")
            assert status == 200
            parsed = parse_folded(body)
            assert parsed
            frames = {f for stack in parsed for f in stack}
            assert any(".py:" in f for f in frames)
        finally:
            server.stop()
            broker.close()


# ---------------------------------------------------------------------------
# default alert rule


def test_xla_recompile_storm_is_a_default_rule():
    from zeebe_tpu.observability.alerts import default_rules

    [rule] = [r for r in default_rules() if r.name == "xla_recompile_storm"]
    assert rule.series == "zeebe_xla_compiles_total"
    assert rule.kind == "changes"
    assert 'cache="miss"' in rule.labels_contains
