"""Device-side multi-instance bodies (VERDICT r3 item 3): eligible MI tasks
lower to K_MI — the body parks like a scope, the device spawns/counts child
tokens and detects completion, while child activations ride the sequential
FIFO drain for byte parity (reference: engine/…/processing/bpmn/container/
MultiInstanceBodyProcessor.java)."""

from __future__ import annotations

from zeebe_tpu.models.bpmn import Bpmn
from zeebe_tpu.testing import EngineHarness

from tests.test_kernel_backend import assert_equivalent, drive_jobs


def mi_proc(pid="mi", seq=False, collection="= items", out=False):
    b = (
        Bpmn.create_executable_process(pid)
        .start_event("s")
        .service_task("work", job_type="w")
        .multi_instance(
            input_collection=collection,
            input_element="item",
            sequential=seq,
            **({"output_collection": "results", "output_element": "= r"}
               if out else {}),
        )
        .end_event("e")
    )
    return b.done()


def mi_after_task(pid="mi_after", seq=False):
    return (
        Bpmn.create_executable_process(pid)
        .start_event("s")
        .service_task("prep", job_type="prep")
        .service_task("work", job_type="w")
        .multi_instance(input_collection="= items", input_element="item",
                        sequential=seq)
        .service_task("after", job_type="aw")
        .end_event("e")
        .done()
    )


class TestMiParity:
    def test_parallel_mi_three_way(self):
        def scenario(h):
            h.deploy(mi_proc())
            h.create_instance("mi", {"items": [10, 20, 30]}, request_id=1)
            drive_jobs(h, "w", {"r": 1})

        assert_equivalent(scenario)

    def test_sequential_mi_three_way(self):
        def scenario(h):
            h.deploy(mi_proc("mis", seq=True))
            h.create_instance("mis", {"items": ["a", "b", "c"]}, request_id=2)
            # each completion spawns the next child
            while drive_jobs(h, "w"):
                pass

        assert_equivalent(scenario)

    def test_mi_with_output_collection(self):
        def scenario(h):
            h.deploy(mi_proc("mio", out=True))
            h.create_instance("mio", {"items": [1, 2]}, request_id=3)
            jobs = h.activate_jobs("w", max_jobs=10)
            for i, j in enumerate(jobs):
                h.complete_job(j["key"], {"r": 100 + i})

        assert_equivalent(scenario)

    def test_collection_produced_by_upstream_job(self):
        # the creation burst parks at `prep`; the MI body is only reached in
        # the job-complete burst whose doc carries the collection
        def scenario(h):
            h.deploy(mi_after_task())
            h.create_instance("mi_after", request_id=4)
            drive_jobs(h, "prep", {"items": [5, 6, 7]})
            drive_jobs(h, "w")
            drive_jobs(h, "aw")

        assert_equivalent(scenario)

    def test_single_item_collection(self):
        def scenario(h):
            h.deploy(mi_proc("mi1"))
            h.create_instance("mi1", {"items": [42]}, request_id=5)
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_empty_collection_falls_back(self):
        # empty bodies complete during activation (declined by admission);
        # parity must hold through the sequential path
        def scenario(h):
            h.deploy(mi_proc("mi0"))
            h.create_instance("mi0", {"items": []}, request_id=6)

        assert_equivalent(scenario)

    def test_invalid_collection_falls_back(self):
        def scenario(h):
            h.deploy(mi_proc("mibad"))
            h.create_instance("mibad", {"items": "oops"}, request_id=7)
            h.create_instance("mibad", {}, request_id=8)  # missing

        assert_equivalent(scenario)

    def test_large_collection_falls_back(self):
        def scenario(h):
            h.deploy(mi_proc("mibig"))
            h.create_instance("mibig", {"items": list(range(40))}, request_id=9)
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_mi_beside_parallel_branch(self):
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("mifork")
                .start_event("s")
                .parallel_gateway("split")
                .service_task("work", job_type="w")
                .multi_instance(input_collection="= items", input_element="item")
                .parallel_gateway("join")
                .end_event("e")
                .move_to_element("split")
                .service_task("side", job_type="sidew")
                .connect_to("join")
                .done()
            )
            h.create_instance("mifork", {"items": [1, 2]}, request_id=10)
            drive_jobs(h, "sidew")
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_mi_inside_called_child(self):
        # MI body inside an inlined call-activity region
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("michild")
                .start_event("cs")
                .service_task("cw", job_type="cw")
                .multi_instance(input_collection="= items", input_element="it")
                .end_event("ce")
                .done()
            )
            h.deploy(
                Bpmn.create_executable_process("micaller")
                .start_event("s")
                .call_activity("call", process_id="michild")
                .end_event("e")
                .done()
            )
            h.create_instance("micaller", {"items": [1, 2, 3]}, request_id=11)
            drive_jobs(h, "cw")

        assert_equivalent(scenario)

    def test_two_mi_bodies_in_sequence(self):
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("mi2")
                .start_event("s")
                .service_task("a", job_type="aw")
                .multi_instance(input_collection="= xs", input_element="x")
                .service_task("b", job_type="bw")
                .multi_instance(input_collection="= ys", input_element="y",
                                sequential=True)
                .end_event("e")
                .done()
            )
            h.create_instance("mi2", {"xs": [1, 2], "ys": [3, 4]}, request_id=12)
            drive_jobs(h, "aw")
            while drive_jobs(h, "bw"):
                pass

        assert_equivalent(scenario)

    def test_partial_completions_across_bursts(self):
        # complete children one at a time: each resume reconstructs the
        # parked body + remaining children
        def scenario(h):
            h.deploy(mi_proc("mipart"))
            h.create_instance("mipart", {"items": [1, 2, 3]}, request_id=13)
            jobs = h.activate_jobs("w", max_jobs=10)
            for j in jobs:  # one command per group (same-instance conflict)
                h.complete_job(j["key"], {"out": j["key"] % 7})

        assert_equivalent(scenario)

    def test_mi_with_condition_downstream(self):
        # MI defs may carry device conditions; the collection variable is
        # distinct from the condition variable
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("micond")
                .start_event("s")
                .service_task("work", job_type="w")
                .multi_instance(input_collection="= items", input_element="item")
                .exclusive_gateway("gw")
                .condition_expression("x > 5")
                .end_event("hi")
                .move_to_element("gw")
                .default_flow()
                .end_event("lo")
                .done()
            )
            h.create_instance("micond", {"items": [1, 2], "x": 10}, request_id=14)
            h.create_instance("micond", {"items": [1], "x": 1}, request_id=15)
            drive_jobs(h, "w")

        assert_equivalent(scenario)


class TestMiMechanics:
    def test_kernel_actually_executes_mi(self):
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(mi_proc())
            h.create_instance("mi", {"items": [1, 2, 3]})
            with h.db.transaction():
                meta = h.engine.state.processes.get_latest_by_id("mi")
            info = h.kernel_backend.registry.lookup(
                meta["processDefinitionKey"], None)
            assert info is not None and info.mi_inner, "MI body not inlined"
            before = h.kernel_backend.commands_processed
            assert before >= 1  # the creation rode the kernel
            for j in h.activate_jobs("w", max_jobs=10):
                h.complete_job(j["key"])
            assert h.kernel_backend.commands_processed >= before + 3
        finally:
            h.close()

    def test_collection_written_by_output_mapping_stays_host(self):
        # an output mapping targeting the collection variable makes the
        # admission prediction unsound → the body must not be device-inlined
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("mitaint")
                .start_event("s")
                .service_task("prep", job_type="p")
                .zeebe_output("= raw", "items")
                .service_task("work", job_type="w")
                .multi_instance(input_collection="= items", input_element="item")
                .end_event("e")
                .done()
            )
            h.create_instance("mitaint", request_id=20)
            drive_jobs(h, "p", {"raw": [1, 2]})
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_mi_on_cycle_stays_host(self):
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("miloop")
                .start_event("s")
                .exclusive_gateway("back")
                .service_task("work", job_type="w")
                .multi_instance(input_collection="= items", input_element="item")
                .exclusive_gateway("gw")
                .condition_expression("again = 1")
                .connect_to("back")
                .move_to_element("gw")
                .default_flow()
                .end_event("e")
                .done()
            )
            h.create_instance("miloop", {"items": [1], "again": 0},
                              request_id=21)
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_cancel_instance_with_parked_mi(self):
        def scenario(h):
            h.deploy(mi_proc("micancel"))
            k = h.create_instance("micancel", {"items": [1, 2]}, request_id=22)
            h.cancel_instance(k)

        assert_equivalent(scenario)

    def test_script_result_rewriting_collection_stays_host(self):
        # a script task's result variable aliasing the collection could
        # rewrite it mid-burst (host-escaped, drained FIFO) — the body must
        # not be device-inlined (review finding r4)
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("miscript")
                .start_event("s")
                .parallel_gateway("split")
                .script_task("sc", expression='= ["x"]',
                             result_variable="items")
                .parallel_gateway("join")
                .end_event("e")
                .move_to_element("split")
                .service_task("work", job_type="w")
                .multi_instance(input_collection="= items", input_element="it")
                .connect_to("join")
                .done()
            )
            h.create_instance("miscript", {"items": [1, 2]}, request_id=30)
            drive_jobs(h, "w")

        assert_equivalent(scenario)

    def test_sibling_call_propagation_keeps_mi_host(self):
        # a non-ancestor call activity's completion propagates arbitrary
        # child variables mid-burst — the body must not be device-inlined
        def scenario(h):
            h.deploy(
                Bpmn.create_executable_process("writer_child")
                .start_event("cs").manual_task("cm").end_event("ce").done()
            )
            h.deploy(
                Bpmn.create_executable_process("misib")
                .start_event("s")
                .call_activity("call", process_id="writer_child")
                .service_task("work", job_type="w")
                .multi_instance(input_collection="= items", input_element="it")
                .end_event("e")
                .done()
            )
            h.create_instance("misib", {"items": [7, 8]}, request_id=31)
            drive_jobs(h, "w")

        assert_equivalent(scenario)
