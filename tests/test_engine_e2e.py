"""End-to-end engine tests: deploy → create instance → jobs → completion,
asserting on the event stream like the reference's RecordingExporter tests.

The intent sequences asserted here mirror the reference engine's published
event streams for the same scenarios (e.g. the docs' one-task example:
ACTIVATING/ACTIVATED/COMPLETING/COMPLETED per element, SEQUENCE_FLOW_TAKEN
between elements, job lifecycle interleaved).
"""

import pytest

from zeebe_tpu.models.bpmn import Bpmn
from zeebe_tpu.protocol import RecordType, ValueType
from zeebe_tpu.protocol.enums import BpmnElementType
from zeebe_tpu.protocol.intent import (
    DeploymentIntent,
    IncidentIntent,
    JobIntent,
    ProcessInstanceIntent as PI,
    ProcessIntent,
    VariableIntent,
)
from zeebe_tpu.testing import EngineHarness


@pytest.fixture
def harness(tmp_path):
    h = EngineHarness(tmp_path)
    yield h
    h.close()


def one_task():
    return (
        Bpmn.create_executable_process("one_task")
        .start_event("start")
        .service_task("task", job_type="work")
        .end_event("end")
        .done()
    )


class TestDeployment:
    def test_deploy_creates_process(self, harness):
        harness.deploy(one_task())
        process = harness.exporter.process_records().with_intent(ProcessIntent.CREATED).first()
        assert process.record.value["bpmnProcessId"] == "one_task"
        assert process.record.value["version"] == 1
        deployment = (
            harness.exporter.deployment_records().with_intent(DeploymentIntent.CREATED).first()
        )
        assert deployment.record.value["processesMetadata"][0]["bpmnProcessId"] == "one_task"
        assert harness.exporter.deployment_records().with_intent(
            DeploymentIntent.FULLY_DISTRIBUTED
        ).exists()

    def test_redeploy_same_is_duplicate(self, harness):
        harness.deploy(one_task())
        harness.deploy(one_task())
        deployments = harness.exporter.deployment_records().with_intent(DeploymentIntent.CREATED).to_list()
        assert deployments[1].record.value["processesMetadata"][0]["duplicate"] is True
        assert deployments[1].record.value["processesMetadata"][0]["version"] == 1
        # only one PROCESS CREATED event
        assert harness.exporter.process_records().with_intent(ProcessIntent.CREATED).count() == 1

    def test_redeploy_changed_bumps_version(self, harness):
        harness.deploy(one_task())
        changed = (
            Bpmn.create_executable_process("one_task")
            .start_event("start")
            .service_task("task", job_type="different-type")
            .end_event("end")
            .done()
        )
        harness.deploy(changed)
        versions = [
            r.record.value["version"]
            for r in harness.exporter.process_records().with_intent(ProcessIntent.CREATED)
        ]
        assert versions == [1, 2]

    def test_invalid_process_rejected(self, harness):
        bad = Bpmn.create_executable_process("bad").done()  # no start event
        harness.deploy(bad)
        rejections = harness.exporter.deployment_records().rejections().to_list()
        assert len(rejections) == 1
        assert "start" in rejections[0].record.rejection_reason

    def test_deploy_responds_to_request(self, harness):
        harness.deploy(one_task())
        assert any(
            r.record.value_type == ValueType.DEPLOYMENT for r in harness.responses
        )


class TestOneTaskLifecycle:
    def test_instance_runs_to_task(self, harness):
        harness.deploy(one_task())
        pi_key = harness.create_instance("one_task")
        # process + start event lifecycle
        process_intents = (
            harness.exporter.process_instance_records()
            .events()
            .with_element_id("one_task")
            .intent_sequence()
        )
        assert process_intents == ["ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED"]
        start_intents = (
            harness.exporter.process_instance_records()
            .events()
            .with_element_id("start")
            .intent_sequence()
        )
        assert start_intents == [
            "ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED", "ELEMENT_COMPLETING", "ELEMENT_COMPLETED",
        ]
        # flow taken to the task, task waits activated with a job
        assert (
            harness.exporter.process_instance_records()
            .with_intent(PI.SEQUENCE_FLOW_TAKEN)
            .with_element_type(BpmnElementType.SEQUENCE_FLOW)
            .count()
            == 1
        )
        task_intents = (
            harness.exporter.process_instance_records().events().with_element_id("task").intent_sequence()
        )
        assert task_intents == ["ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED"]
        job = harness.exporter.job_records().with_intent(JobIntent.CREATED).first()
        assert job.record.value["type"] == "work"
        assert job.record.value["elementId"] == "task"
        assert job.record.value["processInstanceKey"] == pi_key

    def test_complete_job_completes_instance(self, harness):
        harness.deploy(one_task())
        pi_key = harness.create_instance("one_task")
        jobs = harness.activate_jobs("work")
        assert len(jobs) == 1
        harness.complete_job(jobs[0]["key"])

        assert harness.is_instance_done(pi_key)
        end_intents = (
            harness.exporter.process_instance_records().events().with_element_id("end").intent_sequence()
        )
        assert end_intents == [
            "ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED", "ELEMENT_COMPLETING", "ELEMENT_COMPLETED",
        ]
        # the process itself completes last
        proc_events = (
            harness.exporter.process_instance_records()
            .events()
            .with_element_id("one_task")
            .intent_sequence()
        )
        assert proc_events == [
            "ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED", "ELEMENT_COMPLETING", "ELEMENT_COMPLETED",
        ]
        # full event order sanity: process completed is the last PI event
        all_pi = harness.exporter.process_instance_records().events().to_list()
        assert all_pi[-1].record.value["elementId"] == "one_task"
        assert all_pi[-1].record.intent == PI.ELEMENT_COMPLETED

    def test_job_activation_carries_variables(self, harness):
        harness.deploy(one_task())
        harness.create_instance("one_task", variables={"amount": 99, "user": "bo"})
        jobs = harness.activate_jobs("work")
        assert jobs[0]["variables"] == {"amount": 99, "user": "bo"}

    def test_job_completion_variables_merge(self, harness):
        harness.deploy(one_task())
        pi_key = harness.create_instance("one_task")
        jobs = harness.activate_jobs("work")
        harness.complete_job(jobs[0]["key"], variables={"result": "ok"})
        var = harness.exporter.variable_records().with_intent(VariableIntent.CREATED).with_value(
            name="result"
        ).first()
        assert var.record.value["value"] == "ok"
        assert var.record.value["scopeKey"] == pi_key


class TestExclusiveGateway:
    def deploy_branching(self, harness):
        harness.deploy(
            Bpmn.create_executable_process("branching")
            .start_event("start")
            .exclusive_gateway("gw")
            .sequence_flow_id("to_big")
            .condition_expression("amount >= 100")
            .service_task("big", job_type="big-order")
            .end_event("end_big")
            .move_to_element("gw")
            .sequence_flow_id("to_small")
            .default_flow()
            .service_task("small", job_type="small-order")
            .end_event("end_small")
            .done()
        )

    def test_condition_true_path(self, harness):
        self.deploy_branching(harness)
        harness.create_instance("branching", variables={"amount": 150})
        job = harness.exporter.job_records().with_intent(JobIntent.CREATED).first()
        assert job.record.value["type"] == "big-order"
        taken = harness.exporter.process_instance_records().with_intent(PI.SEQUENCE_FLOW_TAKEN).to_list()
        assert any(t.record.value["elementId"] == "to_big" for t in taken)

    def test_default_path(self, harness):
        self.deploy_branching(harness)
        harness.create_instance("branching", variables={"amount": 10})
        job = harness.exporter.job_records().with_intent(JobIntent.CREATED).first()
        assert job.record.value["type"] == "small-order"

    def test_no_match_no_default_raises_incident(self, harness):
        harness.deploy(
            Bpmn.create_executable_process("nodefault")
            .start_event("s")
            .exclusive_gateway("gw")
            .condition_expression("x > 10")
            .end_event("e")
            .done()
        )
        harness.create_instance("nodefault", variables={"x": 1})
        incident = harness.exporter.incident_records().with_intent(IncidentIntent.CREATED).first()
        assert incident.record.value["errorType"] == "CONDITION_ERROR"
        assert incident.record.value["elementId"] == "gw"

    def test_incident_resolution_retries_gateway(self, harness):
        harness.deploy(
            Bpmn.create_executable_process("nodefault")
            .start_event("s")
            .exclusive_gateway("gw")
            .condition_expression("x > 10")
            .end_event("e")
            .done()
        )
        pi_key = harness.create_instance("nodefault", variables={"x": 1})
        incident = harness.exporter.incident_records().with_intent(IncidentIntent.CREATED).first()
        # fix the variable, resolve → process completes
        harness.set_variables(pi_key, {"x": 50})
        harness.resolve_incident(incident.record.key)
        assert harness.is_instance_done(pi_key)


class TestParallelGateway:
    def test_fork_join(self, harness):
        harness.deploy(
            Bpmn.create_executable_process("fork_join")
            .start_event("start")
            .parallel_gateway("fork")
            .service_task("a", job_type="a")
            .parallel_gateway("join")
            .end_event("end")
            .move_to_element("fork")
            .service_task("b", job_type="b")
            .connect_to("join")
            .done()
        )
        pi_key = harness.create_instance("fork_join")
        # both branches have jobs
        assert len(harness.activate_jobs("a")) == 1
        assert len(harness.activate_jobs("b")) == 1
        jobs_a = harness.exporter.job_records().with_intent(JobIntent.CREATED).with_value(type="a").first()
        harness.complete_job(jobs_a.record.key)
        # join not yet satisfied: the join must not have activated
        assert not (
            harness.exporter.process_instance_records()
            .with_element_id("join").events().exists()
        )
        assert not harness.is_instance_done(pi_key)
        jobs_b = harness.exporter.job_records().with_intent(JobIntent.CREATED).with_value(type="b").first()
        harness.complete_job(jobs_b.record.key)
        # join activated exactly once, process completed
        join_intents = (
            harness.exporter.process_instance_records().events().with_element_id("join").intent_sequence()
        )
        assert join_intents == [
            "ELEMENT_ACTIVATING", "ELEMENT_ACTIVATED", "ELEMENT_COMPLETING", "ELEMENT_COMPLETED",
        ]
        assert harness.is_instance_done(pi_key)


class TestJobFailure:
    def test_fail_with_retries_reactivatable(self, harness):
        harness.deploy(one_task())
        harness.create_instance("one_task")
        jobs = harness.activate_jobs("work")
        harness.fail_job(jobs[0]["key"], retries=2, error_message="flaky")
        # job activatable again
        jobs2 = harness.activate_jobs("work")
        assert len(jobs2) == 1
        assert jobs2[0]["retries"] == 2

    def test_fail_no_retries_creates_incident(self, harness):
        harness.deploy(one_task())
        harness.create_instance("one_task")
        jobs = harness.activate_jobs("work")
        harness.fail_job(jobs[0]["key"], retries=0, error_message="broken")
        incident = harness.exporter.incident_records().with_intent(IncidentIntent.CREATED).first()
        assert incident.record.value["errorType"] == "JOB_NO_RETRIES"
        assert incident.record.value["jobKey"] == jobs[0]["key"]
        # not activatable anymore
        assert harness.activate_jobs("work") == []

    def test_incident_resolution_after_retries_update(self, harness):
        harness.deploy(one_task())
        pi_key = harness.create_instance("one_task")
        jobs = harness.activate_jobs("work")
        harness.fail_job(jobs[0]["key"], retries=0)
        incident = harness.exporter.incident_records().with_intent(IncidentIntent.CREATED).first()
        harness.update_job_retries(jobs[0]["key"], retries=3)
        harness.resolve_incident(incident.record.key)
        jobs2 = harness.activate_jobs("work")
        assert len(jobs2) == 1
        harness.complete_job(jobs2[0]["key"])
        assert harness.is_instance_done(pi_key)


class TestCancel:
    def test_cancel_terminates_tree(self, harness):
        harness.deploy(one_task())
        pi_key = harness.create_instance("one_task")
        jobs = harness.activate_jobs("work")
        harness.cancel_instance(pi_key)
        assert harness.is_instance_done(pi_key)
        # task terminated, job canceled
        assert (
            harness.exporter.process_instance_records()
            .with_element_id("task")
            .with_intent(PI.ELEMENT_TERMINATED)
            .exists()
        )
        assert harness.exporter.job_records().with_intent(JobIntent.CANCELED).exists()
        # process terminated last
        assert (
            harness.exporter.process_instance_records()
            .with_element_id("one_task")
            .with_intent(PI.ELEMENT_TERMINATED)
            .exists()
        )

    def test_cancel_unknown_rejected(self, harness):
        harness.deploy(one_task())
        harness.cancel_instance(999999)
        assert (
            harness.exporter.process_instance_records()
            .rejections()
            .with_intent(PI.CANCEL)
            .exists()
        )


class TestCreateRejections:
    def test_unknown_process_rejected(self, harness):
        harness.write_command(
            __import__("zeebe_tpu.protocol", fromlist=["command"]).command(
                ValueType.PROCESS_INSTANCE_CREATION,
                __import__(
                    "zeebe_tpu.protocol.intent", fromlist=["ProcessInstanceCreationIntent"]
                ).ProcessInstanceCreationIntent.CREATE,
                {"bpmnProcessId": "ghost", "version": -1, "variables": {}},
            ),
            request_id=10,
        )
        rej = harness.exporter.all().rejections().first()
        assert "ghost" in rej.record.rejection_reason
