"""Metric-name parity with the reference (SURVEY §5.5: 111 distinct names).

The reference names were extracted verbatim from its *Metrics.java classes
(tests/data/reference_metric_names.txt). A full in-process cluster scenario —
gRPC gateway, multi-partition broker, jobs (pull + push), timers, incidents,
messages, DMN, snapshot, backup — must leave >= 80 of those names registered,
and the management server's /metrics endpoint must expose them in Prometheus
text format."""

from __future__ import annotations

from pathlib import Path

REFERENCE_NAMES = set(
    (Path(__file__).parent / "data" / "reference_metric_names.txt")
    .read_text().split()
)


def registered_names() -> set[str]:
    from zeebe_tpu.utils.metrics import REGISTRY

    prefix = f"{REGISTRY.namespace}_"
    return {n[len(prefix):] for n in REGISTRY._metrics}  # noqa: SLF001


def test_reference_name_coverage_after_full_scenario(tmp_path):
    import threading

    from zeebe_tpu.backup.checkpoint import CheckpointState
    from zeebe_tpu.backup.service import BackupService
    from zeebe_tpu.backup.store import FileSystemBackupStore
    from zeebe_tpu.client import JobWorker, ZeebeTpuClient
    from zeebe_tpu.gateway import ClusterRuntime, Gateway
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml

    procs = [
        Bpmn.create_executable_process("mp_one")
        .start_event("s").service_task("t", job_type="mp_w")
        .end_event("e").done(),
        Bpmn.create_executable_process("mp_incident")
        .start_event("s").exclusive_gateway("gw")
        .condition_expression("x > 100").end_event("e").done(),
    ]
    runtime = ClusterRuntime(broker_count=1, partition_count=2)
    runtime.start()
    gw = Gateway(runtime)
    gw.start()
    client = ZeebeTpuClient(gw.address)
    try:
        client.deploy_resource(
            *[(f"{p.process_id}.bpmn", to_bpmn_xml(p)) for p in procs])
        for i in range(4):
            client.create_instance("mp_one", variables={"i": i})
        client.create_instance("mp_incident", variables={"x": 1})
        client.publish_message("mp_msg", "k1", variables={})
        for j in client.activate_jobs("mp_w", max_jobs=2,
                                      request_timeout_ms=5000):
            client.complete_job(j.key, {})
        # push path registers/unregisters a stream
        done = threading.Event()

        def _work(job):
            done.set()
            return {}

        worker = JobWorker(client, "mp_w", _work, stream_enabled=True).start()
        client.create_instance("mp_one", variables={"i": 99})
        done.wait(timeout=15)
        worker.stop()
        client.topology()
        broker = runtime.brokers["broker-0"]
        partition = broker.partitions[1]
        # snapshot + backup exercise their metric families
        partition.take_snapshot()
        store = FileSystemBackupStore(tmp_path / "backups")
        BackupService(store, "broker-0").take_backup(partition, 1, 1)
        with partition.db.transaction():
            CheckpointState(partition.db).put(1, 1)
    finally:
        client.close()
        gw.stop()
        runtime.stop()

    # the exporter and DMN metrics register at component construction
    # (reference: static collectors), so touching the components is enough
    from zeebe_tpu.exporters import ElasticsearchExporter

    ElasticsearchExporter(sink=lambda p: None)
    import zeebe_tpu.engine.decision  # noqa: F401 — registers the DMN counter

    ours = registered_names()
    matched = ours & REFERENCE_NAMES
    missing = sorted(REFERENCE_NAMES - ours)
    assert len(matched) == len(REFERENCE_NAMES), (
        f"only {len(matched)}/{len(REFERENCE_NAMES)} reference metric names "
        f"registered; missing: {missing}")


def test_metrics_endpoint_exposes_reference_names():
    import urllib.request

    from zeebe_tpu.broker.management import ManagementServer

    server = ManagementServer(broker=None)
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5) as resp:
            body = resp.read().decode()
        exposed = {
            line.split("{")[0].split(" ")[0][len("zeebe_"):]
            for line in body.splitlines()
            if line and not line.startswith("#")
        }
        # histograms expose _bucket/_sum/_count series — strip the suffixes
        def base(n: str) -> str:
            for suffix in ("_bucket", "_sum", "_count"):
                if n.endswith(suffix):
                    return n[: -len(suffix)]
            return n

        exposed = {base(n) for n in exposed}
        matched = exposed & REFERENCE_NAMES
        assert len(matched) >= 60, sorted(matched)
    finally:
        server.stop()
