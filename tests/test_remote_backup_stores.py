"""S3 / GCS remote backup store tests against in-process fake object stores.

Reference: backup-stores/s3 (S3BackupStoreIT against localstack),
backup-stores/gcs (against fake-gcs-server) — same idea, zero containers:
a threaded stdlib HTTP server emulating the minimal API surface each client
uses."""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from zeebe_tpu.backup import (
    Backup,
    GcsBackupStore,
    GcsClient,
    S3BackupStore,
    S3Client,
)
from zeebe_tpu.backup.store import BackupStatusCode
from zeebe_tpu.backup.s3 import sign_v4


class TestSigV4:
    def test_aws_published_vector(self):
        """The get-vanilla-query example from AWS's SigV4 test suite."""
        auth = sign_v4(
            method="GET", host="example.amazonaws.com", path="/",
            query={"Param1": "value1", "Param2": "value2"},
            headers={"x-amz-date": "20150830T123600Z"},
            payload_hash="e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855",
            region="us-east-1", service="service",
            access_key="AKIDEXAMPLE",
            secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
            amz_date="20150830T123600Z",
        )
        assert auth == (
            "AWS4-HMAC-SHA256 "
            "Credential=AKIDEXAMPLE/20150830/us-east-1/service/aws4_request, "
            "SignedHeaders=host;x-amz-date, "
            "Signature=b97d918cfa904a5beff61c982a1b6f458b799221646efd99d3219ec94cdf2500"
        )


# ---------------------------------------------------------------------------
# fake object stores


class _FakeS3Handler(BaseHTTPRequestHandler):
    """Path-style S3 subset: PUT/GET/DELETE object + ListObjectsV2."""

    store: dict[str, bytes] = {}
    seen_auth: list[str] = []

    def log_message(self, *args):  # quiet
        pass

    def _key(self) -> str:
        path = urllib.parse.urlparse(self.path).path
        return urllib.parse.unquote(path).lstrip("/").split("/", 1)[1]

    def do_PUT(self):
        self.seen_auth.append(self.headers.get("Authorization", ""))
        length = int(self.headers.get("Content-Length", 0))
        self.store[self._key()] = self.rfile.read(length)
        self.send_response(200)
        self.end_headers()

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        query = urllib.parse.parse_qs(parsed.query)
        if "list-type" in query:
            prefix = query.get("prefix", [""])[0]
            keys = sorted(k for k in self.store if k.startswith(prefix))
            body = "<ListBucketResult>" + "".join(
                f"<Contents><Key>{k}</Key></Contents>" for k in keys
            ) + "</ListBucketResult>"
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body.encode())
            return
        data = self.store.get(self._key())
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.end_headers()
        self.wfile.write(data)

    def do_DELETE(self):
        self.store.pop(self._key(), None)
        self.send_response(204)
        self.end_headers()


class _FakeGcsHandler(BaseHTTPRequestHandler):
    """GCS JSON API subset: media upload/download, delete, list."""

    store: dict[str, bytes] = {}

    def log_message(self, *args):
        pass

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        query = urllib.parse.parse_qs(parsed.query)
        name = query.get("name", [""])[0]
        length = int(self.headers.get("Content-Length", 0))
        self.store[name] = self.rfile.read(length)
        self.send_response(200)
        self.end_headers()
        self.wfile.write(b"{}")

    def _object_name(self) -> str:
        parsed = urllib.parse.urlparse(self.path)
        return urllib.parse.unquote(parsed.path.rsplit("/o/", 1)[1])

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        if "/o/" in parsed.path:
            data = self.store.get(self._object_name())
            if data is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.end_headers()
            self.wfile.write(data)
            return
        query = urllib.parse.parse_qs(parsed.query)
        prefix = query.get("prefix", [""])[0]
        items = [{"name": k} for k in sorted(self.store) if k.startswith(prefix)]
        self.send_response(200)
        self.end_headers()
        self.wfile.write(json.dumps({"items": items}).encode())

    def do_DELETE(self):
        self.store.pop(self._object_name(), None)
        self.send_response(204)
        self.end_headers()


@pytest.fixture()
def fake_server(request):
    handler = request.param
    handler.store = {}
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_port}", handler
    server.shutdown()
    thread.join(timeout=2)


def _sample_backup() -> Backup:
    return Backup(
        checkpoint_id=7, partition_id=1, node_id="broker-0",
        checkpoint_position=123,
        descriptor={"snapshotId": "5-2-100-100"},
        snapshot_files={"state.bin": b"\x01\x02state", "meta.bin": b"meta"},
        segment_files={"segment-001.log": b"\x00seg"},
    )


def _store_for(endpoint: str, handler) -> object:
    if handler is _FakeS3Handler:
        return S3BackupStore(S3Client(endpoint, "bucket", "key", "secret"))
    return GcsBackupStore(GcsClient("bucket", access_token="tok",
                                    endpoint=endpoint))


@pytest.mark.parametrize("fake_server", [_FakeS3Handler, _FakeGcsHandler],
                         indirect=True, ids=["s3", "gcs"])
class TestRemoteBackupStores:
    def test_save_status_read_roundtrip(self, fake_server):
        endpoint, handler = fake_server
        store = _store_for(endpoint, handler)
        backup = _sample_backup()
        assert store.get_status(7, 1).status == BackupStatusCode.DOES_NOT_EXIST
        status = store.save(backup)
        assert status.status == BackupStatusCode.COMPLETED
        restored = store.read(7, 1)
        assert restored.snapshot_files == backup.snapshot_files
        assert restored.segment_files == backup.segment_files
        assert restored.checkpoint_position == 123

    def test_list_and_delete(self, fake_server):
        endpoint, handler = fake_server
        store = _store_for(endpoint, handler)
        store.save(_sample_backup())
        listed = store.list_backups()
        assert [(s.partition_id, s.checkpoint_id) for s in listed] == [(1, 7)]
        store.delete(7, 1)
        assert store.list_backups() == []
        assert store.get_status(7, 1).status == BackupStatusCode.DOES_NOT_EXIST

    def test_partial_upload_reads_in_progress(self, fake_server):
        endpoint, handler = fake_server
        store = _store_for(endpoint, handler)
        # only content, no manifest yet (crash mid-save)
        store.client.put_object("backups/1/9/snapshot/state.bin", b"x")
        assert store.get_status(9, 1).status == BackupStatusCode.IN_PROGRESS


class TestS3Signing:
    @pytest.mark.parametrize("fake_server", [_FakeS3Handler],
                             indirect=True, ids=["s3"])
    def test_requests_carry_sigv4_authorization(self, fake_server):
        endpoint, handler = fake_server
        handler.seen_auth = []
        store = _store_for(endpoint, handler)
        store.save(_sample_backup())
        assert handler.seen_auth
        for auth in handler.seen_auth:
            assert auth.startswith("AWS4-HMAC-SHA256 Credential=key/")
            assert "Signature=" in auth


class TestBrokerWithRemoteStore:
    @pytest.mark.parametrize("fake_server", [_FakeS3Handler],
                             indirect=True, ids=["s3"])
    def test_checkpoint_backs_up_to_s3(self, fake_server):
        from zeebe_tpu.broker.broker import Broker, BrokerCfg
        from zeebe_tpu.cluster.messaging import LoopbackNetwork

        endpoint, handler = fake_server
        store = _store_for(endpoint, handler)
        import time

        net = LoopbackNetwork()
        broker = Broker(BrokerCfg(), net.join("broker-0"), backup_store=store)
        try:
            deadline = time.time() + 30
            while not broker.partitions[1].is_leader:
                broker.pump()
                net.deliver_all()
                time.sleep(0.005)
                assert time.time() < deadline, "no leader elected"
            assert broker.trigger_checkpoint(5) == 1
            for _ in range(50):
                broker.pump()
                net.deliver_all()
            statuses = store.list_backups()
            assert [(s.partition_id, s.checkpoint_id) for s in statuses] == [(1, 5)]
            assert statuses[0].status == BackupStatusCode.COMPLETED
        finally:
            broker.close()
