"""Rolling-upgrade fixture generator (reference: qa/update-tests/…/
RollingUpdateTest.java:51 — verify log/state compatibility across versions).

``build_fixture(out_dir)`` runs a breadth scenario with the CURRENT code and
freezes the produced artifacts: the journal segments, a state snapshot, and
an ``expected.json`` describing the in-flight work. The artifacts are
committed under ``tests/fixtures/upgrade/<tag>/``; every FUTURE round's CI
replays them with its own code (tests/test_update.py) and must (a) rebuild
the same state, (b) restore the old snapshot through its migrations, and
(c) drive the in-flight instances to completion — the update-tests contract.

Regenerate with  ``python -m tests.upgrade_fixture <tag>``  (run from the
repo root) whenever a new round wants to freeze its own artifacts. Never
regenerate an EXISTING tag: the committed bytes are the compatibility
surface.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path

EPOCH = 1_750_000_000_000

FIXTURES_DIR = Path(__file__).parent / "fixtures" / "upgrade"


def _models():
    from zeebe_tpu.models.bpmn import Bpmn

    one_task = (
        Bpmn.create_executable_process("one_task")
        .start_event("start").service_task("task", job_type="up_work")
        .end_event("end").done()
    )
    timer_wait = (
        Bpmn.create_executable_process("timer_wait")
        .start_event("s")
        .intermediate_catch_timer("wait", duration="PT30S")
        .service_task("after", job_type="up_after_timer")
        .end_event("e").done()
    )
    msg_wait = (
        Bpmn.create_executable_process("msg_wait")
        .start_event("s")
        .intermediate_catch_message("catch", "up_go", correlation_key="key")
        .end_event("e").done()
    )
    sub_bnd = (
        Bpmn.create_executable_process("sub_bnd")
        .start_event("s")
        .sub_process("sub")
        .start_event("is_")
        .service_task("inner", job_type="up_inner")
        .boundary_timer("tb", attached_to="inner", duration="PT1H")
        .end_event("bnd_e")
        .move_to_element("inner")
        .end_event("ie")
        .sub_process_done()
        .end_event("e").done()
    )
    io_chain = (
        Bpmn.create_executable_process("io_chain")
        .start_event("s")
        .service_task("t0", job_type="up_io")
        .zeebe_input("= base", "local0")
        .zeebe_output("= local0", "result0")
        .service_task("t1", job_type="up_io2")
        .end_event("e").done()
    )
    nomatch = (
        Bpmn.create_executable_process("nomatch")
        .start_event("s")
        .exclusive_gateway("gw")
        .condition_expression("x > 100")
        .end_event("e").done()
    )
    # round-4 kernel shapes: parked multi-instance bodies (parallel and
    # sequential), an inlined call-activity frame, and an inclusive fork —
    # future rounds must reconstruct these exact state shapes
    mi_par = (
        Bpmn.create_executable_process("mi_par")
        .start_event("s")
        .service_task("work", job_type="up_mi")
        .multi_instance(input_collection="= items", input_element="item")
        .end_event("e").done()
    )
    mi_seq = (
        Bpmn.create_executable_process("mi_seq")
        .start_event("s")
        .service_task("work", job_type="up_mi_seq")
        .multi_instance(input_collection="= items", input_element="item",
                        sequential=True)
        .end_event("e").done()
    )
    call_child = (
        Bpmn.create_executable_process("up_child_proc")
        .start_event("cs")
        .service_task("cw", job_type="up_child")
        .end_event("ce").done()
    )
    caller = (
        Bpmn.create_executable_process("up_caller")
        .start_event("s")
        .call_activity("call", process_id="up_child_proc")
        .end_event("e").done()
    )
    incl = (
        Bpmn.create_executable_process("up_incl")
        .start_event("s")
        .inclusive_gateway("gw")
        .condition_expression("a > 0")
        .service_task("ta", job_type="up_inc")
        .end_event("ea")
        .move_to_element("gw")
        .condition_expression("b > 0")
        .service_task("tb", job_type="up_inc")
        .end_event("eb")
        .move_to_element("gw").default_flow().end_event("ed")
        .done()
    )
    # round-5 shapes: link events (throw jumps to the same-scope catch) and
    # a root-level event sub-process definition (start subscription on the
    # process instance) — future rounds must replay their records and
    # reconstruct their state shapes
    link = (
        Bpmn.create_executable_process("up_link")
        .start_event("s")
        .service_task("before", job_type="up_link_a")
        .intermediate_throw_link("jump", "L")
        .intermediate_catch_link("land", "L")
        .service_task("after", job_type="up_link_b")
        .end_event("e").done()
    )
    esp_root = (
        Bpmn.create_executable_process("up_esp")
        .start_event("s")
        .service_task("work", job_type="up_esp_w")
        .end_event("e")
        .event_sub_process("esp")
        .message_start_event("ms", "up_alarm", correlation_key="= key")
        .end_event("esp_e")
        .sub_process_done()
        .done()
    )
    return [one_task, timer_wait, msg_wait, sub_bnd, io_chain, nomatch,
            mi_par, mi_seq, call_child, caller, incl, link, esp_root]


def run_scenario(h) -> dict:
    """Drive the breadth scenario; returns the expected.json payload."""
    h.deploy(*_models())
    # a short-TTL message expires during the build: the frozen log then
    # carries a MESSAGE_BATCH EXPIRED record (round-5 batched expiry) that
    # every future round must replay
    h.publish_message("up_ephemeral", "gone", ttl=1_000)
    h.advance_time(1_100)
    done_keys = []
    for i in range(2):  # completed end to end
        k = h.create_instance("one_task", variables={"i": i})
        done_keys.append(k)
    for job in h.activate_jobs("up_work", max_jobs=10):
        h.complete_job(job["key"], {"done": True})
    running = {}
    for i in range(2):  # mid-flight: job pending
        running[h.create_instance("one_task", variables={"i": 10 + i})] = "one_task"
    running[h.create_instance("timer_wait")] = "timer_wait"
    running[h.create_instance("msg_wait", variables={"key": "k-up"})] = "msg_wait"
    running[h.create_instance("sub_bnd")] = "sub_bnd"
    running[h.create_instance("io_chain", variables={"base": 9})] = "io_chain"
    running[h.create_instance("mi_par", variables={"items": [1, 2, 3]})] = "mi_par"
    running[h.create_instance("mi_seq", variables={"items": ["a", "b"]})] = "mi_seq"
    running[h.create_instance("up_caller")] = "up_caller"
    running[h.create_instance("up_incl", variables={"a": 1, "b": 1})] = "up_incl"
    # link events: one instance COMPLETES during the build (link lifecycle
    # records land in the frozen log), one parks mid-flight before the jump
    done_link = h.create_instance("up_link")
    for job in h.activate_jobs("up_link_a", max_jobs=5):
        h.complete_job(job["key"])
    for job in h.activate_jobs("up_link_b", max_jobs=5):
        h.complete_job(job["key"])
    done_keys.append(done_link)
    running[h.create_instance("up_link")] = "up_link"
    # root-ESP instance: parked with its start subscription open on the root
    running[h.create_instance("up_esp", variables={"key": "esp-k"})] = "up_esp"
    incident_key = h.create_instance("nomatch", variables={"x": 1})
    return {
        "tag_clock_millis": h.clock(),
        "completed_keys": done_keys,
        "running": {str(k): v for k, v in running.items()},
        "incident_instance": incident_key,
        "pending_jobs": {"up_work": 2, "up_inner": 1, "up_io": 1,
                         "up_mi": 3, "up_mi_seq": 1, "up_child": 1,
                         "up_inc": 2, "up_link_a": 1, "up_link_b": 0,
                         "up_esp_w": 1},
        # job types that respawn after completion (sequential MI): the drive
        # test keeps completing until the type is silent
        "drain_loop_types": ["up_mi_seq"],
        "message": {"name": "up_go", "correlation_key": "k-up"},
        "timer_advance_ms": 31_000,
        "last_position": h.stream.last_position,
    }


def build_fixture(tag: str) -> Path:
    import tempfile

    from zeebe_tpu.testing import ControlledClock, EngineHarness

    out = FIXTURES_DIR / tag
    if out.exists():
        raise SystemExit(f"fixture {tag} already exists — never regenerate "
                         "a committed tag")
    with tempfile.TemporaryDirectory() as tmp:
        h = EngineHarness(directory=tmp, clock=ControlledClock(EPOCH))
        try:
            expected = run_scenario(h)
            snapshot = h.db.to_snapshot_bytes()
            h.journal.close()
            out.mkdir(parents=True)
            shutil.copytree(Path(tmp) / "log", out / "log")
            (out / "state.snapshot").write_bytes(snapshot)
            (out / "expected.json").write_text(json.dumps(expected, indent=2))
        finally:
            h._tmp = None  # the caller's tempdir context cleans up
            try:
                h.close()
            except Exception:  # noqa: BLE001 — journal already closed above
                pass
    return out


if __name__ == "__main__":
    import sys

    tag = sys.argv[1] if len(sys.argv) > 1 else "r3"
    path = build_fixture(tag)
    print(f"fixture written to {path}")
