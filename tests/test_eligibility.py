"""The kernel observatory (ISSUE 13): reason catalog, static eligibility
classifier, consolidated path accounting, parity gate, wave events, and
bounded flight dumps.

The fixture definitions under tests/fixtures/eligibility/ carry one
host-forcing shape each (plus one fully-eligible definition); every test
asserts EXACT reason codes so a classifier change that silently re-labels
a shape fails here, not in a dashboard."""

from __future__ import annotations

import ast
import json
from pathlib import Path

import pytest

from zeebe_tpu.engine.eligibility import (
    ALL_REASONS,
    DEFINITION_REASONS,
    HEAD_FAMILIES,
    RUNTIME_REASONS,
    STATIC_ELEMENT_REASONS,
    PathAccounting,
    canonical_reason,
    classify_definition,
    parity_violations,
)
from zeebe_tpu.models.bpmn import Bpmn, parse_bpmn_xml
from zeebe_tpu.models.bpmn.executable import transform
from zeebe_tpu.testing import EngineHarness

FIXTURES = Path(__file__).parent / "fixtures" / "eligibility"
REPO = Path(__file__).resolve().parent.parent


def classify_fixture(name: str) -> dict:
    (model,) = parse_bpmn_xml((FIXTURES / name).read_text())
    return classify_definition(transform(model))


def host_reasons_of(report: dict) -> dict[str, str]:
    return {el["id"]: el.get("reason") for el in report["elements"]
            if el["path"] == "host"}


# ---------------------------------------------------------------------------
# the catalog


class TestReasonCatalog:
    def test_catalog_groups_are_disjoint_families_aside(self):
        assert not (STATIC_ELEMENT_REASONS & RUNTIME_REASONS)
        assert not (RUNTIME_REASONS & HEAD_FAMILIES)
        # definition-level shares only condition-not-compilable with the
        # element level (the same compile declines both granularities)
        assert (DEFINITION_REASONS & STATIC_ELEMENT_REASONS
                == {"condition-not-compilable"})

    def test_canonical_reason(self):
        assert canonical_reason("no-quiesce") == "no-quiesce"
        assert canonical_reason("multi-instance") == "multi-instance"
        assert (canonical_reason("head-sequential:DEPLOYMENT.CREATE")
                == "head-sequential")
        assert (canonical_reason("head-not-admittable:JOB.COMPLETE")
                == "head-not-admittable")
        assert canonical_reason("made-up-reason") is None

    def test_every_reason_has_a_note_and_no_stale_notes(self):
        from zeebe_tpu.analysis.eligibility_notes import (
            stale_reason_notes,
            undocumented_reasons,
        )

        assert undocumented_reasons() == []
        assert stale_reason_notes() == []

    def test_committed_doc_matches_generated(self):
        """Tree gate mirroring CI's `cli eligibility-doc --check`."""
        from zeebe_tpu.analysis.eligibility_notes import render_eligibility_doc

        committed = (REPO / "docs" / "eligibility.md").read_text()
        assert committed == render_eligibility_doc(), (
            "docs/eligibility.md drifted — regenerate with "
            "`python -m zeebe_tpu.cli eligibility-doc`")

    def test_no_unregistered_reason_literals_in_source(self):
        """Satellite: every reason string the two accounting seams note
        must resolve against the catalog — a stale or unregistered literal
        fails HERE, not by silently minting a new metric label."""
        sources = [
            REPO / "zeebe_tpu" / "engine" / "kernel_backend.py",
            REPO / "zeebe_tpu" / "stream" / "processor.py",
        ]
        checked = 0
        for path in sources:
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "note_host" and node.args):
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    assert canonical_reason(arg.value) is not None, (
                        f"{path.name}: unregistered reason {arg.value!r}")
                    checked += 1
                elif isinstance(arg, ast.JoinedStr):
                    head = arg.values[0]
                    assert isinstance(head, ast.Constant), ast.dump(arg)
                    family = str(head.value).split(":", 1)[0]
                    assert family in HEAD_FAMILIES, (
                        f"{path.name}: unregistered reason family "
                        f"{head.value!r}")
                    checked += 1
                else:
                    # dynamic argument (pg.fail_reason or ...): both operands
                    # must be catalog members — covered by the runtime tests
                    checked += 1
        assert checked >= 4  # the seams this satellite consolidated


# ---------------------------------------------------------------------------
# per-reason fixtures — exact codes


class TestClassifierFixtures:
    def test_fully_eligible(self):
        report = classify_fixture("eligible.bpmn")
        assert report["eligible"] is True
        assert report["definitionReasons"] == []
        assert report["counts"]["host"] == 0
        assert host_reasons_of(report) == {}

    def test_multi_instance(self):
        report = classify_fixture("multi_instance.bpmn")
        assert report["eligible"] is True  # element escapes, definition rides
        assert host_reasons_of(report) == {"each": "multi-instance"}

    def test_timer_cycle(self):
        report = classify_fixture("timer_cycle.bpmn")
        assert host_reasons_of(report) == {"every": "timer-cycle-date"}

    def test_escalation_boundary(self):
        report = classify_fixture("escalation_boundary.bpmn")
        reasons = host_reasons_of(report)
        assert reasons["esc"] == "escalation-boundary"
        assert reasons["scope"] == "boundary-on-nontask"

    def test_unsafe_expression(self):
        report = classify_fixture("unsafe_expression.bpmn")
        assert host_reasons_of(report) == {"t": "unsafe-expression"}

    def test_event_subprocess_body(self):
        report = classify_fixture("esp_message_start.bpmn")
        assert report["eligible"] is True
        reasons = host_reasons_of(report)
        assert reasons["handle"] == "event-subprocess-body"
        assert reasons["esp_e"] == "event-subprocess-body"

    def test_no_none_start_is_definition_level(self):
        report = classify_fixture("no_none_start.bpmn")
        assert report["eligible"] is False
        assert report["definitionReasons"] == ["no-none-start"]
        assert report["counts"]["kernel"] == 0

    def test_native_user_task(self):
        report = classify_fixture("user_task.bpmn")
        assert host_reasons_of(report) == {"review": "user-task"}

    def test_esp_cycle_start_declines_definition(self):
        model = (
            Bpmn.create_executable_process("esp_cyc").start_event("s")
            .service_task("t", job_type="w").end_event("e")
            .event_sub_process("esp")
            .timer_start_event("ts", cycle="R/PT1M")
            .end_event("esp_e")
            .sub_process_done().done())
        report = classify_definition(transform(model))
        assert report["eligible"] is False
        assert report["definitionReasons"] == ["esp-start-unsupported"]

    def test_joint_classification_sees_registry_capacity(self):
        """A shared registry makes the prediction deployment-SET-aware:
        the definition past max_definitions is typed table-set-full (a
        solo classifier cannot see this — the bench parity gate classifies
        jointly for exactly this reason)."""
        from zeebe_tpu.engine.kernel_backend import KernelRegistry

        reg = KernelRegistry(max_definitions=2)
        reports = [
            classify_definition(transform(eligible_def(f"joint_{i}")),
                                definition_key=i + 1, registry=reg)
            for i in range(3)
        ]
        assert [r["eligible"] for r in reports] == [True, True, False]
        assert reports[2]["definitionReasons"] == ["table-set-full"]

    def test_every_fixture_reason_is_in_catalog(self):
        for path in sorted(FIXTURES.glob("*.bpmn")):
            (model,) = parse_bpmn_xml(path.read_text())
            report = classify_definition(transform(model))
            for el in report["elements"]:
                reason = el.get("reason")
                if reason is not None:
                    assert reason in ALL_REASONS, (path.name, el)
            for reason in report["definitionReasons"]:
                assert reason in DEFINITION_REASONS, (path.name, reason)


# ---------------------------------------------------------------------------
# PathAccounting — the one counter home


class TestPathAccounting:
    def test_counts_and_coverage(self):
        acct = PathAccounting("t-unit-1")
        acct.note_kernel("defA", 3)
        acct.note_host("head-sequential:DEPLOYMENT.CREATE")
        acct.note_host("no-quiesce", "defA")
        assert acct.kernel_records == 3
        assert acct.host_records == 2
        assert acct.coverage_ratio() == pytest.approx(0.6)
        snap = acct.snapshot()
        assert snap["perDefinition"]["defA"] == {
            "kernel": 3, "host": 1, "coverageRatio": 0.75,
            "hostReasons": {"no-quiesce": 1},
        }
        assert snap["perDefinition"]["-"]["host"] == 1
        assert {r["reason"] for r in snap["topFallbackReasons"]} == {
            "head-sequential:DEPLOYMENT.CREATE", "no-quiesce"}

    def test_unregistered_reason_is_quarantined(self):
        acct = PathAccounting("t-unit-2")
        acct.note_host("never-registered")
        assert acct.unregistered == {"never-registered": 1}
        # the full string still lands in the Counter (nothing is lost)
        assert acct.reasons["never-registered"] == 1

    def test_registry_metric_children(self):
        from zeebe_tpu.utils.metrics import REGISTRY

        acct = PathAccounting("t-unit-3")
        acct.note_kernel("defZ", 5)
        acct.note_host("token-overflow", "defZ")
        rows = {
            (labels, value)
            for name, _kind, labels, value in REGISTRY.snapshot()
            if name == "zeebe_kernel_records_total"
            and 't-unit-3' in str(labels)
        }
        by_label = {labels: value for labels, value in rows}
        assert any("kernel" in str(k) and v == 5 for k, v in by_label.items())
        assert any("token-overflow" in str(k) and v == 1
                   for k, v in by_label.items())
        gauge = [
            value for name, _kind, labels, value in REGISTRY.snapshot()
            if name == "zeebe_kernel_coverage_ratio"
            and "t-unit-3" in str(labels) and "defZ" in str(labels)
        ]
        assert gauge == [pytest.approx(5 / 6)]

    def test_definition_overflow_folds_into_other(self):
        acct = PathAccounting("t-unit-4")
        for i in range(PathAccounting.MAX_DEFINITIONS):
            acct.note_kernel(f"def{i}")
        acct.note_kernel("one-too-many")
        acct.note_host("no-quiesce", "and-another")
        assert "one-too-many" not in acct.per_definition
        assert acct.per_definition["other"][0] == 1
        assert acct.per_definition["other"][1] == 1

    def test_mark_delta(self):
        acct = PathAccounting("t-unit-5")
        acct.note_kernel("d", 2)
        mark = acct.mark()
        acct.note_kernel("d", 3)
        acct.note_host("geometry-bounds", "d")
        delta = acct.delta_since(mark)
        assert delta["kernel"] == 3 and delta["host"] == 1
        assert delta["perDefinition"]["d"] == {
            "kernel": 3, "host": 1,
            "hostReasons": {"geometry-bounds": 1}}
        assert delta["reasons"] == {"geometry-bounds": 1}


# ---------------------------------------------------------------------------
# the parity gate


class TestParityGate:
    def test_green_on_matching_prediction(self):
        observed = {
            "a": {"kernel": 10, "host": 2,
                  "hostReasons": {"no-quiesce": 1,
                                  "head-sequential:DEPLOYMENT.CREATE": 1}},
            "b": {"kernel": 0, "host": 5,
                  "hostReasons": {
                      "head-not-admittable:PROCESS_INSTANCE_CREATION.CREATE": 5}},
        }
        assert parity_violations({"a": True, "b": False}, observed) == []

    def test_eligible_but_host_routed_is_violation(self):
        observed = {"a": {"kernel": 0, "host": 4, "hostReasons": {
            "head-not-admittable:JOB.COMPLETE": 4}}}
        (violation,) = parity_violations({"a": True}, observed)
        assert "non-runtime" in violation and "a" in violation

    def test_ineligible_but_kernel_routed_is_violation(self):
        observed = {"b": {"kernel": 3, "host": 0, "hostReasons": {}}}
        (violation,) = parity_violations({"b": False}, observed)
        assert "rode the kernel" in violation

    def test_runtime_reasons_never_count_against_prediction(self):
        observed = {"a": {"kernel": 0, "host": 3,
                          "hostReasons": {"no-quiesce": 2,
                                          "geometry-bounds": 1}}}
        assert parity_violations({"a": True}, observed) == []

    def test_undeclared_definitions_are_skipped(self):
        observed = {"-": {"kernel": 0, "host": 9, "hostReasons": {
            "head-sequential:MESSAGE.PUBLISH": 9}}}
        assert parity_violations({"a": True}, observed) == []


# ---------------------------------------------------------------------------
# runtime: accounting + waves through a real kernel partition


def eligible_def(pid="acct_ok"):
    return (
        Bpmn.create_executable_process(pid).start_event("s")
        .service_task("t", job_type="acct_work").end_event("e").done())


def host_forced_def(pid="acct_msgstart"):
    # message-start-only: definition-level no-none-start (kernel declines
    # registration; creations take the sequential path)
    return (
        Bpmn.create_executable_process(pid)
        .message_start_event("ms", "acct_kick")
        .service_task("t", job_type="acct_host_work").end_event("e").done())


class TestRuntimeAccounting:
    def test_mixed_definition_parity_prediction_equals_observation(self):
        """The seeded mixed run: one kernel-eligible and one host-forced
        definition drive both paths; the classifier's prediction must match
        the observed routing (the bench gate's logic, in-tree)."""
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(eligible_def(), host_forced_def())
            acct = h.kernel_backend.accounting
            mark = acct.mark()
            predictions = {
                m.process_id: classify_definition(transform(m))["eligible"]
                for m in (eligible_def(), host_forced_def())
            }
            assert predictions == {"acct_ok": True, "acct_msgstart": False}
            for _ in range(6):
                h.create_instance("acct_ok", {})
            for _ in range(3):
                h.create_instance("acct_msgstart", {})
            h.pump()
            delta = acct.delta_since(mark)
            obs = delta["perDefinition"]
            assert obs["acct_ok"]["kernel"] >= 6
            assert obs["acct_ok"].get("host", 0) == 0
            assert obs["acct_msgstart"]["kernel"] == 0
            assert obs["acct_msgstart"]["host"] >= 3
            assert all(
                r.startswith("head-not-admittable:PROCESS_INSTANCE_CREATION")
                for r in obs["acct_msgstart"]["hostReasons"])
            assert parity_violations(predictions, obs) == []
            # and the gate actually bites: flip the prediction
            assert parity_violations({"acct_msgstart": True}, obs)

        finally:
            h.close()
    def test_no_unregistered_reasons_after_driving(self):
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(eligible_def("acct_clean"), host_forced_def("acct_h2"))
            for _ in range(4):
                h.create_instance("acct_clean", {})
            h.create_instance("acct_h2", {})
            h.pump()
            assert h.kernel_backend.accounting.unregistered == {}

        finally:
            h.close()
    def test_fallback_reasons_alias_preserved(self):
        """BENCH back-compat: kernel.fallback_reasons IS the accounting
        Counter (clear() clears both — the bench reset protocol)."""
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(eligible_def("acct_alias"))
            h.create_instance("acct_alias", {})
            h.pump()
            k = h.kernel_backend
            assert k.fallback_reasons is k.accounting.reasons
            k.fallback_reasons.clear()
            assert sum(k.accounting.reasons.values()) == 0

        finally:
            h.close()
    def test_kernel_wave_events_emitted(self):
        h = EngineHarness(use_kernel_backend=True)
        try:
            events: list[dict] = []
            h.processor.wave_listener = events.append
            h.deploy(eligible_def("acct_wave"))
            for _ in range(8):
                h.create_instance("acct_wave", {})
            h.pump()
            assert events, "no kernel_wave event emitted"
            ev = events[0]
            assert ev["waves"] >= 1
            assert ev["commands"] >= 1
            assert ev["kernelRecords"] >= 1
            assert 0.0 <= ev["coverageRatio"] <= 1.0
            assert "avgWave" in ev and "chunks" in ev

        finally:
            h.close()
    def test_dispatch_overlap_gauge_set(self):
        from zeebe_tpu.utils.metrics import REGISTRY

        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(eligible_def("acct_overlap"))
            for _ in range(4):
                h.create_instance("acct_overlap", {})
            h.pump()
            values = [
                value for name, _k, labels, value in REGISTRY.snapshot()
                if name == "zeebe_kernel_dispatch_overlap_ratio"
            ]
            assert values, "overlap gauge never set"
            assert all(0.0 <= v <= 1.0 for v in values)

        finally:
            h.close()
    def test_registry_decline_reason_typed(self):
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(host_forced_def("acct_decline"))
            h.create_instance("acct_decline", {})
            h.pump()
            reg = h.kernel_backend.registry
            keys = list(reg._ineligible)
            assert keys, "definition never consulted the registry"
            assert reg.decline_reason(keys[0]) == "no-none-start"


        finally:
            h.close()
# ---------------------------------------------------------------------------
# bounded flight dumps (ISSUE 13 satellite)


class TestBoundedFlightDumps:
    def test_dump_truncates_oldest_first(self, tmp_path):
        from zeebe_tpu.observability.flight_recorder import FlightRecorder

        rec = FlightRecorder("n1", tmp_path, capacity=4096,
                             max_dump_bytes=8_192)
        for i in range(2_000):
            # non-ASCII padding: the cap must hold in BYTES on disk
            # whatever the serializer's escaping does with it
            rec.record(1, "noise", seq=i, pad="ü" * 20)
        rec.record(1, "the_crash", seq=999_999)
        path = rec.dump("test", force=True)
        assert path is not None
        assert path.stat().st_size <= 8_192
        body = path.read_text()
        payload = json.loads(body)
        assert payload["truncatedEntries"] > 0
        events = payload["partitions"]["1"]
        # newest evidence survives; the oldest entries were dropped
        assert events[-1]["kind"] == "the_crash"
        assert events[0]["seq"] > 0

    def test_small_dump_untouched(self, tmp_path):
        from zeebe_tpu.observability.flight_recorder import FlightRecorder

        rec = FlightRecorder("n1", tmp_path, max_dump_bytes=262_144)
        rec.record(1, "only_event")
        path = rec.dump("test", force=True)
        payload = json.loads(path.read_text())
        assert "truncatedEntries" not in payload
        assert len(payload["partitions"]["1"]) == 1

    def test_env_knob_controls_cap(self, tmp_path, monkeypatch):
        from zeebe_tpu.observability import flight_recorder as fr

        monkeypatch.setenv("ZEEBE_FLIGHT_MAXDUMPBYTES", "4096")
        rec = fr.FlightRecorder("n1", tmp_path)
        assert rec.max_dump_bytes == 4096


# ---------------------------------------------------------------------------
# CLI surfaces


class TestEligibilityCli:
    def test_file_mode_json(self, tmp_path, capsys):
        from zeebe_tpu import cli

        rc = cli.main(["eligibility",
                       str(FIXTURES / "multi_instance.bpmn")])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        (report,) = payload["definitions"]
        assert report["bpmnProcessId"] == "elig_mi"
        assert host_reasons_of(report) == {"each": "multi-instance"}

    def test_file_mode_output_artifact(self, tmp_path, capsys):
        from zeebe_tpu import cli

        out = tmp_path / "report.json"
        rc = cli.main(["eligibility", str(FIXTURES / "eligible.bpmn"),
                       "-o", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["definitions"][0]["eligible"] is True

    def test_deployed_mode_over_harness_journal(self, tmp_path, capsys):
        from zeebe_tpu import cli

        h = EngineHarness(directory=tmp_path, use_kernel_backend=True)
        try:
            h.deploy(eligible_def("cli_dep_ok"),
                     host_forced_def("cli_dep_host"))
            h.pump()
        finally:
            h.close()
        rc = cli.main(["eligibility", "--deployed",
                       "--data-dir", str(tmp_path)])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        by_id = {r["bpmnProcessId"]: r for r in payload["definitions"]}
        assert by_id["cli_dep_ok"]["eligible"] is True
        assert by_id["cli_dep_host"]["eligible"] is False
        assert by_id["cli_dep_host"]["definitionReasons"] == ["no-none-start"]

    def test_eligibility_doc_check_green(self, capsys):
        from zeebe_tpu import cli

        assert cli.main(["eligibility-doc", "--check"]) == 0

    def test_top_renders_kernel_coverage_section(self):
        from zeebe_tpu.cli import _render_top

        frame = _render_top({
            "clusterSize": 1, "partitionsCount": 1, "health": "HEALTHY",
            "topology": {"version": 1},
            "brokers": [{
                "nodeId": "broker-0", "health": "HEALTHY",
                "partitions": {"1": {
                    "role": "leader", "term": 1,
                    "kernelCoverage": {
                        "kernelRecords": 900, "hostRecords": 100,
                        "coverageRatio": 0.9,
                        "dominantHostReason":
                            "head-sequential:DEPLOYMENT.CREATE"},
                }},
            }],
        })
        assert "KERNEL" in frame
        assert "90.0%" in frame
        assert "head-sequential:DEPLOYMENT.CREATE" in frame

    def test_health_carries_kernel_coverage(self):
        """registry → accounting → partition /health block end-to-end
        (cluster-status rows share the same accounting object)."""
        h = EngineHarness(use_kernel_backend=True)
        try:
            h.deploy(eligible_def("health_cov"))
            for _ in range(3):
                h.create_instance("health_cov", {})
            h.pump()
            snap = h.kernel_backend.accounting.snapshot()
            assert snap["kernelRecords"] >= 3
            assert 0.0 <= snap["coverageRatio"] <= 1.0
            assert "health_cov" in snap["perDefinition"]

        finally:
            h.close()