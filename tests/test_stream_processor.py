"""Stream platform tests with a fake RecordProcessor (reference strategy §4.4:
stream-platform/src/test with fake processors).

The fake processor implements a tiny counter machine: INCREMENT commands produce
INCREMENTED events which add to a counter in state; a CHAIN command produces a
follow-up INCREMENT command (exercising batch processing); a BOOM command raises.
"""

import pytest

from zeebe_tpu.journal import SegmentedJournal
from zeebe_tpu.logstreams import LogAppendEntry, LogStream
from zeebe_tpu.protocol import Record, RecordType, ValueType, command, event
from zeebe_tpu.protocol.intent import SignalIntent
from zeebe_tpu.state import ColumnFamilyCode, ZbDb
from zeebe_tpu.stream import (
    Phase,
    ProcessingResultBuilder,
    RecordProcessor,
    StreamProcessor,
    StreamProcessorMode,
)

# We borrow the SIGNAL value type for the fake machine; intents:
INCREMENT = SignalIntent.BROADCAST  # command
INCREMENTED = SignalIntent.BROADCASTED  # event


class CounterProcessor(RecordProcessor):
    """Fake machine: counter in state; op in the value drives behavior."""

    def __init__(self, db: ZbDb):
        self.cf = db.column_family(ColumnFamilyCode.DEFAULT)
        self.processed_ops = []

    def accepts(self, value_type):
        return value_type == ValueType.SIGNAL

    def process(self, logged, result):
        op = logged.record.value.get("op", "inc")
        self.processed_ops.append(op)
        if op == "boom":
            raise RuntimeError("kaboom")
        if op == "chain":
            # produce a follow-up command (processed in-batch if budget allows)
            result.append_record(
                command(ValueType.SIGNAL, INCREMENT, {"op": "inc", "amount": 10})
            )
            return
        amount = logged.record.value.get("amount", 1)
        ev = event(ValueType.SIGNAL, INCREMENTED, {"amount": amount})
        self._apply(ev)
        result.append_record(ev)
        if logged.record.request_id >= 0:
            result.with_response(ev, logged.record.request_stream_id, logged.record.request_id)

    def _apply(self, ev: Record):
        count = self.cf.get(("counter",)) or 0
        self.cf.put(("counter",), count + ev.value["amount"])

    def replay(self, logged):
        self._apply(logged.record)

    def counter(self, db):
        with db.transaction():
            return self.cf.get(("counter",)) or 0


def make_env(tmp_path, mode=StreamProcessorMode.PROCESSING, max_batch=100, subdir="log"):
    journal = SegmentedJournal(tmp_path / subdir)
    stream = LogStream(journal, partition_id=1, clock=lambda: 1000)
    db = ZbDb()
    proc = CounterProcessor(db)
    responses = []
    sp = StreamProcessor(
        stream, db, proc, mode=mode, max_commands_in_batch=max_batch,
        response_sink=responses.append,
    )
    return journal, stream, db, proc, sp, responses


def write_cmd(stream, op="inc", amount=1, request_id=-1):
    return stream.writer.try_write(
        [LogAppendEntry(command(ValueType.SIGNAL, INCREMENT, {"op": op, "amount": amount},
                                request_id=request_id, request_stream_id=9))]
    )


class TestProcessing:
    def test_command_produces_event_and_state(self, tmp_path):
        journal, stream, db, proc, sp, responses = make_env(tmp_path)
        sp.start()
        write_cmd(stream, amount=5)
        steps = sp.run_until_idle()
        assert steps == 1
        assert proc.counter(db) == 5
        events = [r for r in stream.new_reader() if r.record.is_event]
        assert len(events) == 1
        assert events[0].record.value["amount"] == 5
        assert events[0].source_position == 1
        journal.close()

    def test_response_delivered(self, tmp_path):
        journal, stream, db, proc, sp, responses = make_env(tmp_path)
        sp.start()
        write_cmd(stream, amount=2, request_id=77)
        sp.run_until_idle()
        assert len(responses) == 1
        assert responses[0].request_id == 77
        assert responses[0].record.value["amount"] == 2
        journal.close()

    def test_follow_up_command_processed_in_batch(self, tmp_path):
        journal, stream, db, proc, sp, responses = make_env(tmp_path)
        sp.start()
        write_cmd(stream, op="chain")
        sp.run_until_idle()
        assert proc.counter(db) == 10
        recs = list(stream.new_reader())
        # batch: chained INCREMENT command (processed) + INCREMENTED event
        cmds = [r for r in recs if r.record.is_command and r.position > 1]
        assert len(cmds) == 1 and cmds[0].processed
        assert proc.processed_ops == ["chain", "inc"]
        journal.close()

    def test_batch_budget_defers_follow_up(self, tmp_path):
        journal, stream, db, proc, sp, responses = make_env(tmp_path, max_batch=1)
        sp.start()
        write_cmd(stream, op="chain")
        sp.run_until_idle()
        # follow-up command written unprocessed, then processed as its own step
        assert proc.counter(db) == 10
        recs = list(stream.new_reader())
        follow_cmds = [r for r in recs if r.record.is_command and r.position > 1]
        assert len(follow_cmds) == 1 and not follow_cmds[0].processed
        journal.close()


class TestErrorHandling:
    def test_error_rolls_back_and_rejects(self, tmp_path):
        journal, stream, db, proc, sp, responses = make_env(tmp_path)
        sp.start()
        write_cmd(stream, op="boom", request_id=5)
        write_cmd(stream, amount=3, request_id=6)
        sp.run_until_idle()
        assert proc.counter(db) == 3  # boom rolled back, next command fine
        rejections = [r for r in stream.new_reader() if r.record.is_rejection]
        assert len(rejections) == 1
        assert "kaboom" in rejections[0].record.rejection_reason
        assert len(responses) == 2  # rejection response + ok response
        assert responses[0].record.is_rejection
        journal.close()


class TestReplay:
    def test_replay_reaches_identical_state(self, tmp_path):
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        for amount in (1, 2, 3, 4):
            write_cmd(stream, amount=amount)
        write_cmd(stream, op="chain")
        sp.run_until_idle()
        assert proc.counter(db) == 20
        journal.close()

        # fresh db, same log → replay-only must land on the same state
        journal2 = SegmentedJournal(tmp_path / "log")
        stream2 = LogStream(journal2, partition_id=1)
        db2 = ZbDb()
        proc2 = CounterProcessor(db2)
        sp2 = StreamProcessor(stream2, db2, proc2, mode=StreamProcessorMode.REPLAY)
        sp2.start()
        sp2.run_until_idle()
        assert proc2.counter(db2) == 20
        assert sp2.last_processed_position == sp.last_processed_position
        journal2.close()

    def test_restart_does_not_reprocess(self, tmp_path):
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        write_cmd(stream, amount=7)
        sp.run_until_idle()
        journal.close()

        # restart with *fresh state* (no snapshot): replay rebuilds, then
        # processing resumes without double-applying
        journal2 = SegmentedJournal(tmp_path / "log")
        stream2 = LogStream(journal2, partition_id=1)
        db2 = ZbDb()
        proc2 = CounterProcessor(db2)
        sp2 = StreamProcessor(stream2, db2, proc2)
        sp2.start()
        sp2.run_until_idle()
        assert proc2.counter(db2) == 7
        assert proc2.processed_ops == []  # nothing reprocessed
        # new commands still work
        write_cmd(stream2, amount=1)
        sp2.run_until_idle()
        assert proc2.counter(db2) == 8
        journal2.close()

    def test_poison_record_fails_processor_without_propagating(self, tmp_path):
        """A throwing applier during replay must FAIL this processor (health
        turns unhealthy, replay stops) — not raise out of the pump and take
        every co-hosted partition down with it."""
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        write_cmd(stream, amount=2)
        sp.run_until_idle()

        follower_db = ZbDb()
        follower_proc = CounterProcessor(follower_db)
        follower = StreamProcessor(stream, follower_db, follower_proc,
                                   mode=StreamProcessorMode.REPLAY)
        follower.start()
        assert follower_proc.counter(follower_db) == 2

        def poison_replay(logged):
            raise RuntimeError("poison record")

        follower_proc.replay = poison_replay
        write_cmd(stream, amount=3)
        sp.run_until_idle()
        applied = follower.replay_available()  # must not raise
        assert applied == 0
        assert follower.phase == Phase.FAILED
        # failed processor stays down (no retry storm) and state is unchanged
        assert follower.replay_available() == 0
        assert follower_proc.counter(follower_db) == 2
        journal.close()

    def test_poison_record_during_recovery_blocks_processing(self, tmp_path):
        """A poison record hit during start()'s recovery replay must leave the
        processor FAILED — becoming a leader over half-replayed state would
        reprocess logged commands and duplicate their events."""
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        write_cmd(stream, amount=2)
        sp.run_until_idle()
        journal.close()

        journal2 = SegmentedJournal(tmp_path / "log")
        stream2 = LogStream(journal2, partition_id=1)
        db2 = ZbDb()
        proc2 = CounterProcessor(db2)
        proc2.replay = lambda logged: (_ for _ in ()).throw(
            RuntimeError("poison record"))
        sp2 = StreamProcessor(stream2, db2, proc2)
        sp2.start()  # must not raise
        assert sp2.phase == Phase.FAILED
        with pytest.raises(RuntimeError, match="cannot process"):
            sp2.process_next()
        journal2.close()

    def test_follower_mode_applies_continuously(self, tmp_path):
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        write_cmd(stream, amount=2)
        sp.run_until_idle()

        follower_db = ZbDb()
        follower_proc = CounterProcessor(follower_db)
        follower = StreamProcessor(stream, follower_db, follower_proc, mode=StreamProcessorMode.REPLAY)
        follower.start()
        assert follower.phase == Phase.REPLAY
        assert follower_proc.counter(follower_db) == 2
        # leader processes more; follower catches up incrementally
        write_cmd(stream, amount=3)
        sp.run_until_idle()
        follower.run_until_idle()
        assert follower_proc.counter(follower_db) == 5
        journal.close()


class TestScheduleService:
    def test_due_tasks_write_commands(self, tmp_path):
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        fired = []
        sp.schedule_service.run_at(
            500, lambda: (fired.append(1), [command(ValueType.SIGNAL, INCREMENT, {"amount": 4})])[1]
        )
        sp.run_until_idle()
        assert fired == [1]
        assert proc.counter(db) == 4
        journal.close()

    def test_future_tasks_not_run(self, tmp_path):
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        sp.schedule_service.run_at(99999, lambda: [])
        sp.run_until_idle()
        assert sp.schedule_service.next_due_millis == 99999
        journal.close()

    def test_cancelled_task_not_run(self, tmp_path):
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        handle = sp.schedule_service.run_at(500, lambda: [command(ValueType.SIGNAL, INCREMENT, {})])
        handle.cancel()
        sp.run_until_idle()
        assert proc.counter(db) == 0
        journal.close()


class TestSnapshotRecovery:
    def test_recover_from_snapshot_does_not_reapply_events(self, tmp_path):
        """Regression: replay must skip events whose source position is <= the
        snapshot's last-processed position (else state double-applies)."""
        journal, stream, db, proc, sp, _ = make_env(tmp_path)
        sp.start()
        for amount in (1, 2, 3):
            write_cmd(stream, amount=amount)
        sp.run_until_idle()
        snapshot_bytes = db.to_snapshot_bytes()
        # post-snapshot traffic
        write_cmd(stream, amount=10)
        sp.run_until_idle()
        journal.close()

        from zeebe_tpu.state import ZbDb as _ZbDb

        journal2 = SegmentedJournal(tmp_path / "log")
        stream2 = LogStream(journal2, partition_id=1)
        db2 = _ZbDb.from_snapshot_bytes(snapshot_bytes)
        proc2 = CounterProcessor(db2)
        sp2 = StreamProcessor(stream2, db2, proc2)
        sp2.start()
        sp2.run_until_idle()
        assert proc2.counter(db2) == 16  # 1+2+3 (snapshot) + 10 (replayed)
        assert proc2.processed_ops == []  # replay only, no reprocessing
        journal2.close()


class TestRejectionReplay:
    def test_rejection_only_step_not_reprocessed_after_restart(self, tmp_path):
        """Regression: a command whose only output was a rejection must not be
        reprocessed on restart (rejections carry the source backlink too)."""
        journal, stream, db, proc, sp, responses = make_env(tmp_path)
        sp.start()
        write_cmd(stream, op="boom", request_id=5)
        sp.run_until_idle()
        n_records = sum(1 for _ in stream.new_reader())
        journal.close()

        journal2 = SegmentedJournal(tmp_path / "log")
        stream2 = LogStream(journal2, partition_id=1)
        db2 = ZbDb()
        proc2 = CounterProcessor(db2)
        responses2 = []
        sp2 = StreamProcessor(stream2, db2, proc2, response_sink=responses2.append)
        sp2.start()
        sp2.run_until_idle()
        assert proc2.processed_ops == []  # not reprocessed
        assert responses2 == []  # no duplicate client response
        assert sum(1 for _ in stream2.new_reader()) == n_records  # no new records
        journal2.close()
