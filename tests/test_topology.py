"""Dynamic cluster topology: gossiped versioned state + change operations.

Reference: topology/…/ClusterTopologyManager.java, changes/ (MemberJoin/
PartitionJoin/PartitionLeave appliers), gossip/ClusterTopologyGossiper.java.
The VERDICT round-1 acceptance test: add a broker to a RUNNING cluster and
move a partition onto it, with processing continuing on the moved partition.
"""

from __future__ import annotations

import pytest

from zeebe_tpu.broker import InProcessCluster
from zeebe_tpu.cluster.topology import (
    ACTIVE,
    ClusterTopology,
    TopologyManager,
)
from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
from zeebe_tpu.protocol import ValueType, command
from zeebe_tpu.protocol.intent import DeploymentIntent, ProcessInstanceCreationIntent


def one_task():
    return (
        Bpmn.create_executable_process("p")
        .start_event("s").service_task("t", job_type="w").end_event("e").done()
    )


def deploy_cmd(model):
    return command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
        "resources": [{"resourceName": "p.bpmn", "resource": to_bpmn_xml(model)}],
    })


def create_cmd():
    return command(
        ValueType.PROCESS_INSTANCE_CREATION, ProcessInstanceCreationIntent.CREATE,
        {"bpmnProcessId": "p", "version": -1, "variables": {}},
    )


def run_until(cluster, predicate, rounds=60, millis=200) -> None:
    for _ in range(rounds):
        cluster.run(millis)
        if predicate():
            return
    pytest.fail("condition not reached")


class TestTopologyState:
    def test_initial_topology_from_distribution(self):
        topo = ClusterTopology.initial({1: ["a", "b"], 2: ["b", "c"]},
                                       ["a", "b", "c"])
        assert topo.partition_members(1) == ["a", "b"]
        assert topo.partition_members(2) == ["b", "c"]
        assert topo.members["a"]["state"] == ACTIVE
        assert topo.version == 0

    def test_gossip_merges_higher_version(self):
        class FakeMember:
            def __init__(self, props):
                self.properties = props

        class FakeMembership:
            def __init__(self):
                self.members = {}
                self.properties = {}

            def set_property(self, key, value):
                self.properties[key] = value

        ms = FakeMembership()
        mgr = TopologyManager("a", ms, lambda *a: None, lambda *a: None,
                              lambda pid: None, lambda *a: None)
        mgr.bootstrap({1: ["a"]}, ["a"])
        newer = mgr.topology.copy()
        newer.doc["version"] = 7
        newer.doc["members"]["b"] = {"state": ACTIVE, "partitions": {}}
        ms.members["b"] = FakeMember({TopologyManager.GOSSIP_PROPERTY: newer.doc})
        mgr.tick()
        assert mgr.topology.version == 7
        assert "b" in mgr.topology.members

    def test_propose_rejects_concurrent_change(self):
        class FakeMembership:
            members: dict = {}
            properties: dict = {}

            def set_property(self, key, value):
                self.properties[key] = value

        mgr = TopologyManager("a", FakeMembership(), lambda *a: None,
                              lambda *a: None, lambda pid: None, lambda *a: None)
        mgr.bootstrap({1: ["a"]}, ["a"])
        assert mgr.propose([mgr.join_member("b")])
        assert not mgr.propose([mgr.join_member("c")])


class TestClusterScaleOut:
    def test_add_broker_and_move_partition(self):
        """The acceptance scenario: a new broker joins a RUNNING cluster, a
        partition replica moves onto it (join new → leave old), the raft
        group reconfigures, and processing continues with prior state."""
        c = InProcessCluster(broker_count=2, partition_count=2,
                             replication_factor=2)
        try:
            c.await_leaders()
            c.write_command(1, deploy_cmd(one_task()))
            c.write_command(2, deploy_cmd(one_task()))
            c.write_command(2, create_cmd())

            new = c.add_broker("broker-2")
            run_until(c, lambda: any(
                m.member_id == "broker-2"
                for m in c.brokers["broker-0"].membership.alive_members()
            ))

            # move partition 2's replica from broker-1 onto broker-2
            coordinator = c.brokers["broker-0"].topology
            assert coordinator.propose([
                coordinator.join_member("broker-2"),
                coordinator.join_partition("broker-2", 2, priority=5),
                coordinator.leave_partition("broker-1", 2),
            ])

            run_until(c, lambda: (
                2 in new.partitions
                and 2 not in c.brokers["broker-1"].partitions
                and all(b.topology.topology.change is None
                        for b in c.brokers.values())
            ), rounds=120)

            # the raft group is exactly the new replica set
            for b in ("broker-0", "broker-2"):
                raft = c.brokers[b].partitions[2].raft
                assert raft.members == ["broker-0", "broker-2"]

            # the moved partition still has the deployed definition and keeps
            # processing: create another instance on it
            run_until(c, lambda: c.leader_broker(2) is not None)
            position = c.write_command(2, create_cmd())
            assert position is not None
            leader = c.leader_broker(2).partitions[2]
            # two instances total (one before the move, one after)
            instances = [
                logged for logged in leader.stream.new_reader(1)
                if logged.record.value_type == ValueType.PROCESS_INSTANCE_CREATION
                and logged.record.is_event
            ]
            assert len(instances) == 2

            # topology document converged everywhere with broker-2 active
            for b in c.brokers.values():
                doc = b.topology.topology
                assert doc.members["broker-2"]["state"] == ACTIVE
                assert "2" in doc.members["broker-2"]["partitions"]
                assert "2" not in doc.members["broker-1"].get("partitions", {})
        finally:
            c.close()

    def test_follower_replica_leave(self):
        """Leaving a FOLLOWER replica: the leader reconfigures it out and the
        leaver learns of its removal (config entry or the leader's
        confirmation reply), shrinking the group without wedging the plan."""
        c = InProcessCluster(broker_count=3, partition_count=1,
                             replication_factor=3)
        try:
            c.await_leaders()
            c.write_command(1, deploy_cmd(one_task()))
            leader_broker = c.leader_broker(1)
            follower = next(
                b for b in c.brokers.values()
                if b is not leader_broker and 1 in b.partitions
            )
            coordinator = c.brokers["broker-0"].topology
            assert coordinator.propose([
                coordinator.leave_partition(follower.cfg.node_id, 1),
            ])
            run_until(c, lambda: (
                1 not in follower.partitions
                and all(b.topology.topology.change is None
                        for b in c.brokers.values())
            ), rounds=120)
            expected = sorted(
                b.cfg.node_id for b in c.brokers.values() if b is not follower
            )
            run_until(c, lambda: c.leader_broker(1) is not None)
            assert c.leader_broker(1).partitions[1].raft.members == expected
            # processing continues on the shrunk group
            assert c.write_command(1, create_cmd()) is not None
        finally:
            c.close()

    def test_moved_partition_survives_broker_restart(self, tmp_path):
        """The topology document persists: a broker restarted after a
        PARTITION_JOIN must restart the moved replica, not just the static
        bootstrap distribution."""
        from zeebe_tpu.broker import Broker, BrokerCfg

        c = InProcessCluster(broker_count=2, partition_count=2,
                             replication_factor=2, directory=tmp_path)
        try:
            c.await_leaders()
            new = c.add_broker("broker-2")
            run_until(c, lambda: any(
                m.member_id == "broker-2"
                for m in c.brokers["broker-0"].membership.alive_members()
            ))
            coordinator = c.brokers["broker-0"].topology
            assert coordinator.propose([
                coordinator.join_member("broker-2"),
                coordinator.join_partition("broker-2", 2, priority=5),
            ])
            run_until(c, lambda: (
                2 in new.partitions
                and all(b.topology.topology.change is None
                        for b in c.brokers.values())
            ), rounds=120)

            # restart broker-2 from its directory: the moved replica returns
            cfg = new.cfg
            new.close()
            del c.brokers["broker-2"]
            c.net.leave("broker-2") if hasattr(c.net, "leave") else None
            restarted = Broker(cfg, c.net.join("broker-2"),
                               directory=tmp_path / "broker-2",
                               clock_millis=c.clock)
            c.brokers["broker-2"] = restarted
            assert 2 in restarted.partitions
            assert restarted.partitions[2].raft.members == sorted(
                set(["broker-0", "broker-1", "broker-2"])
            ) or "broker-2" in restarted.partitions[2].raft.members
            run_until(c, lambda: c.leader_broker(2) is not None)
        finally:
            c.close()

    def test_member_leave_requires_empty_member(self):
        c = InProcessCluster(broker_count=2, partition_count=1,
                             replication_factor=1)
        try:
            c.await_leaders()
            holder = next(
                b for b in c.brokers.values() if 1 in b.partitions
            )
            coordinator = c.brokers["broker-0"].topology
            assert coordinator.propose([coordinator.leave_member(holder.cfg.node_id)])
            # the member still hosts a partition: the operation must not
            # complete (plan stays in flight)
            c.run(2_000)
            assert coordinator.topology.change is not None or (
                holder.topology.topology.members[holder.cfg.node_id]["state"] != "left"
            )
        finally:
            c.close()
