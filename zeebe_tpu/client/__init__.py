"""Client library: ZeebeClient-equivalent fluent API + job worker (SURVEY §2.11)."""

from zeebe_tpu.client.client import ZeebeTpuClient
from zeebe_tpu.client.worker import JobWorker

__all__ = ["ZeebeTpuClient", "JobWorker"]
