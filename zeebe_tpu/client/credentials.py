"""Client credentials: attach OAuth bearer tokens to every gRPC call.

Reference: clients/java/…/impl/oauth/OAuthCredentialsProvider.java (and the
Go client's equivalent) — the standard OAuth2 client-credentials flow against
a token endpoint, with the token cached until shortly before expiry and the
`Authorization: Bearer <token>` metadata attached per call. Environment
binding mirrors the reference client:

  ZEEBE_CLIENT_ID / ZEEBE_CLIENT_SECRET
  ZEEBE_AUTHORIZATION_SERVER_URL
  ZEEBE_TOKEN_AUDIENCE
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import urllib.parse
import urllib.request
from typing import Any

import grpc


class CredentialsProvider:
    """Interface: a bearer token per call (empty string = anonymous)."""

    def token(self) -> str:
        raise NotImplementedError


class StaticCredentialsProvider(CredentialsProvider):
    def __init__(self, token: str) -> None:
        self._token = token

    def token(self) -> str:
        return self._token


class OAuthCredentialsProvider(CredentialsProvider):
    """Client-credentials flow with expiry-aware caching (refreshes when
    less than ``refresh_slack_s`` of lifetime remains)."""

    def __init__(self, authorization_server_url: str, client_id: str,
                 client_secret: str, audience: str | None = None,
                 refresh_slack_s: float = 30.0) -> None:
        self.url = authorization_server_url
        self.client_id = client_id
        self.client_secret = client_secret
        self.audience = audience
        self.refresh_slack_s = refresh_slack_s
        self._lock = threading.Lock()
        self._token = ""
        self._expires_at = 0.0

    @classmethod
    def from_env(cls) -> "OAuthCredentialsProvider | None":
        url = os.environ.get("ZEEBE_AUTHORIZATION_SERVER_URL")
        client_id = os.environ.get("ZEEBE_CLIENT_ID")
        if not url or not client_id:
            return None
        return cls(url, client_id,
                   os.environ.get("ZEEBE_CLIENT_SECRET", ""),
                   audience=os.environ.get("ZEEBE_TOKEN_AUDIENCE"))

    def token(self) -> str:
        with self._lock:
            if self._token and time.time() < self._expires_at - self.refresh_slack_s:
                return self._token
            form = {
                "grant_type": "client_credentials",
                "client_id": self.client_id,
                "client_secret": self.client_secret,
            }
            if self.audience:
                form["audience"] = self.audience
            request = urllib.request.Request(
                self.url,
                data=urllib.parse.urlencode(form).encode("ascii"),
                headers={"Content-Type": "application/x-www-form-urlencoded"},
            )
            with urllib.request.urlopen(request, timeout=10) as response:
                body = json.loads(response.read())
            self._token = body["access_token"]
            self._expires_at = time.time() + float(body.get("expires_in", 300))
            return self._token


class _BearerCallDetails(
    # structured clone of grpc.ClientCallDetails with metadata replaced
    collections.namedtuple(
        "_BearerCallDetails",
        ("method", "timeout", "metadata", "credentials",
         "wait_for_ready", "compression"),
    ),
    grpc.ClientCallDetails,
):
    pass


class _AuthInterceptor(grpc.UnaryUnaryClientInterceptor,
                       grpc.UnaryStreamClientInterceptor):
    def __init__(self, provider: CredentialsProvider) -> None:
        self.provider = provider

    def _with_token(self, details: Any) -> Any:
        token = self.provider.token()
        if not token:
            return details
        metadata = list(details.metadata or ())
        metadata.append(("authorization", f"Bearer {token}"))
        return _BearerCallDetails(
            details.method, details.timeout, metadata, details.credentials,
            getattr(details, "wait_for_ready", None),
            getattr(details, "compression", None),
        )

    def intercept_unary_unary(self, continuation, details, request):
        return continuation(self._with_token(details), request)

    def intercept_unary_stream(self, continuation, details, request):
        return continuation(self._with_token(details), request)


def authenticated_channel(channel: grpc.Channel,
                          provider: CredentialsProvider) -> grpc.Channel:
    """Wrap a channel so every call carries the provider's bearer token."""
    return grpc.intercept_channel(channel, _AuthInterceptor(provider))
