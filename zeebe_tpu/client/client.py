"""Fluent client over the gateway gRPC API.

Reference: clients/java/src/main/java/io/camunda/zeebe/client/ZeebeClient.java
— one fluent command builder per rpc (api/command/*), variables as JSON,
worker subscription builder. The builder step chain mirrors the Java client's
(newCreateInstanceCommand().bpmnProcessId(x).latestVersion().variables(v)
.send().join()) in pythonic form with keyword arguments + a .send() terminal.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Iterator

import grpc

from zeebe_tpu.gateway.proto import gateway_pb2 as pb

_SERVICE = "gateway_protocol.Gateway"


def _method(channel, name, req_cls, resp_cls, streaming=False):
    path = f"/{_SERVICE}/{name}"
    if streaming:
        return channel.unary_stream(
            path, request_serializer=req_cls.SerializeToString,
            response_deserializer=resp_cls.FromString,
        )
    return channel.unary_unary(
        path, request_serializer=req_cls.SerializeToString,
        response_deserializer=resp_cls.FromString,
    )


@dataclass
class Topology:
    cluster_size: int
    partitions_count: int
    replication_factor: int
    gateway_version: str
    brokers: list[dict] = field(default_factory=list)


@dataclass
class ProcessInstance:
    process_definition_key: int
    bpmn_process_id: str
    version: int
    process_instance_key: int
    variables: dict | None = None


@dataclass
class ActivatedJob:
    key: int
    type: str
    process_instance_key: int
    bpmn_process_id: str
    element_id: str
    element_instance_key: int
    custom_headers: dict
    worker: str
    retries: int
    deadline: int
    variables: dict


def _job_of(j) -> ActivatedJob:
    return ActivatedJob(
        key=j.key, type=j.type, process_instance_key=j.processInstanceKey,
        bpmn_process_id=j.bpmnProcessId, element_id=j.elementId,
        element_instance_key=j.elementInstanceKey,
        custom_headers=json.loads(j.customHeaders or "{}"),
        worker=j.worker, retries=j.retries, deadline=j.deadline,
        variables=json.loads(j.variables or "{}"),
    )


class ZeebeTpuClient:
    """Synchronous client; one instance per gateway address."""

    def __init__(self, address: str, channel: grpc.Channel | None = None,
                 access_token: str | None = None,
                 default_tenant: str = "",
                 credentials_provider=None) -> None:
        """Credential precedence (mirrors the reference client):
        an explicit ``credentials_provider`` wins; else an explicit
        ``access_token`` (static bearer); else the ZEEBE_CLIENT_ID /
        ZEEBE_CLIENT_SECRET / ZEEBE_AUTHORIZATION_SERVER_URL environment.
        Pass ``credentials_provider=False`` to force anonymous calls."""
        from zeebe_tpu.client.credentials import (
            OAuthCredentialsProvider,
            StaticCredentialsProvider,
            authenticated_channel,
        )

        self.address = address
        self.channel = channel or grpc.insecure_channel(address)
        if credentials_provider is None:
            if access_token:
                credentials_provider = StaticCredentialsProvider(access_token)
            else:
                credentials_provider = OAuthCredentialsProvider.from_env()
        if credentials_provider:
            self.channel = authenticated_channel(self.channel,
                                                 credentials_provider)
        # tenant stamped on tenant-scoped commands unless overridden per call
        self.default_tenant = default_tenant
        c = self.channel
        self._topology = _method(c, "Topology", pb.TopologyRequest, pb.TopologyResponse)
        self._deploy = _method(c, "DeployResource", pb.DeployResourceRequest, pb.DeployResourceResponse)
        self._create = _method(c, "CreateProcessInstance", pb.CreateProcessInstanceRequest, pb.CreateProcessInstanceResponse)
        self._create_with_result = _method(c, "CreateProcessInstanceWithResult", pb.CreateProcessInstanceWithResultRequest, pb.CreateProcessInstanceWithResultResponse)
        self._cancel = _method(c, "CancelProcessInstance", pb.CancelProcessInstanceRequest, pb.CancelProcessInstanceResponse)
        self._publish = _method(c, "PublishMessage", pb.PublishMessageRequest, pb.PublishMessageResponse)
        self._activate = _method(c, "ActivateJobs", pb.ActivateJobsRequest, pb.ActivateJobsResponse, streaming=True)
        self._stream_jobs = _method(c, "StreamActivatedJobs", pb.StreamActivatedJobsRequest, pb.ActivatedJob, streaming=True)
        self._complete = _method(c, "CompleteJob", pb.CompleteJobRequest, pb.CompleteJobResponse)
        self._fail = _method(c, "FailJob", pb.FailJobRequest, pb.FailJobResponse)
        self._throw = _method(c, "ThrowError", pb.ThrowErrorRequest, pb.ThrowErrorResponse)
        self._retries = _method(c, "UpdateJobRetries", pb.UpdateJobRetriesRequest, pb.UpdateJobRetriesResponse)
        self._update_timeout = _method(c, "UpdateJobTimeout", pb.UpdateJobTimeoutRequest, pb.UpdateJobTimeoutResponse)
        self._set_vars = _method(c, "SetVariables", pb.SetVariablesRequest, pb.SetVariablesResponse)
        self._resolve = _method(c, "ResolveIncident", pb.ResolveIncidentRequest, pb.ResolveIncidentResponse)
        self._signal = _method(c, "BroadcastSignal", pb.BroadcastSignalRequest, pb.BroadcastSignalResponse)

    def close(self) -> None:
        self.channel.close()

    def __enter__(self) -> "ZeebeTpuClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- cluster ---------------------------------------------------------------

    def topology(self) -> Topology:
        r = self._topology(pb.TopologyRequest())
        return Topology(
            cluster_size=r.clusterSize, partitions_count=r.partitionsCount,
            replication_factor=r.replicationFactor, gateway_version=r.gatewayVersion,
            brokers=[
                {"nodeId": b.nodeId,
                 "partitions": {p.partitionId: pb.Partition.PartitionBrokerRole.Name(p.role)
                                for p in b.partitions}}
                for b in r.brokers
            ],
        )

    # -- deployment ------------------------------------------------------------

    def deploy_resource(self, *resources: tuple[str, str | bytes] | str,
                        tenant_id: str = "") -> dict:
        """deploy_resource(("proc.bpmn", xml), …) or a path string."""
        reqs = []
        for res in resources:
            if isinstance(res, str):
                with open(res, "rb") as f:
                    reqs.append(pb.Resource(name=res.rsplit("/", 1)[-1], content=f.read()))
            else:
                name, content = res
                if isinstance(content, str):
                    content = content.encode("utf-8")
                reqs.append(pb.Resource(name=name, content=content))
        r = self._deploy(pb.DeployResourceRequest(
            resources=reqs, tenantId=tenant_id or self.default_tenant))
        return {
            "key": r.key,
            "processes": [
                {"bpmnProcessId": d.process.bpmnProcessId,
                 "version": d.process.version,
                 "processDefinitionKey": d.process.processDefinitionKey}
                for d in r.deployments if d.WhichOneof("Metadata") == "process"
            ],
            "decisions": [
                {"decisionId": d.decision.dmnDecisionId,
                 "decisionName": d.decision.dmnDecisionName,
                 "version": d.decision.version,
                 "decisionKey": d.decision.decisionKey,
                 "decisionRequirementsKey": d.decision.decisionRequirementsKey}
                for d in r.deployments if d.WhichOneof("Metadata") == "decision"
            ],
            "forms": [
                {"formId": d.form.formId, "version": d.form.version,
                 "formKey": d.form.formKey}
                for d in r.deployments if d.WhichOneof("Metadata") == "form"
            ],
        }

    # -- process instances -----------------------------------------------------

    def create_instance(self, bpmn_process_id: str = "",
                        process_definition_key: int = 0, version: int = 0,
                        variables: dict | None = None,
                        tenant_id: str = "") -> ProcessInstance:
        r = self._create(pb.CreateProcessInstanceRequest(
            bpmnProcessId=bpmn_process_id,
            processDefinitionKey=process_definition_key, version=version,
            variables=json.dumps(variables or {}),
            tenantId=tenant_id or self.default_tenant,
        ))
        return ProcessInstance(r.processDefinitionKey, r.bpmnProcessId,
                               r.version, r.processInstanceKey)

    def create_instance_with_result(self, bpmn_process_id: str = "",
                                    process_definition_key: int = 0,
                                    version: int = 0,
                                    variables: dict | None = None,
                                    fetch_variables: list[str] | None = None,
                                    timeout_s: float = 20.0,
                                    tenant_id: str = "") -> ProcessInstance:
        r = self._create_with_result(pb.CreateProcessInstanceWithResultRequest(
            request=pb.CreateProcessInstanceRequest(
                bpmnProcessId=bpmn_process_id,
                processDefinitionKey=process_definition_key,
                version=version,
                variables=json.dumps(variables or {}),
                tenantId=tenant_id or self.default_tenant,
            ),
            requestTimeout=int(timeout_s * 1000),
            fetchVariables=fetch_variables or [],
        ))
        return ProcessInstance(r.processDefinitionKey, r.bpmnProcessId, r.version,
                               r.processInstanceKey,
                               variables=json.loads(r.variables or "{}"))

    def cancel_instance(self, process_instance_key: int) -> None:
        self._cancel(pb.CancelProcessInstanceRequest(
            processInstanceKey=process_instance_key))

    # -- messages / signals ----------------------------------------------------

    def publish_message(self, name: str, correlation_key: str,
                        variables: dict | None = None, ttl_ms: int = 3_600_000,
                        message_id: str = "", tenant_id: str = "") -> int:
        r = self._publish(pb.PublishMessageRequest(
            name=name, correlationKey=correlation_key, timeToLive=ttl_ms,
            messageId=message_id, variables=json.dumps(variables or {}),
            tenantId=tenant_id or self.default_tenant,
        ))
        return r.key

    def broadcast_signal(self, signal_name: str,
                         variables: dict | None = None,
                         tenant_id: str = "") -> int:
        r = self._signal(pb.BroadcastSignalRequest(
            signalName=signal_name, variables=json.dumps(variables or {}),
            tenantId=tenant_id or self.default_tenant))
        return r.key

    # -- jobs ------------------------------------------------------------------

    def activate_jobs(self, job_type: str, max_jobs: int = 32,
                      worker: str = "python-client", timeout_ms: int = 300_000,
                      request_timeout_ms: int = 0,
                      tenant_ids: list[str] | None = None) -> list[ActivatedJob]:
        if tenant_ids is None and self.default_tenant:
            tenant_ids = [self.default_tenant]
        jobs: list[ActivatedJob] = []
        for resp in self._activate(pb.ActivateJobsRequest(
            type=job_type, worker=worker, timeout=timeout_ms,
            maxJobsToActivate=max_jobs, requestTimeout=request_timeout_ms,
            tenantIds=tenant_ids or [],
        )):
            jobs.extend(_job_of(j) for j in resp.jobs)
        return jobs

    def stream_jobs(self, job_type: str, worker: str = "python-client",
                    timeout_ms: int = 300_000,
                    tenant_ids: list[str] | None = None) -> Iterator[ActivatedJob]:
        if tenant_ids is None and self.default_tenant:
            tenant_ids = [self.default_tenant]
        for j in self._stream_jobs(pb.StreamActivatedJobsRequest(
            type=job_type, worker=worker, timeout=timeout_ms,
            tenantIds=tenant_ids or [],
        )):
            yield _job_of(j)

    def open_job_stream(self, job_type: str, worker: str = "python-client",
                        timeout_ms: int = 300_000,
                        tenant_ids: list[str] | None = None):
        """StreamActivatedJobs with a cancellation handle: returns
        ``(call, jobs)`` where ``call.cancel()`` ends the stream and ``jobs``
        iterates ActivatedJob (the streaming JobWorker's ingress). The
        iterator ends cleanly on cancellation."""
        if tenant_ids is None and self.default_tenant:
            tenant_ids = [self.default_tenant]
        call = self._stream_jobs(pb.StreamActivatedJobsRequest(
            type=job_type, worker=worker, timeout=timeout_ms,
            tenantIds=tenant_ids or [],
        ))

        def _jobs():
            try:
                for j in call:
                    yield _job_of(j)
            except grpc.RpcError as exc:
                if exc.code() != grpc.StatusCode.CANCELLED:
                    raise

        return call, _jobs()

    def complete_job(self, job_key: int, variables: dict | None = None) -> None:
        self._complete(pb.CompleteJobRequest(
            jobKey=job_key, variables=json.dumps(variables or {})))

    def fail_job(self, job_key: int, retries: int, error_message: str = "",
                 retry_back_off_ms: int = 0) -> None:
        self._fail(pb.FailJobRequest(
            jobKey=job_key, retries=retries, errorMessage=error_message,
            retryBackOff=retry_back_off_ms))

    def throw_error(self, job_key: int, error_code: str,
                    error_message: str = "") -> None:
        self._throw(pb.ThrowErrorRequest(
            jobKey=job_key, errorCode=error_code, errorMessage=error_message))

    def update_job_retries(self, job_key: int, retries: int) -> None:
        self._retries(pb.UpdateJobRetriesRequest(jobKey=job_key, retries=retries))

    def update_job_timeout(self, job_key: int, timeout_ms: int) -> None:
        self._update_timeout(pb.UpdateJobTimeoutRequest(
            jobKey=job_key, timeout=timeout_ms))

    # -- variables / incidents -------------------------------------------------

    def set_variables(self, element_instance_key: int, variables: dict,
                      local: bool = False) -> int:
        r = self._set_vars(pb.SetVariablesRequest(
            elementInstanceKey=element_instance_key,
            variables=json.dumps(variables), local=local))
        return r.key

    def resolve_incident(self, incident_key: int) -> None:
        self._resolve(pb.ResolveIncidentRequest(incidentKey=incident_key))
