"""JobWorker: poll/stream jobs, dispatch to a handler, complete or fail.

Reference: clients/java/…/worker/JobWorker + JobWorkerBuilderStep1 (poller +
streamer, exponential poll backoff, maxJobsActive flow control), and the Go
worker (clients/go/pkg/worker/jobPoller.go, jobDispatcher.go).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Callable

from zeebe_tpu.client.client import ActivatedJob, ZeebeTpuClient

Handler = Callable[["JobClient", ActivatedJob], None]


class JobClient:
    """Handed to handlers: complete/fail/throw for the current job."""

    def __init__(self, client: ZeebeTpuClient) -> None:
        self._client = client

    def complete(self, job: ActivatedJob, variables: dict | None = None) -> None:
        self._client.complete_job(job.key, variables)

    def fail(self, job: ActivatedJob, retries: int | None = None,
             error_message: str = "", retry_back_off_ms: int = 0) -> None:
        self._client.fail_job(
            job.key, job.retries - 1 if retries is None else retries,
            error_message, retry_back_off_ms,
        )

    def throw_error(self, job: ActivatedJob, error_code: str,
                    error_message: str = "") -> None:
        self._client.throw_error(job.key, error_code, error_message)


class JobWorker:
    """Background polling worker with exponential empty-poll backoff.

    ``auto_complete``: a handler return (no exception) completes the job with
    the handler's returned dict (or {}); an exception fails it with
    retries-1 (the Java client's default error behavior).

    ``stream_enabled``: use the StreamActivatedJobs push path instead of the
    ActivateJobs poll loop (reference: JobWorkerBuilderStep1.streamEnabled —
    jobs arrive as the broker creates them, no polling)."""

    def __init__(
        self,
        client: ZeebeTpuClient,
        job_type: str,
        handler: Handler | Callable[[ActivatedJob], dict | None],
        worker_name: str = "python-worker",
        max_jobs_active: int = 32,
        timeout_ms: int = 300_000,
        poll_interval_s: float = 0.05,
        max_backoff_s: float = 1.0,
        auto_complete: bool = True,
        stream_enabled: bool = False,
    ) -> None:
        self.client = client
        self.job_type = job_type
        self.handler = handler
        self.worker_name = worker_name
        self.max_jobs_active = max_jobs_active
        self.timeout_ms = timeout_ms
        self.poll_interval_s = poll_interval_s
        self.max_backoff_s = max_backoff_s
        self.auto_complete = auto_complete
        self.stream_enabled = stream_enabled
        self._running = False
        self._thread: threading.Thread | None = None
        self.handled_count = 0
        self.failed_count = 0

    def start(self) -> "JobWorker":
        self._running = True
        target = self._stream_loop if self.stream_enabled else self._poll_loop
        self._thread = threading.Thread(target=target, daemon=True,
                                        name=f"worker-{self.job_type}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        call = getattr(self, "_call", None)
        if call is not None:
            call.cancel()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _poll_loop(self) -> None:
        backoff = self.poll_interval_s
        job_client = JobClient(self.client)
        while self._running:
            try:
                jobs = self.client.activate_jobs(
                    self.job_type, max_jobs=self.max_jobs_active,
                    worker=self.worker_name, timeout_ms=self.timeout_ms,
                )
            except Exception:
                time.sleep(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
                continue
            if not jobs:
                time.sleep(backoff)
                backoff = min(backoff * 2, self.max_backoff_s)
                continue
            backoff = self.poll_interval_s
            for job in jobs:
                if not self._running:
                    return
                self._dispatch(job_client, job)

    def _stream_loop(self) -> None:
        job_client = JobClient(self.client)
        while self._running:
            try:
                self._call, jobs = self.client.open_job_stream(
                    self.job_type, worker=self.worker_name,
                    timeout_ms=self.timeout_ms,
                )
                if not self._running:
                    # stop() raced the reconnect: its cancel hit the old call
                    self._call.cancel()
                    return
                for job in jobs:
                    if not self._running:
                        return
                    self._dispatch(job_client, job)
            except Exception:
                if not self._running:
                    return
                time.sleep(self.poll_interval_s)

    def _dispatch(self, job_client: JobClient, job: ActivatedJob) -> None:
        try:
            if self.auto_complete:
                result = self.handler(job)
                job_client.complete(job, result if isinstance(result, dict) else {})
            else:
                self.handler(job_client, job)
            self.handled_count += 1
        except Exception as exc:  # handler error → fail with retries-1
            self.failed_count += 1
            try:
                job_client.fail(job, error_message=(
                    f"{type(exc).__name__}: {exc}\n{traceback.format_exc(limit=5)}"
                ))
            except Exception:
                pass
