"""Env-knob inventory: every ``ZEEBE_*`` environment variable the tree
reads, found by AST scan — the source for ``cli knobs-doc`` and its CI
drift gate (the metrics-doc ``--check`` pattern applied to configuration).

Collection is literal-based, not call-based, on purpose: the broker binds
env vars through a declarative ``_ENV_BINDINGS`` table and the exporter
loader scans ``os.environ`` by prefix, so "calls to os.environ.get" would
miss half the real surface. Instead every string constant matching
``ZEEBE_[A-Z0-9_]+`` inside ``zeebe_tpu/`` counts as a knob mention; names
ending in ``_`` are prefix *families* (``ZEEBE_BROKER_EXPORTERS_<ID>_…``),
and full names extending a known family fold into it as examples.

Every knob MUST have a one-line description in ``KNOB_NOTES`` —
``cli knobs-doc --check`` fails on a missing note (undocumented knob) or on
drift between the generated table and the committed docs/knobs.md.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

_KNOB_RE = re.compile(r"^ZEEBE_[A-Z0-9_]+$")

#: the curated one-liners — the human half of the generated doc. A new env
#: read without an entry here fails `cli knobs-doc --check` (CI): config
#: knobs that exist only in the code ARE the drift this gate exists for.
KNOB_NOTES: dict[str, str] = {
    "ZEEBE_ALERT_RSSWATERMARKBYTES": (
        "RSS high-watermark (bytes) for the default memory alert rule; the "
        "scale soak tightens it to its budget"),
    "ZEEBE_AUDIT_CRCWINDOW": (
        "records per replica-CRC checkpoint window the online auditor "
        "publishes for cross-worker spot agreement (default 5000)"),
    "ZEEBE_AUDIT_ENABLED": (
        "enable the per-broker online auditor: invariant monitors, SLO "
        "burn-rate alerts, leak-trend detection (default true)"),
    "ZEEBE_AUDIT_FASTWINDOWMS": (
        "fast burn-rate window (ms, default 5m): pages only when BOTH "
        "windows burn — the multiwindow SRE alerting shape"),
    "ZEEBE_AUDIT_GOODPUTFLOOR": (
        "acked/terminal fraction below which a tick counts as bad toward "
        "the burn-rate budget (default 0.7)"),
    "ZEEBE_AUDIT_LEAKMINGROWTH": (
        "minimum relative growth over the leak window before a trend can "
        "latch a leak verdict (default 0.3 = +30%)"),
    "ZEEBE_AUDIT_LEAKMINSAMPLES": (
        "minimum samples before the leak-trend detector renders any "
        "verdict (default 24)"),
    "ZEEBE_AUDIT_LEAKWARMUPMS": (
        "hold-off after broker boot before resource series feed the leak "
        "detector — boot-era monotone climbs are genuine, not leaks "
        "(default 60s)"),
    "ZEEBE_AUDIT_LEAKWINDOWMS": (
        "sliding window (ms, default 10m) for the least-squares "
        "resource-trend leak detector"),
    "ZEEBE_AUDIT_QUARANTINEMAXMS": (
        "max time the device-health ladder may sit QUARANTINED before the "
        "auditor latches a quarantine_latch violation (default 10m)"),
    "ZEEBE_AUDIT_SLOP99MS": (
        "admission ack-p99 SLO bound (ms) feeding the burn-rate good/bad "
        "classification (default 5000)"),
    "ZEEBE_AUDIT_SLOTARGET": (
        "availability SLO target for burn-rate math, e.g. 0.999 = 0.1% "
        "error budget (default 0.999)"),
    "ZEEBE_AUDIT_SLOWWINDOWMS": (
        "slow burn-rate window (ms, default 1h); sustained-but-mild burns "
        "raise a ticket instead of a page"),
    "ZEEBE_AUDIT_TESTLEAK": (
        "test-only deliberate leak (`fd:25`, `ring:50`) for the fleet-day "
        "recall arm — the auditor MUST convict a worker running this; "
        "never enable outside a harness"),
    "ZEEBE_BROKER_BACKPRESSURE_ALGORITHM": (
        "ingress rate-limit algorithm: `vegas` (default) | `aimd` | `fixed`"),
    "ZEEBE_BROKER_BACKPRESSURE_ENABLED": (
        "enable the per-partition in-flight command limiter (default true)"),
    "ZEEBE_BROKER_CLUSTER_INITIALCONTACTPOINTS": (
        "comma-separated member ids forming the cluster"),
    "ZEEBE_BROKER_CLUSTER_NODEID": "this broker's member id",
    "ZEEBE_BROKER_CLUSTER_PARTITIONSCOUNT": "number of partitions (>= 1)",
    "ZEEBE_BROKER_CLUSTER_REPLICATIONFACTOR": (
        "Raft replication factor per partition (>= 1)"),
    "ZEEBE_BROKER_DATA_BACKUP": (
        "prefix family: remote backup store — `…_STORE=S3|GCS|NONE` plus "
        "per-store sub-keys (`…_S3_ENDPOINT`, `…_GCS_BUCKETNAME`, …; "
        "backup/__init__.py)"),
    "ZEEBE_BROKER_DATA_DISK_ENABLEMONITORING": (
        "enable free-disk monitoring / ingestion pause (default true)"),
    "ZEEBE_BROKER_DATA_DISK_MINFREEBYTES": (
        "pause ingestion below this free-space watermark (default 128MiB)"),
    "ZEEBE_BROKER_DATA_LOGFLUSHDELAYMS": (
        "raft journal group-commit pacing: 0 (default) = fsync before every "
        "ack; > 0 = defer the fsync up to this many ms with acks strictly "
        "AFTER the covering fsync (several appends share one fsync). The "
        "journal-flush controller's knob — its actuator owns runtime "
        "changes"),
    "ZEEBE_BROKER_DATA_LOGMAXUNFLUSHEDBYTES": (
        "raft journal group-commit byte bound: a deferred flush drains "
        "early once this many unfsynced bytes accumulate (default 1MiB)"),
    "ZEEBE_BROKER_DATA_RECOVERYBUDGETMS": (
        "recovery-time budget: slower recoveries fire the "
        "recovery_budget_exceeded alert; the snapshot scheduler adapts its "
        "cadence to keep projected replay debt under it (<= 0 disables)"),
    "ZEEBE_BROKER_DATA_SNAPSHOTCHAINLENGTH": (
        "incremental snapshots: base+delta chain length before a full "
        "rebase (1 = every snapshot full)"),
    "ZEEBE_BROKER_DATA_SNAPSHOTPERIOD": "periodic snapshot cadence (ms)",
    "ZEEBE_BROKER_DATA_SCRUB_ENABLED": (
        "at-rest storage scrubber: pump-throttled background CRC walk over "
        "journal bytes, snapshot chain files, and cold segments — bit rot "
        "is detected (and repaired) before a read serves it (default on)"),
    "ZEEBE_BROKER_DATA_SCRUB_INTERVALMS": (
        "scrubber: minimum ms between scrub slices on the pump "
        "(default 1000)"),
    "ZEEBE_BROKER_DATA_SCRUB_BYTESPERPASS": (
        "scrubber: byte budget re-CRCed per slice — bounds the pump stall "
        "per pass (default 4MiB)"),
    "ZEEBE_BROKER_DATA_TIERING_ENABLED": (
        "state tiering: spill parked instances to the cold disk store"),
    "ZEEBE_BROKER_DATA_TIERING_PARKAFTERMS": (
        "tiering: park an instance this long before it becomes a spill "
        "candidate"),
    "ZEEBE_BROKER_DATA_TIERING_SPILLBATCH": (
        "tiering: instances spilled per pump pass"),
    "ZEEBE_BROKER_DEVICE_DISPATCHTIMEOUTMS": (
        "device dispatch watchdog: a dispatch/fetch exceeding this deadline "
        "is contained as a typed wedge (0 disables; armed only on real "
        "accelerators or under device chaos — default 45000)"),
    "ZEEBE_BROKER_DEVICE_SHADOWSAMPLERATE": (
        "fraction of kernel groups re-executed on the host oracle and "
        "compared byte-for-byte before commit (silent-corruption "
        "detection; default 0.02)"),
    "ZEEBE_BROKER_DEVICE_SUSPECTSHADOWBOOST": (
        "shadow-sample-rate multiplier while the device health ladder is "
        "SUSPECT (default 8)"),
    "ZEEBE_BROKER_DEVICE_QUARANTINEFAULTS": (
        "device faults inside the fault window that escalate SUSPECT to "
        "QUARANTINED (default 3)"),
    "ZEEBE_BROKER_DEVICE_FAULTWINDOWMS": (
        "sliding window the quarantine fault count is evaluated over "
        "(default 60000)"),
    "ZEEBE_BROKER_DEVICE_SUSPECTCLEARMS": (
        "fault-free ms under boosted shadow sampling that steps SUSPECT "
        "back to HEALTHY (default 30000)"),
    "ZEEBE_BROKER_DEVICE_CANARYINTERVALMS": (
        "cadence of known-answer canary dispatches while QUARANTINED "
        "(default 5000)"),
    "ZEEBE_BROKER_DEVICE_CANARYSUCCESSES": (
        "consecutive verified canaries that re-prove a QUARANTINED device "
        "(default 2)"),
    "ZEEBE_BROKER_DEVICE_SHADOWSEED": (
        "seed of the deterministic shadow-sampling decision stream"),
    "ZEEBE_BROKER_EXPERIMENTAL_CONSISTENCYCHECKS": (
        "enable foreign-key consistency checks in the state store"),
    "ZEEBE_BROKER_EXPERIMENTAL_DURABLESTATE": (
        "enable the durable (WAL-backed) state store backend"),
    "ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND": (
        "enable the JAX automaton-kernel processing backend"),
    "ZEEBE_BROKER_EXPERIMENTAL_KERNELMESHSHARDS": (
        "kernel mesh shards: -1 auto (devices), 0 off, N explicit"),
    "ZEEBE_BROKER_METRICS_SAMPLINGINTERVALMS": (
        "registry→time-series sampling cadence (0 disables the store, "
        "sampler, and alert evaluation)"),
    "ZEEBE_BROKER_NETWORK_MAXOUTBOUNDBUFFERBYTES": (
        "zombie-client protection: per-stream outbound buffer bound — a "
        "connected peer that stops reading is disconnected once this many "
        "bytes buffer (default 8MiB)"),
    "ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATEAUTHORITYPATH": (
        "TLS: CA bundle path for cluster messaging"),
    "ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATECHAINPATH": (
        "TLS: certificate chain path for cluster messaging"),
    "ZEEBE_BROKER_NETWORK_SECURITY_ENABLED": (
        "TLS on the cluster messaging plane (default off)"),
    "ZEEBE_BROKER_NETWORK_SECURITY_PRIVATEKEYPATH": (
        "TLS: private key path for cluster messaging"),
    "ZEEBE_BROKER_PROCESSING_COALESCEWINDOWMS": (
        "worker ingress batch-coalescing window (ms): admitted client "
        "commands arriving within it append as ONE raft batch (one fsync, "
        "one replication round). 0 (default) = append per command; the "
        "ingress-coalescing controller's knob"),
    "ZEEBE_BROKER_PIPELINE_SPECULATION": (
        "cross-wave double-buffered dispatch: admit wave k+1 and dispatch "
        "its first device chunk inside wave k's transaction so the chunk "
        "computes under wave k's append/commit/fsync tail (default on; "
        "0/false/off disables)"),
    "ZEEBE_BROKER_PROCESSING_MAXCOMMANDSINBATCH": (
        "commands processed per batch transaction (default 100)"),
    "ZEEBE_BROKER_PROFILING_HZ": (
        "continuous profiler stack-sampling rate (0 disables the plane)"),
    "ZEEBE_BROKER_EXPORTERS_": (
        "prefix family: external exporter loading — "
        "`…_<ID>_CLASSNAME` / `…_<ID>_PATH` / `…_<ID>_ARGS_<K>` "
        "(utils/external_code.py)"),
    "ZEEBE_CHAOS_CRASH_AFTER_APPENDS": (
        "chaos seam: hard-exit the worker process between the Nth "
        "successful ingress append and its reply (one-shot per data dir; "
        "consistency gate)"),
    "ZEEBE_CHAOS_DISK": (
        "chaos disk: seeded storage fault-injection spec (write EIO/ENOSPC/"
        "torn rates, fsync stall/failure rates, at-rest bit-rot cadence, "
        "path classes) installed into the utils/storage_io seam; the "
        "torture gate's fault source"),
    "ZEEBE_CHAOS_DISK_DISARMFILE": (
        "chaos disk: path the controller polls each tick — creating it "
        "disarms all disk faults (the torture harness ends the survival "
        "window before its probe/quiesce phases)"),
    "ZEEBE_CHAOS_DEVICE": (
        "chaos device: seeded accelerator fault-injection spec (compile/"
        "dispatch failure, stall, partial-chunk failure, result bit-flip "
        "rates) installed into the kernel dispatch seam; the device-chaos "
        "gate's fault source"),
    "ZEEBE_CHAOS_DEVICE_DISARMFILE": (
        "chaos device: path the controller polls each tick — creating it "
        "disarms all device faults (the gate's recovery phase lets the "
        "canary ladder re-prove an honest device)"),
    "ZEEBE_CHAOS_EPOCH_MS": (
        "chaos TCP: epoch anchor for deterministic link-partition windows "
        "across processes"),
    "ZEEBE_CHAOS_TCP": (
        "chaos TCP: seeded fault-injection spec (drop/dup/delay/reorder "
        "rates + seed) wrapped around a process's messaging plane"),
    "ZEEBE_CHAOS_TCP_WINDOWSFILE": (
        "chaos TCP: JSON file of link-partition windows the wrapper "
        "enforces"),
    "ZEEBE_CLIENT_ID": "OAuth client id for gateway client credentials",
    "ZEEBE_CLIENT_SECRET": "OAuth client secret for gateway client credentials",
    "ZEEBE_AUTHORIZATION_SERVER_URL": (
        "OAuth token endpoint for the client credentials flow"),
    "ZEEBE_TOKEN_AUDIENCE": "OAuth audience claim requested for gateway tokens",
    "ZEEBE_CONTROL_ENABLED": (
        "closed-loop control plane (docs/control.md): controllers tick off "
        "the broker pump and drive the knob surface from the time-series "
        "store through bounded, audited actuators. 0 = the plane is not "
        "constructed (one is-None check per control pump); default on, "
        "inert without the metrics plane"),
    "ZEEBE_CONTROL_INTERVALMS": (
        "control plane: controller tick cadence (default 500ms; each tick "
        "moves each knob at most one bounded step)"),
    "ZEEBE_CONTROL_ACKP99TARGETMS": (
        "control plane: the journal-flush controller's ack-latency SLO "
        "(default 250ms) — fsync pacing widens while flush pressure "
        "threatens it"),
    "ZEEBE_CONTROL_RSSTARGETBYTES": (
        "control plane: the state-tiering controller's RSS set point; 0 "
        "(default) derives 80% of the rss_watermark alert bound"),
    "ZEEBE_FLIGHT_MAXDUMPBYTES": (
        "flight recorder: per-dump serialized-size cap (default 256KiB) — "
        "oldest ring entries drop first, the dump records truncatedEntries; "
        "0 disables bounding"),
    "ZEEBE_GATEWAY_INTERCEPTORS_": (
        "prefix family: external gateway interceptor loading — "
        "`…_<ID>_CLASSNAME` / `…_<ID>_PATH` (utils/external_code.py)"),
    "ZEEBE_GATEWAY_ADMISSION_DRAINAFTERMS": (
        "admission: /ready degrades after shedding NEW WORK for this long, "
        "so an LB can drain the gateway (0 disables; default 10s)"),
    "ZEEBE_GATEWAY_ADMISSION_ENABLED": (
        "tenant-aware admission + cooperative load shedding at the gateway "
        "and worker ingress (default true)"),
    "ZEEBE_GATEWAY_ADMISSION_MAXINFLIGHT": (
        "admission: in-flight command window for the weighted-fair tenant "
        "share (default 256; workers derive theirs from the partition "
        "backpressure limits)"),
    "ZEEBE_GATEWAY_ADMISSION_SHEDP99MS": (
        "admission: shed-ladder target — the shed level rises while the "
        "observed ack p99 exceeds this (ms, default 1000; hysteresis "
        "recovers below half)"),
    "ZEEBE_GATEWAY_REQUEST_TIMEOUT_MS": (
        "multi-process gateway: per-request routing deadline (bounded "
        "resend across workers)"),
    "ZEEBE_GATEWAY_TENANT_DEFAULTBURST": (
        "admission: default per-tenant token-bucket burst (0 derives "
        "2x rate)"),
    "ZEEBE_GATEWAY_TENANT_DEFAULTRATE": (
        "admission: default per-tenant token-bucket quota (tokens/s; "
        "0 = unmetered)"),
    "ZEEBE_GATEWAY_TENANT_QUOTAS": (
        "admission: per-tenant quota overrides, "
        "`tenant=rate[:burst],...` (e.g. `t-hot=8:16,t-batch=50`)"),
    "ZEEBE_GATEWAY_TENANT_WEIGHTS": (
        "admission: per-tenant weights for the fair in-flight share, "
        "`tenant=weight,...` (default 1.0)"),
    "ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_MODE": (
        "gateway auth mode: `none` (default) or `identity` (JWT)"),
    "ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_SECRET": (
        "HMAC secret validating gateway JWTs in identity mode"),
    "ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_AUDIENCE": (
        "expected audience claim for gateway JWTs in identity mode"),
    "ZEEBE_LOG_APPENDER": "log output shape: `console` or `stackdriver` (JSON)",
    "ZEEBE_LOG_LEVEL": "root log level (info default)",
    "ZEEBE_LOG_STACKDRIVER_SERVICENAME": (
        "serviceContext.service for stackdriver-shaped logs"),
    "ZEEBE_LOG_STACKDRIVER_SERVICEVERSION": (
        "serviceContext.version for stackdriver-shaped logs"),
    "ZEEBE_PROBE_CMD": (
        "test/chaos seam: replaces the killable device-probe child command "
        "(simulate a wedged tunnel from outside the process)"),
    "ZEEBE_PROBE_TIMEOUT_S": (
        "killable device probe: hard SIGKILL deadline (seconds, default "
        "90) for the default-backend query subprocess"),
    "ZEEBE_REQUEST_DEDUPE_RETENTIONPOSITIONS": (
        "replicated request-dedupe retention: entries age out once the log "
        "advances this many positions past them (default 100k). "
        "Deterministic deployment constant — it shapes replicated-state "
        "materialization identically on processing and replay"),
    "ZEEBE_SANITIZE": (
        "tier-1 runtime sanitizer (testing/sanitizer.py): 1 = wrap "
        "ZbDb/journal/flight-recorder with single-writer and reentrancy "
        "assertions, turning latent cross-thread races into deterministic "
        "test failures"),
    "ZEEBE_TPU_NO_NATIVE": (
        "1 = disable the native C codec fast paths (pure-Python parity "
        "mode)"),
    "ZEEBE_TRACING": "1/true = enable the Dapper-style tracer",
    "ZEEBE_TRACE_CAPACITY": "tracer ring capacity (spans retained)",
    "ZEEBE_TRACE_DUMP_DIR": (
        "directory the gateway writes its span dump "
        "(spans-<node>-<pid>.jsonl) into at orderly stop, for the offline "
        "critical-path assembler; unset = no gateway dump (workers always "
        "dump into their broker data dir)"),
    "ZEEBE_TRACE_SAMPLE_RATE": "trace sampling rate in [0,1]",
    "ZEEBE_TRACE_SEED": "trace sampling hash seed (deterministic sampling)",
}


@dataclass
class Knob:
    name: str            # full name, or prefix family ending in "_"
    is_prefix: bool
    sites: set[str] = field(default_factory=set)     # repo-relative paths
    examples: set[str] = field(default_factory=set)  # members of a family


def scan_knobs(root: Path | str) -> list[Knob]:
    """Every ZEEBE_* knob mentioned in ``zeebe_tpu/``, prefix families
    folded, sorted by name."""
    root = Path(root)
    mentions: dict[str, set[str]] = {}
    for path in sorted(root.glob("zeebe_tpu/**/*.py")):
        # analysis/ excluded: KNOB_NOTES itself mentions every knob name —
        # scanning it would make stale notes self-justifying forever
        if "__pycache__" in path.parts or "analysis" in path.parts:
            continue
        rel = path.relative_to(root).as_posix()
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError as exc:  # pragma: no cover — lint catches first
            raise RuntimeError(f"knob scan cannot parse {path}") from exc
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                    and _KNOB_RE.match(node.value)):
                mentions.setdefault(node.value, set()).add(rel)
    prefixes = sorted((n for n in mentions if n.endswith("_")), key=len,
                      reverse=True)
    knobs: dict[str, Knob] = {
        name: Knob(name=name, is_prefix=True, sites=set(sites))
        for name, sites in mentions.items() if name.endswith("_")
    }
    for name, sites in mentions.items():
        if name.endswith("_"):
            continue
        family = next((p for p in prefixes if name.startswith(p)), None)
        if family is not None:
            knobs[family].sites |= sites
            knobs[family].examples.add(name)
        else:
            knobs[name] = Knob(name=name, is_prefix=False, sites=set(sites))
    return sorted(knobs.values(), key=lambda k: k.name)


_KNOBS_DOC_HEADER = """\
# Environment knobs

> Auto-generated by `python -m zeebe_tpu.cli knobs-doc` from an AST scan of
> every `ZEEBE_*` string literal under `zeebe_tpu/` (declarative binding
> tables and prefix scans included — see zeebe_tpu/analysis/knobs.py).
> **Do not edit by hand** — regenerate with
> `python -m zeebe_tpu.cli knobs-doc` and commit; CI fails on drift, and a
> knob without a one-liner in `analysis/knobs.py::KNOB_NOTES` fails the
> check outright (undocumented knobs do not ship).
>
> Names ending in `_<…>` are prefix families: the tree scans the
> environment for every variable under the prefix.
"""


def render_knobs_doc(knobs: list[Knob]) -> str:
    lines = [_KNOBS_DOC_HEADER]
    lines.append(f"{len(knobs)} knobs.\n")
    lines.append("| knob | read sites | description |")
    lines.append("| --- | --- | --- |")
    for knob in knobs:
        shown = f"`{knob.name}<…>`" if knob.is_prefix else f"`{knob.name}`"
        sites = "<br>".join(f"`{s}`" for s in sorted(knob.sites))
        note = KNOB_NOTES.get(knob.name, "**(undocumented)**")
        if knob.is_prefix and knob.examples:
            examples = ", ".join(f"`{e}`" for e in sorted(knob.examples))
            note = f"{note}. In-tree members: {examples}"
        lines.append(f"| {shown} | {sites} | {note} |")
    return "\n".join(lines) + "\n"


def undocumented(knobs: list[Knob]) -> list[str]:
    return [k.name for k in knobs if k.name not in KNOB_NOTES]
