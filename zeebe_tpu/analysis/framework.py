"""zlint rule framework: parsed modules, findings, baseline, suppressions.

Design constraints:

- **stdlib only.** ``cli lint`` runs in CI before anything else and must
  never initialize jax (a wedged TPU tunnel hanging the *linter* would be
  the punchline to the very defect class rule 2 exists for).
- **Line-number-free baseline keys.** A finding's identity is
  ``(rule, path, scope, code)`` — enclosing-function qualname plus the
  stripped source line — so a committed baseline survives unrelated edits
  above the flagged line. Two identical flagged lines in the same function
  share one baseline entry on purpose (they are the same decision).
- **Inline suppressions** (``# zlint: disable=<rule>[,<rule>…]`` or
  ``disable=all``) apply to the flagged line or the enclosing ``def``
  line — for exceptions whose justification belongs next to the code.
  The committed baseline is for pre-existing/architectural exceptions whose
  justification belongs in one reviewable place.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable

BASELINE_FILENAME = ".zlint-baseline"

#: files the lint walk covers, relative to the repo root. Tests are excluded
#: deliberately: they provoke violations on purpose (fixtures under
#: tests/fixtures/lint/ are the rule suite's own corpus).
LINT_GLOBS = ("zeebe_tpu/**/*.py", "bench.py", "__graft_entry__.py")

_SUPPRESS_RE = re.compile(r"#\s*zlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str      # repo-relative posix path
    line: int      # 1-based
    col: int
    scope: str     # enclosing function qualname, or "<module>"
    code: str      # stripped source of the flagged line
    message: str

    @property
    def baseline_key(self) -> tuple[str, str, str, str]:
        return (self.rule, self.path, self.scope, self.code)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}\n    {self.code}")


class ParsedModule:
    """One parsed source file plus the derived indexes rules share: the
    qualname of every node's enclosing function and per-line suppression
    sets. Parsed once, visited by every rule."""

    def __init__(self, root: Path, path: Path) -> None:
        self.path = path
        self.relpath = path.relative_to(root).as_posix()
        self.source = path.read_text(encoding="utf-8")
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        self._scope_of: dict[ast.AST, str] = {}
        self._def_line_of_scope: dict[str, int] = {}
        self._index_scopes(self.tree, ())
        self._suppressed: dict[int, set[str]] = {}
        for lineno, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                names = {n.strip() for n in m.group(1).split(",") if n.strip()}
                self._suppressed[lineno] = names

    def _index_scopes(self, node: ast.AST, stack: tuple[str, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            child_stack = stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_stack = stack + (child.name,)
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._def_line_of_scope.setdefault(
                        ".".join(child_stack), child.lineno)
            self._scope_of[child] = ".".join(child_stack) or "<module>"
            self._index_scopes(child, child_stack)

    def scope_of(self, node: ast.AST) -> str:
        return self._scope_of.get(node, "<module>")

    def has_function(self, qual: str) -> bool:
        """True when ``qual`` names a function in this module, or a scope
        one of this module's functions lives under."""
        return any(q == qual or q.startswith(qual + ".")
                   for q in self._def_line_of_scope)

    def line_source(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def is_suppressed(self, rule: str, node: ast.AST) -> bool:
        lines = [getattr(node, "lineno", 0)]
        def_line = self._def_line_of_scope.get(self.scope_of(node))
        if def_line is not None:
            lines.append(def_line)
        for lineno in lines:
            names = self._suppressed.get(lineno)
            if names and (rule in names or "all" in names):
                return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        lineno = getattr(node, "lineno", 1)
        return Finding(
            rule=rule, path=self.relpath, line=lineno,
            col=getattr(node, "col_offset", 0) + 1,
            scope=self.scope_of(node),
            code=self.line_source(lineno), message=message)


class Rule:
    """A named invariant. Subclasses set ``name``/``summary`` and implement
    either ``check(module)`` (per-module) or ``check_tree(modules)``
    (cross-module rules like drift-copy)."""

    name: str = ""
    summary: str = ""
    cross_module: bool = False

    def check(self, module: ParsedModule) -> list[Finding]:
        return []

    def check_tree(self, modules: list[ParsedModule]) -> list[Finding]:
        return []

    def validate(self, modules: list[ParsedModule]) -> list[Finding]:
        """Report scope/root registrations that no longer match anything in
        the tree. A rename that orphans a registration must FAIL the lint,
        not silently disable the invariant it anchored (the rule equivalent
        of the baseline's stale-entry report)."""
        return []

    def registration_finding(self, entry: str, message: str) -> Finding:
        """A synthetic finding for a stale registration — anchored on the
        rule table itself, since the registered target no longer exists."""
        return Finding(rule=self.name, path="zeebe_tpu/analysis/rules.py",
                       line=1, col=1, scope="<registration>", code=entry,
                       message=message)


def parse_tree(root: Path) -> list[ParsedModule]:
    root = Path(root)
    modules: list[ParsedModule] = []
    seen: set[Path] = set()
    for pattern in LINT_GLOBS:
        for path in sorted(root.glob(pattern)):
            if "__pycache__" in path.parts or path in seen:
                continue
            seen.add(path)
            try:
                modules.append(ParsedModule(root, path))
            except (SyntaxError, UnicodeDecodeError, OSError) as exc:
                raise RuntimeError(f"zlint cannot parse {path}: {exc}") from exc
    return modules


def run_lint(root: Path | str, rules: Iterable[Rule] | None = None
             ) -> list[Finding]:
    """All unsuppressed findings over the repo at ``root`` (baseline NOT
    applied — see :func:`split_findings`)."""
    from zeebe_tpu.analysis.rules import RULES

    root = Path(root)
    modules = parse_tree(root)
    findings: list[Finding] = []
    for rule in (rules if rules is not None else RULES):
        findings.extend(rule.validate(modules))
        if rule.cross_module:
            findings.extend(rule.check_tree(modules))
        else:
            for module in modules:
                findings.extend(rule.check(module))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# -- baseline ------------------------------------------------------------------
#
# Tab-separated, one intentional exception per line:
#   rule<TAB>path<TAB>scope<TAB>code<TAB>justification
# Keys are line-number-free (see module docstring). `cli lint
# --update-baseline` regenerates the file, preserving justifications of
# surviving entries and stamping new ones with "TODO: justify".

_BASELINE_HEADER = """\
# zlint baseline — intentional exceptions to the invariant rules.
# One per line: rule<TAB>path<TAB>scope<TAB>flagged-code<TAB>justification.
# Regenerate with `python -m zeebe_tpu.cli lint --update-baseline` (it
# preserves the justifications of surviving entries); every new entry MUST
# replace its "TODO: justify" stamp before merging. `cli lint --check`
# fails on findings absent from this file.
"""


def load_baseline(path: Path | str) -> dict[tuple[str, str, str, str], str]:
    """{baseline_key: justification} from a baseline file (missing = {})."""
    path = Path(path)
    if not path.exists():
        return {}
    entries: dict[tuple[str, str, str, str], str] = {}
    for raw in path.read_text(encoding="utf-8").splitlines():
        if not raw.strip() or raw.lstrip().startswith("#"):
            continue
        parts = raw.split("\t")
        if len(parts) < 4:
            raise ValueError(f"malformed baseline line: {raw!r}")
        rule, rel, scope, code = parts[0], parts[1], parts[2], parts[3]
        justification = parts[4] if len(parts) > 4 else ""
        entries[(rule, rel, scope, code)] = justification
    return entries


def split_findings(
    findings: list[Finding],
    baseline: dict[tuple[str, str, str, str], str],
) -> tuple[list[Finding], list[tuple[str, str, str, str]]]:
    """(new findings not covered by the baseline, stale baseline keys that
    matched nothing)."""
    keys = {f.baseline_key for f in findings}
    new = [f for f in findings if f.baseline_key not in baseline]
    stale = [k for k in baseline if k not in keys]
    return new, stale


def format_baseline(
    findings: list[Finding],
    previous: dict[tuple[str, str, str, str], str] | None = None,
) -> str:
    """Render a baseline covering ``findings``, carrying justifications over
    from ``previous`` where the key survives."""
    previous = previous or {}
    lines = [_BASELINE_HEADER]
    seen: set[tuple[str, str, str, str]] = set()
    for f in sorted(findings, key=lambda f: f.baseline_key):
        key = f.baseline_key
        if key in seen:
            continue
        seen.add(key)
        justification = previous.get(key, "").strip() or "TODO: justify"
        lines.append("\t".join([*key, justification]))
    return "\n".join(lines) + "\n"
