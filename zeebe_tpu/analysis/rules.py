"""zlint rule catalog: the repo's load-bearing invariants as AST visitors.

Four rule families plus the drift-copy detector. Each rule's *scope* (which
files/functions it applies to) is constructor-injectable so the fixture
tests under tests/fixtures/lint/ can point a rule at an arbitrary file; the
module-level ``RULES`` list carries the production scopes.

Honest limits (documented in docs/static-analysis.md): matching is
syntactic over resolved import aliases — a banned call laundered through a
variable (``f = time.time; f()``) escapes the AST; the runtime sanitizer
(zeebe_tpu/testing/sanitizer.py) is the dynamic complement that catches
what ASTs can't see.
"""

from __future__ import annotations

import ast
import copy
import hashlib
from typing import Iterable

from zeebe_tpu.analysis.framework import Finding, ParsedModule, Rule

# ---------------------------------------------------------------------------
# shared helpers


def _import_aliases(tree: ast.Module) -> dict[str, str]:
    """{local name: canonical dotted name} from every import statement in the
    module (any nesting level) — so ``import time as _t; _t.time()`` and
    ``from time import time`` both resolve to ``time.time``."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".", 1)[0]] = (
                    a.name if a.asname else a.name.split(".", 1)[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _dotted(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Canonical dotted name of a Name/Attribute chain, alias-resolved; None
    for anything more dynamic (subscripts, calls)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(aliases.get(node.id, node.id))
    return ".".join(reversed(parts))


def _matches(dotted: str, banned: Iterable[str]) -> str | None:
    """The banned pattern ``dotted`` hits, if any: exact names or
    ``prefix.*`` wildcard patterns."""
    for pattern in banned:
        if pattern.endswith(".*"):
            if dotted.startswith(pattern[:-1]):
                return pattern
        elif dotted == pattern:
            return pattern
    return None


def _validate_scoped_entries(rule: Rule, entries, modules,
                             what: str) -> list[Finding]:
    """Shared stale-registration check for (path, qualname-prefix | None)
    tables: the path must name a linted module and the prefix (when given)
    must still resolve to a function scope in it."""
    by_path = {m.relpath: m for m in modules}
    out: list[Finding] = []
    for path, prefix in entries:
        module = by_path.get(path)
        if module is None:
            out.append(rule.registration_finding(
                f"{path} :: {prefix or '<whole module>'}",
                f"stale {what} registration: `{path}` matches no linted "
                f"file — the file was moved/renamed and this rule is "
                f"silently scanning nothing; update the registration"))
        elif prefix is not None and not module.has_function(prefix):
            out.append(rule.registration_finding(
                f"{path} :: {prefix}",
                f"stale {what} registration: `{prefix}` no longer names a "
                f"function in {path} — the symbol was renamed and this "
                f"rule is silently scanning nothing; update the "
                f"registration"))
    return out


# ---------------------------------------------------------------------------
# rule 1: replay determinism


_NONDETERMINISTIC_CALLS = (
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today", "datetime.now",
    "datetime.utcnow", "datetime.today", "date.today",
    "random.*", "os.urandom", "uuid.*", "secrets.*",
    "os.environ.get", "os.getenv", "hash",
)

#: construct → called-with wrappers that MAKE the order deterministic
_ORDERING_SANITIZERS = {"sorted", "len", "sum", "min", "max", "any", "all"}

#: wrappers that PRESERVE the unordered iteration order (flagged)
_ORDER_SENSITIVE_WRAPPERS = {"list", "tuple", "enumerate", "iter", "next"}


def _is_unordered_expr(node: ast.AST, aliases: dict[str, str]) -> bool:
    """Syntactically-recognizable unordered collection: a set literal, a set
    comprehension, or a direct set()/frozenset() construction."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func, aliases)
        return dotted in ("set", "frozenset")
    return False


class ReplayDeterminismRule(Rule):
    """No wall clocks / RNGs / env reads / set-iteration-order dependence in
    replay-deterministic code: appliers, state facades, and
    ``BurstTemplate.apply_state``. Replay must rebuild byte-identical state
    (Raft determinism), and each of these constructs can differ between the
    processing run and the replay run."""

    name = "replay-determinism"
    summary = ("appliers/state facades must be clock-, RNG-, env- and "
               "set-order-free: replay rebuilds state from the log alone")

    #: (path, scope-qualname-prefix | None=whole module)
    DEFAULT_SCOPE = (
        ("zeebe_tpu/engine/appliers.py", None),
        ("zeebe_tpu/engine/engine_state.py", None),
        ("zeebe_tpu/engine/burst_templates.py", "BurstTemplate.apply_state"),
        ("zeebe_tpu/state/db.py", None),
        ("zeebe_tpu/state/durable.py", None),
        ("zeebe_tpu/state/tiering.py", None),
        ("zeebe_tpu/state/snapshot.py", None),
        ("zeebe_tpu/state/request_dedupe.py", None),
    )

    def __init__(self, scope=None) -> None:
        self.scope = self.DEFAULT_SCOPE if scope is None else tuple(scope)

    def validate(self, modules):
        return _validate_scoped_entries(self, self.scope, modules,
                                        "determinism-scope")

    def _in_scope(self, module: ParsedModule, node: ast.AST) -> bool:
        for path, prefix in self.scope:
            if module.relpath != path:
                continue
            if prefix is None:
                return True
            qual = module.scope_of(node)
            if qual == prefix or qual.startswith(prefix + "."):
                return True
        return False

    def check(self, module: ParsedModule) -> list[Finding]:
        if not any(module.relpath == path for path, _ in self.scope):
            return []
        aliases = _import_aliases(module.tree)
        out: list[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            if (self._in_scope(module, node)
                    and not module.is_suppressed(self.name, node)):
                out.append(module.finding(self.name, node, message))

        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func, aliases)
                if dotted is not None:
                    hit = _matches(dotted, _NONDETERMINISTIC_CALLS)
                    if hit is not None:
                        flag(node, f"nondeterministic call `{dotted}` in "
                                   f"replay-deterministic code (banned: {hit})")
                # order-preserving wrapper over an unordered collection
                if (dotted in _ORDER_SENSITIVE_WRAPPERS and node.args
                        and _is_unordered_expr(node.args[0], aliases)):
                    flag(node, f"`{dotted}(...)` over a set preserves "
                               f"arbitrary iteration order — wrap in "
                               f"sorted(...) to make replay deterministic")
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "join" and node.args
                        and _is_unordered_expr(node.args[0], aliases)):
                    flag(node, "`.join(...)` over a set depends on set "
                               "iteration order — sort first")
            elif isinstance(node, ast.For):
                if _is_unordered_expr(node.iter, aliases):
                    flag(node.iter, "iterating a set in replay-deterministic "
                                    "code — iteration order is arbitrary; "
                                    "wrap in sorted(...)")
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    if _is_unordered_expr(gen.iter, aliases):
                        flag(gen.iter, "comprehension over a set in "
                                       "replay-deterministic code — wrap in "
                                       "sorted(...)")
            elif (isinstance(node, ast.Subscript)
                  and _dotted(node.value, aliases) == "os.environ"):
                flag(node, "os.environ read in replay-deterministic code — "
                           "environment can differ between processing and "
                           "replay nodes")
        return out


# ---------------------------------------------------------------------------
# rule 2: device-call discipline


_DEVICE_CALLS = (
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend",
    "jax.lib.xla_bridge.get_backend", "jaxlib.xla_bridge.get_backend",
    "jax.extend.backend.get_backend",
)


class DeviceCallDisciplineRule(Rule):
    """No in-process default-backend initialization outside the killable
    probe: on this host class a wedged TPU tunnel hangs ``jax.devices()``
    forever (three 240s timeouts in BENCH.json probe_attempts), so every
    device query must route through ``utils/backend_probe`` (subprocess +
    SIGKILL deadline) or ``parallel/mesh.resolve_mesh_devices`` (which
    delegates to it)."""

    name = "device-call-discipline"
    summary = ("jax.devices()/backend init only inside utils/backend_probe "
               "and parallel/mesh.resolve_mesh_devices")

    #: (path, scope-prefix | None) locations allowed to touch the backend
    DEFAULT_ALLOWED = (
        ("zeebe_tpu/utils/backend_probe.py", None),
        ("zeebe_tpu/parallel/mesh.py", "resolve_mesh_devices"),
    )

    def __init__(self, allowed=None) -> None:
        self.allowed = (self.DEFAULT_ALLOWED if allowed is None
                        else tuple(allowed))

    def validate(self, modules):
        return _validate_scoped_entries(self, self.allowed, modules,
                                        "allowed-location")

    def _allowed(self, module: ParsedModule, node: ast.AST) -> bool:
        for path, prefix in self.allowed:
            if module.relpath != path:
                continue
            if prefix is None:
                return True
            qual = module.scope_of(node)
            if qual == prefix or qual.startswith(prefix + "."):
                return True
        return False

    def check(self, module: ParsedModule) -> list[Finding]:
        aliases = _import_aliases(module.tree)
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func, aliases)
            if dotted is None or _matches(dotted, _DEVICE_CALLS) is None:
                continue
            if self._allowed(module, node):
                continue
            if module.is_suppressed(self.name, node):
                continue
            out.append(module.finding(
                self.name, node,
                f"in-process device/backend query `{dotted}` outside the "
                f"killable probe — a wedged TPU tunnel hangs this forever; "
                f"route through utils/backend_probe or "
                f"parallel.mesh.resolve_mesh_devices"))
        return out


# ---------------------------------------------------------------------------
# rule 3: pump-thread hygiene


_BLOCKING_CALLS = (
    "time.sleep", "os.fsync", "os.sync",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "socket.socket", "socket.create_connection",
    "urllib.request.urlopen", "requests.get", "requests.post",
    "requests.request",
)


class PumpBlockingIoRule(Rule):
    """No blocking I/O reachable (same-module) from a pump hook: the pump IS
    the partition's scheduler — one fsync or sleep on it stalls processing,
    exporters, snapshots, and ingress acks for every instance the partition
    serves. Roots: every function literally named ``pump`` plus the
    registered pump-stage extras below; reachability follows same-module
    ``self.x()`` / bare-name calls (cross-module blocking sinks are the
    runtime sanitizer's job)."""

    name = "pump-blocking-io"
    summary = ("no time.sleep/os.fsync/subprocess/socket calls reachable "
               "from pump hooks or kernel-dispatch stages")

    #: (path, root-qualname) pump-stage functions beyond the `pump` methods:
    #: ingress handlers and dispatch stages the broker drives from its pump
    #: thread. Registering a new pump hook means adding it here (and the
    #: fixture test pins the mechanism).
    DEFAULT_EXTRA_ROOTS = (
        ("zeebe_tpu/multiproc/worker.py", "WorkerRuntime._on_client_command"),
        ("zeebe_tpu/stream/processor.py", "StreamProcessor.run_until_idle"),
        ("zeebe_tpu/stream/processor.py", "StreamProcessor.replay_available"),
        ("zeebe_tpu/exporters/director.py", "ExporterDirector.export_available"),
        ("zeebe_tpu/engine/kernel_backend.py", "KernelBackend.process_group"),
        ("zeebe_tpu/engine/kernel_backend.py", "KernelBackend.begin_group"),
        ("zeebe_tpu/engine/kernel_backend.py", "KernelBackend.finish_group"),
        # at-rest storage scrubber (ISSUE 14): its slice runs between
        # transactions on the partition pump — a sleep or fsync slipped
        # into a scrub walk stalls the whole partition
        ("zeebe_tpu/broker/scrubber.py", "StorageScrubber.maybe_run"),
    )

    def __init__(self, extra_roots=None) -> None:
        self.extra_roots = (self.DEFAULT_EXTRA_ROOTS if extra_roots is None
                            else tuple(extra_roots))

    def validate(self, modules):
        return _validate_scoped_entries(self, self.extra_roots, modules,
                                        "pump-root")

    @staticmethod
    def _function_index(module: ParsedModule) -> dict[str, ast.AST]:
        index: dict[str, ast.AST] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # scope_of(def) is the def's own qualname (it includes the
                # function's name segment)
                index[module.scope_of(node)] = node
        return index

    @staticmethod
    def _callees(qual: str, fn: ast.AST, index: dict[str, ast.AST]
                 ) -> set[str]:
        """Same-module callees of ``fn``: ``self.x()`` resolves within the
        enclosing class, bare ``x()`` at module level."""
        cls = qual.rsplit(".", 2)[0] if qual.count(".") >= 1 else None
        out: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                    and f.value.id in ("self", "cls") and cls is not None):
                candidate = f"{cls}.{f.attr}"
                if candidate in index:
                    out.add(candidate)
            elif isinstance(f, ast.Name) and f.id in index:
                out.add(f.id)
        return out

    def _roots(self, module: ParsedModule,
               index: dict[str, ast.AST]) -> list[str]:
        roots = [q for q in index
                 if q == "pump" or q.endswith(".pump")]
        for path, qual in self.extra_roots:
            if module.relpath == path and qual in index:
                roots.append(qual)
        return roots

    def check(self, module: ParsedModule) -> list[Finding]:
        index = self._function_index(module)
        roots = self._roots(module, index)
        if not roots:
            return []
        reachable: set[str] = set()
        frontier = list(roots)
        while frontier:
            qual = frontier.pop()
            if qual in reachable:
                continue
            reachable.add(qual)
            frontier.extend(self._callees(qual, index[qual], index))
        aliases = _import_aliases(module.tree)
        out: list[Finding] = []
        for qual in sorted(reachable):
            for node in ast.walk(index[qual]):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted(node.func, aliases)
                if dotted is None or _matches(dotted, _BLOCKING_CALLS) is None:
                    continue
                if module.is_suppressed(self.name, node):
                    continue
                out.append(module.finding(
                    self.name, node,
                    f"blocking call `{dotted}` reachable from pump hook "
                    f"`{qual}` — the pump is the partition's scheduler; "
                    f"one stall here stalls processing, exporters, and "
                    f"ingress acks"))
        return out


# ---------------------------------------------------------------------------
# rule 4: committed-read discipline


_TRANSACTIONAL_ATTRS = ("transaction", "require_transaction", "column_family")


class CommittedReadDisciplineRule(Rule):
    """Ingress/query modules may only read partition state through the
    committed accessors (``ZbDb.committed_get`` / ``committed_keys_of`` /
    ``Partition.lookup_request``): opening the processing-owned transaction
    slot from a gateway or management thread races the pump thread's own
    transaction (the PR 8 ColdStore dict-changed-size class, generalized)."""

    name = "committed-read-discipline"
    summary = ("gateway/query threads read via committed_* accessors only — "
               "never the processing-owned transaction slot")

    DEFAULT_SCOPE = (
        "zeebe_tpu/gateway/",
        "zeebe_tpu/engine/query.py",
        "zeebe_tpu/broker/management.py",
        "zeebe_tpu/multiproc/runtime.py",
    )

    def __init__(self, scope=None) -> None:
        self.scope = self.DEFAULT_SCOPE if scope is None else tuple(scope)

    def validate(self, modules):
        out = []
        for entry in self.scope:
            if not any(m.relpath == entry or m.relpath.startswith(entry)
                       for m in modules):
                out.append(self.registration_finding(
                    entry,
                    f"stale ingress/query-scope registration: `{entry}` "
                    f"matches no linted file — the module was "
                    f"moved/renamed and this rule is silently scanning "
                    f"nothing; update the registration"))
        return out

    def _in_scope(self, module: ParsedModule) -> bool:
        return any(module.relpath == p or module.relpath.startswith(p)
                   for p in self.scope)

    def check(self, module: ParsedModule) -> list[Finding]:
        if not self._in_scope(module):
            return []
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _TRANSACTIONAL_ATTRS):
                if module.is_suppressed(self.name, node):
                    continue
                out.append(module.finding(
                    self.name, node,
                    f"`.{node.func.attr}(...)` in an ingress/query module — "
                    f"gateway and management threads must use "
                    f"ZbDb.committed_get / committed_keys_of / "
                    f"Partition.lookup_request; the transaction slot belongs "
                    f"to the pump thread"))
            elif (isinstance(node, ast.Attribute) and node.attr == "_data"
                  and ((isinstance(node.value, ast.Attribute)
                        and node.value.attr.lower().endswith("db"))
                       or (isinstance(node.value, ast.Name)
                           and node.value.id.lower().endswith("db")))):
                if module.is_suppressed(self.name, node):
                    continue
                out.append(module.finding(
                    self.name, node,
                    "raw `._data` access on a state store in an ingress/query "
                    "module — use the committed_* accessors"))
        return out


# ---------------------------------------------------------------------------
# rule 5: control actuation discipline (ISSUE 12)


#: runtime knobs owned by a registered control-plane loop: the attribute
#: name plus the loop that owns its write path. The audit trail
#: (control_adjust flight events + zeebe_control_* metrics) is only
#: trustworthy if the actuator is the SINGLE runtime write path — a direct
#: assignment anywhere else mutates the knob invisibly.
_CONTROLLER_OWNED_ATTRS = {
    "flush_interval_s": "journal-flush controller (raft group-commit pacing)",
    "coalesce_window_ms": "ingress-coalescing controller (worker ingress "
                          "batch window)",
    "park_after_ms": "state-tiering controller (TieringCfg park horizon)",
    "spill_batch": "state-tiering controller (TieringCfg spill batch)",
    "route_threshold_s": "kernel-routing controller (BackendRouter "
                         "host-vs-device threshold)",
    "shed_level": "admission shed ladder (aggregated loop)",
}


class ControlActuationDisciplineRule(Rule):
    """Runtime mutation of a controller-owned knob outside a registered
    Actuator: assignments to the attributes above are legal only inside
    ``zeebe_tpu/control/`` (the actuator framework) or in ``__init__``
    (construction seeds the static default — it is configuration, not a
    runtime decision). Anything else bypasses the bounds clamp, the
    max-step pacing, and the control_adjust audit trail; intentional
    exceptions (a loop that IS its own registered decision engine, like
    the admission shed ladder) are baselined with justifications.

    Honest limit (docs/static-analysis.md): ``setattr(obj, "knob", v)``
    with a dynamic name escapes the AST — the runtime sanitizer's
    actuator-thread assertion is the dynamic complement."""

    name = "control-actuation-discipline"
    summary = ("controller-owned runtime knobs mutate only through "
               "zeebe_tpu/control actuators (construction in __init__ "
               "exempt)")
    cross_module = True

    #: module prefixes allowed to assign owned knobs (the actuator home)
    DEFAULT_ALLOWED_PREFIXES = ("zeebe_tpu/control/",)

    def __init__(self, allowed_prefixes=None, owned=None) -> None:
        self.allowed_prefixes = (self.DEFAULT_ALLOWED_PREFIXES
                                 if allowed_prefixes is None
                                 else tuple(allowed_prefixes))
        self.owned = (_CONTROLLER_OWNED_ATTRS if owned is None
                      else dict(owned))

    @staticmethod
    def _attr_targets(node: ast.AST):
        """Attribute nodes assigned by this statement (tuple targets and
        augmented/annotated assignments included)."""
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        out = []
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Attribute):
                out.append(t)
        return out

    def check_tree(self, modules: list[ParsedModule]) -> list[Finding]:
        out: list[Finding] = []
        seen_attrs: set[str] = set()
        for module in modules:
            allowed_module = any(module.relpath.startswith(p)
                                 for p in self.allowed_prefixes)
            for node in ast.walk(module.tree):
                for target in self._attr_targets(node):
                    attr = target.attr
                    if attr not in self.owned:
                        continue
                    seen_attrs.add(attr)
                    if allowed_module:
                        continue
                    scope = module.scope_of(node)
                    if scope == "__init__" or scope.endswith(".__init__"):
                        continue  # construction seeds the static default
                    if module.is_suppressed(self.name, node):
                        continue
                    out.append(module.finding(
                        self.name, node,
                        f"runtime mutation of controller-owned knob "
                        f"`.{attr}` outside a registered actuator — owned "
                        f"by the {self.owned[attr]}; route the change "
                        f"through zeebe_tpu/control (bounds, pacing, and "
                        f"the control_adjust audit trail live there)"))
        # stale-registration analogue: an owned attr that no linted module
        # even assigns any more was renamed/removed — the registration is
        # silently guarding nothing
        for attr in sorted(set(self.owned) - seen_attrs):
            out.append(self.registration_finding(
                attr,
                f"stale controller-owned-knob registration: `.{attr}` is "
                f"assigned nowhere in the linted tree — the knob was "
                f"renamed or removed; update _CONTROLLER_OWNED_ATTRS"))
        return out


# ---------------------------------------------------------------------------
# rule 6: drift-copy detection


class _Normalizer(ast.NodeTransformer):
    """Alpha-rename names/args, drop annotations/defaults/decorators, and
    collapse string constants and f-strings — so two functions that differ
    only in identifier choice and message wording hash identically."""

    def __init__(self) -> None:
        self._names: dict[str, str] = {}

    def _map(self, name: str) -> str:
        return self._names.setdefault(name, f"n{len(self._names)}")

    def visit_Name(self, node: ast.Name):
        return ast.copy_location(
            ast.Name(id=self._map(node.id), ctx=node.ctx), node)

    def visit_arg(self, node: ast.arg):
        node.arg = self._map(node.arg)
        node.annotation = None
        return node

    def visit_FunctionDef(self, node: ast.FunctionDef):
        node.name = self._map(node.name)
        node.returns = None
        node.decorator_list = []
        self.generic_visit(node)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_JoinedStr(self, node: ast.JoinedStr):
        return ast.copy_location(ast.Constant(value=""), node)

    def visit_Constant(self, node: ast.Constant):
        if isinstance(node.value, str):
            return ast.copy_location(ast.Constant(value=""), node)
        return node


def _body_sans_docstring(fn: ast.AST) -> list[ast.stmt]:
    body = list(fn.body)
    if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant) and isinstance(
            body[0].value.value, str):
        body = body[1:]
    return body


def _body_size(fn: ast.AST) -> int:
    """Recursive statement count of the (docstring-stripped) body: a
    4-statement body wrapping a 20-statement loop is a copy worth catching,
    not idiom. Computed on the ORIGINAL node so the threshold filters
    before the expensive deepcopy/normalize/dump pass."""
    return sum(isinstance(n, ast.stmt)
               for stmt in _body_sans_docstring(fn) for n in ast.walk(stmt))


def _normalized_fingerprint(fn: ast.AST) -> str:
    """sha1 of the alpha-normalized body dump — docstring stripped so
    commenting a copy doesn't hide it."""
    fn = copy.deepcopy(fn)
    fn.body = _body_sans_docstring(fn) or [ast.Pass()]
    normalizer = _Normalizer()
    fn = normalizer.visit(fn)
    dump = ast.dump(ast.Module(body=fn.body, type_ignores=[]))
    return hashlib.sha1(dump.encode()).hexdigest()


class DriftCopyRule(Rule):
    """Silently drifted code copies: two functions whose alpha-normalized
    bodies are identical are one function written twice — the next fix will
    land in one of them (PR 9 found exactly this in the gate harnesses).
    Extract the shared helper instead."""

    name = "drift-copy"
    summary = ("no near-identical function bodies across the tree — "
               "extract the shared helper before the copies drift")
    cross_module = True

    #: bodies with fewer total (recursive) statements are idiom, not copies
    MIN_BODY_STATEMENTS = 8

    def __init__(self, min_body_statements: int | None = None) -> None:
        if min_body_statements is not None:
            self.MIN_BODY_STATEMENTS = min_body_statements

    def check_tree(self, modules: list[ParsedModule]) -> list[Finding]:
        groups: dict[str, list[tuple[ParsedModule, str, ast.AST]]] = {}
        for module in modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if _body_size(node) < self.MIN_BODY_STATEMENTS:
                    continue
                digest = _normalized_fingerprint(node)
                groups.setdefault(digest, []).append(
                    (module, module.scope_of(node), node))
        out: list[Finding] = []
        for twins in groups.values():
            if len(twins) < 2:
                continue
            labels = [f"{m.relpath}:{q}" for m, q, _ in twins]
            for module, qual, node in twins:
                if module.is_suppressed(self.name, node):
                    continue
                others = ", ".join(l for l in labels
                                   if l != f"{module.relpath}:{qual}")
                out.append(module.finding(
                    self.name, node,
                    f"`{qual}` is a drift-copy of {others} — identical "
                    f"normalized body; extract one shared helper"))
        return out


# ---------------------------------------------------------------------------
# rule 7: storage IO discipline (ISSUE 14)


#: syscall-shaped calls that must route through the seam in storage modules
_STORAGE_IO_CALLS = (
    "os.fsync", "os.replace", "os.pwrite", "os.open", "os.rename",
)
_STORAGE_IO_BARE_CALLS = ("open",)
#: attribute-call names that write a file when invoked on a Path
_STORAGE_IO_WRITE_ATTRS = ("write_bytes", "write_text")


class StorageIoDisciplineRule(Rule):
    """Storage modules (journal, snapshot store, cold tier, backup store)
    perform file IO only through ``zeebe_tpu/utils/storage_io.py`` — the
    one seam the disk-fault injector (``ZEEBE_CHAOS_DISK``) and therefore
    the whole torture gate's coverage claim hang off. A direct ``open`` /
    ``os.fsync`` / ``os.replace`` / ``write_bytes`` in a storage module is
    a write (or a durability barrier) the chaos plane cannot fault and the
    fsyncgate handling cannot protect; deliberate exceptions (read-only
    inspection helpers, advisory evidence files) are baselined with
    justifications."""

    name = "storage-io-discipline"
    summary = ("journal/snapshot/tiering/backup file IO routes through "
               "utils/storage_io (the disk-fault seam) — no direct "
               "open/os.fsync/os.replace/write_bytes")

    #: the storage modules under the seam's contract
    DEFAULT_SCOPE = (
        "zeebe_tpu/journal/journal.py",
        "zeebe_tpu/state/snapshot.py",
        "zeebe_tpu/state/tiering.py",
        "zeebe_tpu/backup/store.py",
    )
    #: the seam itself is the only place the raw calls are legal
    SEAM = "zeebe_tpu/utils/storage_io.py"

    def __init__(self, scope=None) -> None:
        self.scope = self.DEFAULT_SCOPE if scope is None else tuple(scope)

    def validate(self, modules):
        out = []
        for entry in self.scope:
            if not any(m.relpath == entry for m in modules):
                out.append(self.registration_finding(
                    entry,
                    f"stale storage-module registration: `{entry}` matches "
                    f"no linted file — the module was moved/renamed and "
                    f"this rule is silently scanning nothing; update the "
                    f"registration"))
        return out

    def check(self, module: ParsedModule) -> list[Finding]:
        if module.relpath not in self.scope:
            return []
        aliases = _import_aliases(module.tree)
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func, aliases)
            if dotted is not None and dotted.startswith(
                    "zeebe_tpu.utils.storage_io."):
                continue  # a call INTO the seam is the whole point
            hit = None
            if dotted is not None:
                if _matches(dotted, _STORAGE_IO_CALLS) is not None:
                    hit = dotted
                elif dotted in _STORAGE_IO_BARE_CALLS:
                    hit = dotted
            if (hit is None and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _STORAGE_IO_WRITE_ATTRS):
                hit = f".{node.func.attr}"
            if hit is None:
                continue
            if module.is_suppressed(self.name, node):
                continue
            out.append(module.finding(
                self.name, node,
                f"direct file IO `{hit}(...)` in a storage module — route "
                f"through zeebe_tpu.utils.storage_io (open_file/fsync/"
                f"pwrite/replace/write_bytes); bypassing the seam makes "
                f"this write invisible to disk-fault injection and the "
                f"at-rest scrub/torture coverage claim"))
        return out


#: the device-result decode/ingestion primitives: calls that turn raw
#: device output into host-side data the engine could commit
_KERNEL_RESULT_CALLS = (
    "zeebe_tpu.ops.automaton.unpack_events",
    "zeebe_tpu.ops.automaton.run_collect",
    "jax.device_get",
)


class KernelResultCommitDisciplineRule(Rule):
    """Kernel group results may only enter the group transaction through
    the validation/shadow seam (ISSUE 15): inside ``engine/`` and
    ``stream/`` the device-result primitives — ``run_collect`` dispatch,
    ``jax.device_get`` fetch, ``unpack_events`` decode — are legal ONLY in
    the registered seam functions of ``engine/kernel_backend.py``
    (``_dispatch_first_chunk`` / ``_complete_device_run`` / ``_fetch_rows``
    / ``_shadow_execute``), whose results flow to materialization
    exclusively via ``finish_group``'s shadow-verification gate. A direct
    fetch+decode anywhere else is a path for silently-corrupted device
    output to reach the replicated log without the watchdog, the chaos
    seam, or shadow verification ever seeing it. (The mesh runner lives
    under ``parallel/`` and is covered at its ``submit`` seam — an honest
    scope limit documented in docs/static-analysis.md.)"""

    name = "kernel-result-commit-discipline"
    summary = ("device-result primitives (run_collect/device_get/"
               "unpack_events) in engine//stream/ only inside the "
               "kernel_backend dispatch/shadow seam")

    DEFAULT_SCOPE_PREFIXES = ("zeebe_tpu/engine/", "zeebe_tpu/stream/")
    SEAM_MODULE = "zeebe_tpu/engine/kernel_backend.py"
    DEFAULT_SEAM_SCOPES = (
        "KernelBackend._dispatch_first_chunk",
        "KernelBackend._complete_device_run",
        "KernelBackend._fetch_rows",
        "KernelBackend._shadow_execute",
    )

    def __init__(self, scope_prefixes=None, seam_module=None,
                 seam_scopes=None) -> None:
        self.scope_prefixes = (self.DEFAULT_SCOPE_PREFIXES
                               if scope_prefixes is None
                               else tuple(scope_prefixes))
        self.seam_module = (self.SEAM_MODULE if seam_module is None
                            else seam_module)
        self.seam_scopes = (self.DEFAULT_SEAM_SCOPES if seam_scopes is None
                            else tuple(seam_scopes))

    def validate(self, modules):
        return _validate_scoped_entries(
            self, [(self.seam_module, prefix) for prefix in self.seam_scopes],
            modules, "kernel-result seam")

    def _in_seam(self, module: ParsedModule, node: ast.AST) -> bool:
        if module.relpath != self.seam_module:
            return False
        scope = module.scope_of(node)
        return any(scope == s or scope.startswith(s + ".")
                   for s in self.seam_scopes)

    def check(self, module: ParsedModule) -> list[Finding]:
        if not module.relpath.startswith(self.scope_prefixes):
            return []
        aliases = _import_aliases(module.tree)
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func, aliases)
            if dotted is None or _matches(dotted, _KERNEL_RESULT_CALLS) is None:
                continue
            if self._in_seam(module, node):
                continue
            if module.is_suppressed(self.name, node):
                continue
            out.append(module.finding(
                self.name, node,
                f"device-result primitive `{dotted}(...)` outside the "
                f"kernel dispatch/shadow seam — device output may only "
                f"enter the group transaction through "
                f"KernelBackend.finish_group's validation gate "
                f"({self.seam_module}); a direct fetch/decode here "
                f"bypasses the watchdog, the chaos seam, and shadow "
                f"verification"))
        return out


RULES: list[Rule] = [
    ReplayDeterminismRule(),
    DeviceCallDisciplineRule(),
    PumpBlockingIoRule(),
    CommittedReadDisciplineRule(),
    ControlActuationDisciplineRule(),
    DriftCopyRule(),
    StorageIoDisciplineRule(),
    KernelResultCommitDisciplineRule(),
]
