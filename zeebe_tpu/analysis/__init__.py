"""zlint — the repo's own AST-based invariant linter.

The engine's correctness story rests on replicated-state-machine determinism:
replay must rebuild byte-identical state, so appliers and state facades can
never touch wall clocks, RNGs, or iteration-order-sensitive constructs; no
code may initialize the default jax backend outside the killable probe; pump
hooks must never block; ingress/query threads must read through committed
accessors. Every one of those is an *architectural invariant* that reviewers
kept re-discovering by hand (the wedged-tunnel rule, the ColdStore
dict-changed-size fix, the drifted `_collect_flight_dumps` copies) — zlint
machine-checks them instead.

Entry points (stdlib-only — the linter must never pull the jax stack):

- ``run_lint(root)``        → list[Finding] over the package + bench.py
- ``python -m zeebe_tpu.cli lint [--check] [--update-baseline]``
- ``python -m zeebe_tpu.cli knobs-doc [--check]`` (env-knob drift gate)

Rule catalog, suppression syntax, and how to add a rule:
docs/static-analysis.md.
"""

from zeebe_tpu.analysis.framework import (
    BASELINE_FILENAME,
    Finding,
    format_baseline,
    load_baseline,
    run_lint,
    split_findings,
)
from zeebe_tpu.analysis.knobs import render_knobs_doc, scan_knobs
from zeebe_tpu.analysis.rules import RULES

__all__ = [
    "BASELINE_FILENAME",
    "Finding",
    "RULES",
    "format_baseline",
    "load_baseline",
    "render_knobs_doc",
    "run_lint",
    "scan_knobs",
    "split_findings",
]
