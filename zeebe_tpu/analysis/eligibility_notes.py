"""Curated one-liners for the kernel-eligibility reason catalog — the
human half of the generated ``docs/eligibility.md`` (the ``knobs-doc``
drift discipline applied to path-routing reasons, ISSUE 13).

Every reason code in ``zeebe_tpu.engine.eligibility``'s catalog MUST have
an entry here: ``cli eligibility-doc --check`` fails on a missing note
(an explained fallback is the whole point of the catalog) or on drift
between the generated doc and the committed file; a note for a retired
code fails the same gate as stale.
"""

from __future__ import annotations

#: reason code → one-line operator-facing explanation. Grouped to match
#: the catalog's split; the renderer sorts within each group.
REASON_NOTES: dict[str, str] = {
    # -- static, element-level (the element forces the host path) ----------
    "multi-instance": (
        "multi-instance activity outside the device K_MI subset (container "
        "body, dynamic collection expression, boundaries/mappings on the "
        "body, or an unstructured/cyclic graph around it)"),
    "io-mapping-nontask": (
        "io mappings on a non-job-worker element — only K_TASK elements "
        "evaluate mappings on the kernel path"),
    "unsafe-expression": (
        "an io-mapping or script expression that can raise mid-burst "
        "(arithmetic, ordered comparison, function call) — the device "
        "routes tokens before the materializer evaluates it"),
    "output-writes-condition-var": (
        "an output mapping / script result writes a variable some flow "
        "condition reads — device condition slots are prefetched at "
        "admission and would not see the write"),
    "user-task": "native user task: lifecycle lives in host-side processors",
    "called-decision": (
        "business-rule task with a called decision: DMN evaluation is "
        "host-side"),
    "script-task-shape": (
        "expression-flavor script task with a disqualifying shape (job "
        "type, io mappings, or boundary events attached)"),
    "timer-cycle-date": (
        "cycle (R/...) or date timer: its wait state is not "
        "kernel-reconstructable — only fixed-duration timers park on "
        "device"),
    "escalation-boundary": (
        "interrupting/non-interrupting escalation boundary: escalations "
        "fire from child scopes through host-side catch resolution"),
    "boundary-unsupported": (
        "boundary event whose subscription kind the parked-task "
        "reconstruction cannot count (or an attached boundary that itself "
        "host-escapes)"),
    "boundary-on-nontask": (
        "boundary events attached to a non-job-worker element: wait-state "
        "reconstruction is implemented for parked K_TASK elements only"),
    "subprocess-no-none-start": (
        "embedded sub-process without a none start event cannot enter as a "
        "device K_SCOPE"),
    "subprocess-event-subprocess": (
        "embedded sub-process hosting event sub-processes: scope "
        "reconstruction does not collect their trigger state"),
    "call-activity-unresolved": (
        "call activity whose called definition could not be statically "
        "inlined (dynamic process id, unresolvable or undeployed target)"),
    "event-gateway-target": (
        "event-based gateway with a succeeding catch the reconstruction "
        "cannot count (or no outgoing flows)"),
    "link-unresolved": "link throw event with no same-scope catch to bind",
    "catch-unsupported": (
        "intermediate catch / receive task without a reconstructable wait "
        "state (no message/signal name, or a mixed timer+message shape)"),
    "unsupported-element": (
        "element type outside the device opcode subset (inclusive "
        "gateway, compensation, transaction, ...)"),
    "event-type-unsupported": (
        "event flavor outside the device subset on an otherwise-lowerable "
        "element (e.g. a message end event)"),
    "job-type-dynamic": (
        "job type or retries is a runtime expression — kernel task rows "
        "need deploy-time constants"),
    "event-subprocess-body": (
        "element inside an event sub-process: individually eligible, but "
        "tokens only enter through the host-routed start event (ROADMAP "
        "item 3's message-start event-sub-process children)"),
    "condition-not-compilable": (
        "the solo/shared lowering downgraded the element (or declined the "
        "definition): a flow condition outside the device VM subset, or a "
        "SlotMap kind clash across co-deployed definitions"),
    # -- static, definition-level ------------------------------------------
    "no-none-start": (
        "definition has only message/timer starts: every creation carries "
        "an explicit start element, so the kernel's none-start entry path "
        "has nothing to run"),
    "esp-start-unsupported": (
        "a root event sub-process start whose subscription the root "
        "wait-state reconstruction cannot count (e.g. cycle/date timer "
        "start)"),
    "table-set-full": (
        "the partition's kernel registry hit max_definitions — "
        "deployment-SET-dependent: visible only when classifying the whole "
        "set against one shared registry"),
    # -- runtime-only (never statically predictable) ------------------------
    "geometry-bounds": (
        "group geometry exceeded the bit-packed event tensor bounds "
        "(T > PACK_MAX_TOKENS or E >= PACK_MAX_ELEMENTS)"),
    "no-quiesce": (
        "the group did not quiesce within max_steps device steps — "
        "sequential path re-runs the head"),
    "token-overflow": (
        "the device token pool overflowed (T undersized for the group's "
        "actual fan-out)"),
    "mesh-dispatch-error": "the shared mesh runner's dispatch errored",
    "mesh-no-quiesce": "a mesh-coalesced group did not quiesce",
    "mesh-token-overflow": "a mesh-coalesced group overflowed its pool",
    "group-error": (
        "the group's processing raised before any append — transaction "
        "rolled back, head re-processed sequentially"),
    "device-dispatch-error": (
        "a device compile/dispatch/fetch exception was contained at the "
        "kernel dispatch seam — the group abandoned, the head host "
        "re-executed, the device health ladder notified"),
    "device-wedged": (
        "a device dispatch exceeded the per-dispatch watchdog deadline "
        "(ZEEBE_BROKER_DEVICE_DISPATCHTIMEOUTMS) — the gray-failure "
        "slow-but-alive shape, contained like a dispatch exception"),
    "device-quarantined": (
        "the broker's device health ladder is QUARANTINED: every group is "
        "host-routed until periodic canary dispatches re-prove the device "
        "against the host oracle"),
    # -- head families (noted as <family>:<VALUE_TYPE>.<INTENT>) ------------
    "head-sequential": (
        "ordinary sequential traffic at the group boundary: the pending "
        "head is a non-candidate command kind (deployment, message "
        "publish, ...)"),
    "head-not-admittable": (
        "a candidate command kind failed admission (unknown/stale "
        "definition, non-default tenant, unpredictable MI cardinality, "
        "un-reconstructable instance state) — a regression signal when "
        "the definition is predicted eligible"),
}


def undocumented_reasons() -> list[str]:
    """Catalog codes without a REASON_NOTES one-liner (CI gate)."""
    from zeebe_tpu.engine.eligibility import ALL_REASONS

    return sorted(ALL_REASONS - set(REASON_NOTES))


def stale_reason_notes() -> list[str]:
    """REASON_NOTES entries whose code left the catalog (CI gate)."""
    from zeebe_tpu.engine.eligibility import ALL_REASONS

    return sorted(set(REASON_NOTES) - ALL_REASONS)


_DOC_HEADER = """\
# Kernel eligibility & path coverage

> Auto-generated by `python -m zeebe_tpu.cli eligibility-doc` from the
> reason catalog in `zeebe_tpu/engine/eligibility.py` and the curated
> notes in `zeebe_tpu/analysis/eligibility_notes.py`. Edit those sources
> and regenerate; CI fails on drift (`cli eligibility-doc --check`).

A record takes the **kernel path** when the stream processor admits it
into a device group (`engine/kernel_backend.py`) and the group's burst
materializes; everything else rides the sequential **host path**. Every
host routing carries a typed reason from the ONE catalog below — the same
codes the static report (`cli eligibility`), the runtime metrics
(`zeebe_kernel_records_total{path,reason}`), the `kernel_wave` flight
events, and the bench parity gate speak.

## How coverage is computed

`coverage = records on the kernel path / total routed records`, where a
"routed record" is a top-level command the processor made a path decision
for: each kernel-group member counts once on the kernel path; each
sequential head counts once on the host path with its reason.
Follow-up commands processed inside a head's batch (or inside a kernel
burst's host-escape drain) ride their head's path and are not separately
counted. The cumulative per-definition ratio is served as
`zeebe_kernel_coverage_ratio{partition,definition}`, on partition
`/health` (`kernelCoverage`), on `/cluster/status` partition rows, and in
`cli top`'s KERNEL section.
"""

_DOC_FOOTER = """\
## Honest caveats

- **Runtime-only reasons are not static-predictable**: a definition the
  report calls fully eligible can still fall back at dispatch time
  (geometry bounds, non-quiescence, pool overflow, mesh errors). The
  parity gate therefore never holds runtime reasons against the
  classifier.
- **Classification is solo**: the report compiles the definition alone.
  Co-deployed definitions can downgrade further through SlotMap kind
  clashes in the shared lowering (`condition-not-compilable` at runtime).
- **Offline classification cannot resolve call activities**: without the
  deployed process state a call activity honestly classifies
  `call-activity-unresolved`; classify `--deployed --data-dir` to inline
  against what is actually deployed.
- **Coverage is per partition, not global**: each partition's accounting
  covers its own log; aggregate across partitions before quoting a
  cluster number.
- **In-batch follow-ups are invisible to the split**: a host-processed
  head's follow-up commands (and a kernel burst's host-escape drain) are
  attributed to the head's path.
"""


def render_eligibility_doc() -> str:
    """docs/eligibility.md content from the catalog + notes."""
    from zeebe_tpu.engine.eligibility import (
        DEFINITION_REASONS,
        HEAD_FAMILIES,
        RUNTIME_REASONS,
        STATIC_ELEMENT_REASONS,
    )

    def cell(text: str) -> str:
        return text.replace("|", "\\|")

    def table(title: str, blurb: str, codes) -> list[str]:
        out = [f"## {title}", "", blurb, "",
               "| reason | meaning |", "| --- | --- |"]
        for code in sorted(codes):
            out.append(f"| `{code}` | {cell(REASON_NOTES.get(code, ''))} |")
        out.append("")
        return out

    lines = [_DOC_HEADER]
    lines += table(
        "Static element-level reasons",
        "Predictable from the definition alone — `cli eligibility` names "
        "the exact element. Retiring one of these (ROADMAP item 3) moves "
        "real records onto the kernel path.",
        STATIC_ELEMENT_REASONS)
    lines += table(
        "Static definition-level reasons",
        "The whole definition declines kernel registration "
        "(`KernelRegistry` records the typed reason the report serves).",
        DEFINITION_REASONS)
    lines += table(
        "Runtime-only reasons",
        "Observable only at dispatch time; excluded from the "
        "static-vs-observed parity gate.",
        RUNTIME_REASONS)
    lines += table(
        "Head families",
        "Noted per sequential head as `<family>:<VALUE_TYPE>.<INTENT>`; "
        "metrics fold them to the family label (bounded cardinality), the "
        "full string stays in `fallback_reasons` / BENCH.",
        HEAD_FAMILIES)
    lines.append(_DOC_FOOTER)
    return "\n".join(lines)
