"""Stream-processing platform (SURVEY.md §2.7)."""

from zeebe_tpu.stream.api import (
    ClientResponse,
    ExceededBatchRecordSizeError,
    FollowUpRecord,
    ProcessingErrorHandling,
    ProcessingResultBuilder,
    ProcessingScheduleService,
    RecordProcessor,
)
from zeebe_tpu.stream.processor import Phase, StreamProcessor, StreamProcessorMode

__all__ = [
    "ClientResponse",
    "ExceededBatchRecordSizeError",
    "FollowUpRecord",
    "Phase",
    "ProcessingErrorHandling",
    "ProcessingResultBuilder",
    "ProcessingScheduleService",
    "RecordProcessor",
    "StreamProcessor",
    "StreamProcessorMode",
]
