"""Stream platform SPI: RecordProcessor, ProcessingResultBuilder, schedule service.

Reference: stream-platform/src/main/java/io/camunda/zeebe/stream/api/
RecordProcessor.java (the seam the engine plugs into), ProcessingResultBuilder.java,
scheduling/ProcessingScheduleService.java, records/TypedRecord.java.

The TPU batch backend registers behind this same SPI (BASELINE.json): a
RecordProcessor whose ``process`` collects device-batchable commands and whose
follow-up records come back from the automaton kernel.
"""

from __future__ import annotations

import abc
import dataclasses
import heapq
from typing import Any, Callable

from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import Record, RejectionType, ValueType


@dataclasses.dataclass(slots=True)
class FollowUpRecord:
    """A record the processor wants appended after the current step."""

    record: Record
    # processed-in-batch: the record is a command that was already processed in
    # the same transaction; replay and later processing must skip it.
    processed: bool = False


@dataclasses.dataclass(slots=True)
class ClientResponse:
    """Response to the client request that carried the command."""

    record: Record
    request_stream_id: int
    request_id: int


def activatable_job_types(follow_ups) -> set[str]:
    """Job types made activatable by a step's follow-up events — the
    jobs-available notification source (reference: the engine's
    JobsAvailableCallback wired through BpmnJobActivationBehavior /
    JobBackoffChecker so gateways can wake parked long-polls and push
    streams instead of polling)."""
    from zeebe_tpu.protocol.intent import JobIntent

    available = set()
    for f in follow_ups:
        rec = f.record
        if rec.value_type != ValueType.JOB or not rec.is_event:
            continue
        intent = int(rec.intent)
        if intent in (int(JobIntent.CREATED), int(JobIntent.TIMED_OUT),
                      int(JobIntent.RECURRED_AFTER_BACKOFF), int(JobIntent.YIELDED)) or (
            intent == int(JobIntent.FAILED)
            and rec.value.get("retries", 0) > 0
            and rec.value.get("retryBackoff", -1) <= 0
        ):
            job_type = rec.value.get("type", "")
            if job_type:
                available.add(job_type)
    return available


class ProcessingResultBuilder:
    """Collects everything one processing step produces: follow-up records, an
    optional client response, and post-commit tasks (side effects).

    ``max_batch_size_bytes`` mirrors the reference's RecordBatch size predicate
    (maxMessageSize): a step whose follow-ups exceed it fails with
    EXCEEDED_BATCH_RECORD_SIZE and is retried unbatched where applicable.
    """

    def __init__(self, max_batch_size_bytes: int = 4 * 1024 * 1024) -> None:
        self.follow_ups: list[FollowUpRecord] = []
        self.response: ClientResponse | None = None
        self.extra_responses: list[ClientResponse] = []
        self.post_commit_tasks: list[Callable[[], None]] = []
        self._size = 0
        self._max_size = max_batch_size_bytes

    def append_record(self, record: Record, processed: bool = False) -> None:
        size = len(record.to_bytes())
        if self._size + size > self._max_size:
            raise ExceededBatchRecordSizeError(
                f"batch would exceed {self._max_size} bytes"
            )
        self._size += size
        self.follow_ups.append(FollowUpRecord(record, processed))

    def with_response(self, record: Record, request_stream_id: int, request_id: int) -> None:
        self.response = ClientResponse(record, request_stream_id, request_id)

    def add_response(self, record: Record, request_stream_id: int, request_id: int) -> None:
        """An extra response to a *different* parked request (await-result:
        the process-completion step answers the original create request)."""
        self.extra_responses.append(ClientResponse(record, request_stream_id, request_id))

    def append_post_commit_task(self, task: Callable[[], None]) -> None:
        self.post_commit_tasks.append(task)


class ExceededBatchRecordSizeError(Exception):
    pass


class RecordProcessor(abc.ABC):
    """The processing SPI (reference: api/RecordProcessor.java)."""

    @abc.abstractmethod
    def accepts(self, value_type: ValueType) -> bool:
        """Whether this processor handles records of ``value_type``."""

    @abc.abstractmethod
    def process(self, record: LoggedRecord, result: ProcessingResultBuilder) -> None:
        """Process a committed command; events appended to ``result`` must
        already be applied to state (StateWriter contract)."""

    @abc.abstractmethod
    def replay(self, record: LoggedRecord) -> None:
        """Apply an event to state during replay — must produce state identical
        to what ``process`` produced when it originally wrote the event."""

    def on_processing_error(
        self, error: Exception, record: LoggedRecord, result: ProcessingResultBuilder
    ) -> "ProcessingErrorHandling":
        """Called in a fresh transaction after the failed one rolled back."""
        return ProcessingErrorHandling.REJECT


class ProcessingErrorHandling:
    REJECT = "reject"  # write rejection, continue with next command
    SKIP = "skip"  # skip the record entirely


class ScheduledTaskHandle:
    __slots__ = ("cancelled", "due_millis", "task")

    def __init__(self, due_millis: int, task: Callable[[], list[Record]]) -> None:
        self.due_millis = due_millis
        self.task = task
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class ProcessingScheduleService:
    """Deterministic deferred-task scheduler (reference:
    api/scheduling/ProcessingScheduleService.java).

    The engine schedules due-date checks (timers, message TTL, job timeouts)
    that *write commands back to the log* — never mutate state directly. Driven
    by the stream processor's pump with the stream clock, so tests control time.
    """

    def __init__(self, clock_millis: Callable[[], int], write_commands: Callable[[list[Record]], None]) -> None:
        self._clock = clock_millis
        self._write = write_commands
        self._heap: list[tuple[int, int, ScheduledTaskHandle]] = []
        self._seq = 0
        # actor-analogue metrics (reference: scheduler/ ActorMetrics —
        # actor_job_scheduling_latency etc.): the schedule service is the
        # runtime's deferred-task executor, the closest analogue of the
        # reference's actor task queues
        from zeebe_tpu.utils.metrics import REGISTRY

        self._m_sched_latency = REGISTRY.histogram(
            "actor_job_scheduling_latency",
            "ms a due task waited past its due time",
            buckets=(1, 5, 10, 50, 100, 500, 1000, 5000)).labels()
        self._m_exec_count = REGISTRY.counter(
            "actor_task_execution_count", "scheduled tasks executed").labels()
        self._m_exec_latency = REGISTRY.histogram(
            "actor_task_execution_latency",
            "seconds per scheduled task execution").labels()
        self._m_queue_len = REGISTRY.gauge(
            "actor_task_queue_length", "scheduled tasks pending").labels()

    def run_delayed(self, delay_millis: int, task: Callable[[], list[Record]]) -> ScheduledTaskHandle:
        return self.run_at(self._clock() + delay_millis, task)

    def run_at(self, due_millis: int, task: Callable[[], list[Record]]) -> ScheduledTaskHandle:
        handle = ScheduledTaskHandle(due_millis, task)
        self._seq += 1
        heapq.heappush(self._heap, (due_millis, self._seq, handle))
        return handle

    def run_due_tasks(self) -> int:
        """Run tasks whose due time has passed; their returned commands are
        written to the log. Returns number of tasks run."""
        import time as _time

        now = self._clock()
        ran = 0
        while self._heap and self._heap[0][0] <= now:
            due, _, handle = heapq.heappop(self._heap)
            if handle.cancelled:
                continue
            self._m_sched_latency.observe(max(0, now - due))
            start = _time.perf_counter()
            commands = handle.task() or []
            if commands:
                self._write(commands)
            self._m_exec_count.inc()
            self._m_exec_latency.observe(_time.perf_counter() - start)
            ran += 1
        self._m_queue_len.set(len(self._heap))
        return ran

    @property
    def next_due_millis(self) -> int | None:
        while self._heap and self._heap[0][2].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else None
