"""Stream processor: replay → processing state machines over one partition's log.

Reference: stream-platform/src/main/java/io/camunda/zeebe/stream/impl/
StreamProcessor.java:77 (phases), ProcessingStateMachine.java:94 (command loop
documented at :55-93, batchProcessing :328-374), ReplayStateMachine.java:42
(REPLAY_FILTER: events only), StreamProcessorMode.java.

The command loop per step:
  read next unprocessed command → open txn → process (engine applies events to
  state as it appends them) → recursively process follow-up commands in the same
  txn up to ``max_commands_in_batch`` (marking them processed in the log) →
  append all follow-ups as one batch (source = command position) → record last
  processed position → commit → execute side effects (client responses).

Replay applies EVENT records only (processed-marked commands and rejections are
skipped) and tracks the last processed position from event source backlinks, so
a restarted or follower partition reaches state identical to the one that
processed the commands — the determinism contract the whole design rests on
(and what lets the TPU backend batch thousands of steps without changing
observable semantics).

Synchronous and pump-driven: callers (broker partition actor, tests, bench)
call ``run_until_idle``. The reference's actor pipeline exists to decouple
threads; one owner thread per partition gives the same single-writer guarantee.
"""

from __future__ import annotations

import enum
import logging
import os
from typing import Callable

from zeebe_tpu.journal.journal import CorruptedJournalError
from zeebe_tpu.logstreams import LogAppendEntry, LoggedRecord, LogStream
from zeebe_tpu.protocol import Record, RecordType, RejectionType, ValueType, rejection
from zeebe_tpu.state.tiering import ColdCorruptionError

#: typed storage-corruption errors (ISSUE 14) pass THROUGH the processor's
#: blanket failure containment: the partition pump catches them and runs
#: the matching repair (truncate/re-materialize/transition) — converting
#: them into FAILED phases or command rejections would bury a repairable
#: disk fault
_STORAGE_CORRUPTION = (CorruptedJournalError, ColdCorruptionError)
from zeebe_tpu.state import ColumnFamilyCode, ZbDb
from zeebe_tpu.stream.api import (
    ClientResponse,
    ExceededBatchRecordSizeError,
    ProcessingErrorHandling,
    ProcessingResultBuilder,
    ProcessingScheduleService,
    RecordProcessor,
    activatable_job_types,
)

from zeebe_tpu.protocol.intent import ProcessInstanceIntent as _PI

# ELEMENT_* lifecycle intents → metric action label (reference:
# ProcessEngineMetrics.ExecutedInstanceAction)
_ELEMENT_ACTIONS = {
    int(_PI.ELEMENT_ACTIVATED): "activated",
    int(_PI.ELEMENT_COMPLETED): "completed",
    int(_PI.ELEMENT_TERMINATED): "terminated",
}

logger = logging.getLogger("zeebe_tpu.stream")


class Phase(enum.Enum):
    INITIAL = "initial"
    REPLAY = "replay"
    PROCESSING = "processing"
    FAILED = "failed"


class StreamProcessorMode(enum.Enum):
    """PROCESSING: replay then process (leaders). REPLAY: replay continuously
    (followers) — reference: StreamProcessorMode.java:10-22."""

    PROCESSING = "processing"
    REPLAY = "replay"


class StreamProcessor:
    """One partition's processing heart. Owns the db transaction lifecycle."""

    def __init__(
        self,
        log_stream: LogStream,
        db: ZbDb,
        processor: RecordProcessor,
        mode: StreamProcessorMode = StreamProcessorMode.PROCESSING,
        max_commands_in_batch: int = 100,
        response_sink: Callable[[ClientResponse], None] | None = None,
        clock_millis: Callable[[], int] | None = None,
        writer=None,
        kernel_backend=None,
    ) -> None:
        self.log_stream = log_stream
        self.db = db
        self.processor = processor
        self.mode = mode
        # pluggable write path: the broker passes a Raft-appending writer so
        # follow-ups/scheduled commands replicate before becoming readable
        # (reference: Sequencer → LogStorageAppender → AtomixLogStorage → Raft)
        self.writer = writer if writer is not None else log_stream.writer
        self.max_commands_in_batch = max_commands_in_batch
        # optional batched device execution (engine/kernel_backend.py): groups
        # of eligible commands ride the automaton kernel instead of the
        # per-command sequential path; everything else falls through unchanged
        self.kernel_backend = kernel_backend
        if kernel_backend is not None:
            # single source of truth: the backend's host-escape drain must
            # account commands against the SAME budget as _batch_process, or
            # the flattened bursts' processed flags diverge from sequential
            kernel_backend.max_commands_in_batch = max_commands_in_batch
        self.response_sink = response_sink or (lambda response: None)
        # post-commit jobs-available notification (reference: the engine's
        # jobsAvailable callback → gateway long-poll wakeup / job push);
        # receives the set of job types a committed step made activatable
        self.on_jobs_available: Callable[[set], None] | None = None
        self.phase = Phase.INITIAL
        self._positions = db.column_family(ColumnFamilyCode.LAST_PROCESSED_POSITION)
        # replicated request dedupe (ISSUE 9): materialized here on BOTH the
        # processing and replay paths from the same logged evidence, so the
        # family replays to byte-identical state (chaos parity oracle) and a
        # promoted follower / restarted leader inherits every request's fate
        from collections import OrderedDict as _OrderedDict

        from zeebe_tpu.state.request_dedupe import RequestDedupeState

        self._dedupe = RequestDedupeState(db)
        # position → (stream id, request id) of request-carrying commands
        # seen during replay, awaiting their processing evidence (the
        # follow-up batch with that source); bounded — an evicted entry just
        # skips one awaiting note for a request that never got processed
        self._replay_pending: _OrderedDict[int, tuple[int, int]] = _OrderedDict()
        # hot-path metrics, children pre-resolved (reference names:
        # stream-platform impl/metrics/StreamProcessorMetrics —
        # zeebe_stream_processor_records_total, processing latency)
        from zeebe_tpu.utils.metrics import REGISTRY

        partition_label = str(log_stream.partition_id)
        records_total = REGISTRY.counter(
            "stream_processor_records_total",
            "records handled by the stream processor",
            ("partition", "action"))
        self._m_processed = records_total.labels(partition_label, "processed")
        self._m_replayed = records_total.labels(partition_label, "replayed")
        self._m_batched = records_total.labels(partition_label, "kernel_batched")
        self._m_latency = REGISTRY.histogram(
            "stream_processor_latency",
            "seconds spent processing one command (or one kernel group)",
            ("partition",)).labels(partition_label)
        # engine activity counters, observed PROCESSING-side from the step's
        # follow-up events — never during replay, so counts are not inflated
        # by followers or restart recovery (reference: engine/metrics/
        # ProcessEngineMetrics, JobMetrics, IncidentMetrics count in
        # processors, not appliers). Kernel burst hits are counted coarsely
        # via action=kernel_batched instead.
        instances = REGISTRY.counter(
            "executed_instances_total",
            "root process instances by lifecycle action",
            ("partition", "action"))
        jobs = REGISTRY.counter(
            "job_events_total", "job lifecycle events written",
            ("partition", "action"))
        incidents = REGISTRY.counter(
            "incident_events_total", "incident events written",
            ("partition", "action"))
        from zeebe_tpu.protocol.intent import (
            IncidentIntent,
            JobIntent,
            ProcessInstanceIntent,
        )

        self._m_pi_actions = {
            int(ProcessInstanceIntent.ELEMENT_ACTIVATED):
                instances.labels(partition_label, "activated"),
            int(ProcessInstanceIntent.ELEMENT_COMPLETED):
                instances.labels(partition_label, "completed"),
            int(ProcessInstanceIntent.ELEMENT_TERMINATED):
                instances.labels(partition_label, "terminated"),
        }
        self._m_job_actions = {
            int(JobIntent.CREATED): jobs.labels(partition_label, "created"),
            int(JobIntent.COMPLETED): jobs.labels(partition_label, "completed"),
            int(JobIntent.FAILED): jobs.labels(partition_label, "failed"),
            int(JobIntent.TIMED_OUT): jobs.labels(partition_label, "timed_out"),
            int(JobIntent.CANCELED): jobs.labels(partition_label, "canceled"),
            int(JobIntent.ERROR_THROWN): jobs.labels(partition_label, "error_thrown"),
        }
        # element transitions by BPMN element type (reference:
        # ProcessEngineMetrics zeebe_element_instance_events_total)
        self._m_element_events = REGISTRY.counter(
            "element_instance_events_total",
            "element instance lifecycle events by element type",
            ("partition", "action", "type"))
        self._m_element_children: dict = {}
        self._m_incident_actions = {
            int(IncidentIntent.CREATED): incidents.labels(partition_label, "created"),
            int(IncidentIntent.RESOLVED): incidents.labels(partition_label, "resolved"),
        }
        self._m_batch_commands = REGISTRY.histogram(
            "stream_processor_batch_processing_commands",
            "commands processed in one batch/group", ("partition",),
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 512, 2048),
        ).labels(partition_label)
        self._m_batch_duration = REGISTRY.histogram(
            "stream_processor_batch_processing_duration",
            "seconds per processed batch/group", ("partition",)
        ).labels(partition_label)
        self._m_processing_duration = REGISTRY.histogram(
            "stream_processor_processing_duration",
            "seconds per processed command incl. write+commit",
            ("partition",)).labels(partition_label)
        self._m_post_commit = REGISTRY.histogram(
            "stream_processor_batch_processing_post_commit_tasks",
            "post-commit side effects per step", ("partition",),
            buckets=(0, 1, 2, 4, 8, 16, 64),
        ).labels(partition_label)
        self._m_batch_retry = REGISTRY.counter(
            "stream_processor_batch_processing_retry",
            "batches retried after an error rollback", ("partition",)
        ).labels(partition_label)
        # stream_processor_last_processed_position is owned by the broker
        # metrics (node+partition labels); here we only keep a no-label twin
        # out of the registry to avoid a label-shape collision
        self._m_recovery_time = REGISTRY.gauge(
            "stream_processor_startup_recovery_time",
            "seconds spent in startup replay recovery", ("partition",)
        ).labels(partition_label)
        self._m_replay_duration = REGISTRY.histogram(
            "replay_event_batch_replay_duration",
            "seconds per replayed event batch", ("partition",)
        ).labels(partition_label)
        self._m_replay_events = REGISTRY.counter(
            "replay_events_total", "events applied during replay",
            ("partition",)).labels(partition_label)
        self._m_replay_last_source = REGISTRY.gauge(
            "replay_last_source_position",
            "source position of the last replayed batch", ("partition",)
        ).labels(partition_label)
        # pipelined-batch stage histograms: the before/after breakdown of the
        # host-path gap (decode/admission, device run, burst materialization,
        # log append, group-commit flush, deferred side effects) — children
        # pre-resolved, the group loop is hot
        self._m_pipeline = {
            stage: REGISTRY.histogram(
                f"stream_processor_pipeline_{stage}",
                f"seconds per kernel group in the {stage} stage of the "
                "pipelined batch-execution path",
                ("partition",)).labels(partition_label)
            for stage in ("decode", "device", "materialize", "append",
                          "flush", "side_effects")
        }
        # dispatch-overlap receipt (ISSUE 13): fraction of a kernel group's
        # wall time during which the host did useful work (the previous
        # group's deferred side effects) while a dispatched device chunk was
        # in flight — the begin_group/finish_group double-buffer seam's
        # before/after number for the ROADMAP item 2 async work. EMA'd so
        # the gauge reads as a recent-history ratio, not one group's jitter.
        self._m_overlap = REGISTRY.gauge(
            "kernel_dispatch_overlap_ratio",
            "EMA of host-work-overlapping-device-dispatch time / kernel "
            "group wall time (begin_group..finish_group seam)",
            ("partition",)).labels(partition_label)
        self._overlap_ema: float | None = None
        # cross-wave double-buffered dispatch (ISSUE 17): wave k+1 is
        # admitted and its first device chunk dispatched inside wave k's
        # transaction, right after wave k materialized — the chunk computes
        # under wave k's entire host tail (append, dedupe notes, commit,
        # group-commit fsync, deferred effects) instead of starting cold at
        # the next round. The stash is (pending_group, expected_reader_pos,
        # state_epoch, dispatch_stamp); the next round consumes it only if
        # nothing invalidated the admission snapshot in between.
        self._spec_group: tuple | None = None
        # bumped by anything that mutates engine state outside the group
        # pipeline itself (a post-commit task with its own transaction);
        # sequential commands are covered by the reader-position check
        self._state_epoch = 0
        self._speculation_enabled = os.environ.get(
            "ZEEBE_BROKER_PIPELINE_SPECULATION", "1"
        ).lower() not in ("0", "false", "off")
        self._m_spec = {
            outcome: REGISTRY.counter(
                "kernel_speculative_groups",
                "cross-wave speculative dispatches by outcome: consumed = "
                "committed by the next pump round; discarded = invalidated "
                "before consumption (interleaved sequential command, "
                "state-mutating post-commit task, quarantine latched, or "
                "the speculating round rolled back)",
                ("partition", "outcome")).labels(partition_label, outcome)
            for outcome in ("consumed", "discarded")
        }
        # bounded kernel_wave flight events: per-wave stats aggregate here
        # and flush through wave_listener (set by the broker partition →
        # flight recorder) at most once per second — the ring stays
        # reviewable and the hot loop never records per group
        self.wave_listener: Callable[[dict], None] | None = None
        self._wave_agg = {"waves": 0, "commands": 0, "chunks": 0,
                          "maxWave": 0}
        self._wave_marks: tuple[int, int, dict] = (0, 0, {})
        self._wave_last_emit = 0.0
        # tracing: spans are minted ONLY on the PROCESSING-phase paths below —
        # replay_available has no tracing hooks, so crash-restart replay is
        # structurally unable to emit (duplicate) spans. The singleton is
        # mutated in place by configure_tracing; caching it here is safe.
        from zeebe_tpu.observability.tracer import get_tracer

        self._tracer = get_tracer()
        # ack-release hook (ISSUE 19): the broker partition wires this to its
        # LatencyObservatory — called as (trace_id, latency_s) at the moment
        # a command's reply is released, only while tracing is enabled
        self.on_ack: Callable[[str, float], None] | None = None
        clock = clock_millis or log_stream.clock_millis
        self.schedule_service = ProcessingScheduleService(clock, self._write_scheduled_commands)
        self._reader_position = 1
        self._scan_hint = -1  # batch-slot cursor for the sequential scans
        self.last_processed_position = -1
        self.last_written_position = -1
        # plain int lifetime counter (metrics children are shared across
        # partition transitions): the partition's recovery accounting reads
        # it right after start() to learn this recovery's replay length
        self.replayed_records = 0
        # double-buffered pipeline state: each processed group's post-commit
        # side effects (client responses, jobs-available notifications) are
        # deferred and run while the NEXT group's device chunk computes.
        # Entries are (last_written_position, builders, ack_notes); with a
        # journal flush_interval configured they additionally wait for the
        # covering group-commit fsync before acking (no-acked-command-lost
        # invariant). ack_notes (tracing only) are the commands' append→ack
        # stamps, resolved at RELEASE time — so the processor-scope
        # command_ack_latency observation and the ack/fsync-wait spans fire
        # when the reply actually goes out, never for a prefix whose
        # covering fsync failed and was rewound (ISSUE 19 satellite).
        self._deferred_effects: list[tuple[int, list, list | None]] = []
        self._acked_position = -1
        # acks gated on the covering group-commit fsync: only meaningful when
        # this processor appends to the local stream journal AND that journal
        # has a flush cadence configured (broker partitions pass a Raft
        # writer — durability is raft's ack barrier there, never gated here)
        self._ack_gated = (
            self.writer is log_stream.writer
            and getattr(log_stream.journal, "flush_interval", None) is not None
        )
        # async ack path (ISSUE 17): gated replies release from the journal's
        # flush callback — EVERY covering fsync (the pump-tail cadence check,
        # the idle-boundary flush, an external barrier) frees the replies it
        # covers the moment durability is real, instead of the pump polling
        # for it at the next group tail. The reentrancy latch stops the drain
        # from re-entering itself when a post-commit task (or the drain's own
        # forced flush) triggers another fsync mid-drain.
        self._in_flush_ack = False
        if self._ack_gated:
            log_stream.journal.flush_listeners.append(self._on_journal_flush)

    # -- bookkeeping ---------------------------------------------------------

    def _load_last_processed(self) -> int:
        with self.db.transaction():
            pos = self._positions.get(("last",))
        return pos if pos is not None else -1

    def _store_last_processed(self, position: int) -> None:
        # caller must hold the open processing transaction
        self._positions.put(("last",), position)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Recover: replay from the last processed position, then (in
        PROCESSING mode) become ready to process commands."""
        import time as _time

        recovery_start = _time.perf_counter()
        self.phase = Phase.REPLAY
        self.last_processed_position = self._load_last_processed()
        self._reader_position = 1 if self.last_processed_position < 0 else self.last_processed_position + 1
        self.replay_available()
        self._m_recovery_time.set(_time.perf_counter() - recovery_start)
        if self.phase == Phase.FAILED:
            # a poison record during recovery replay FAILED the processor;
            # becoming a leader over half-replayed state would silently
            # reprocess logged commands and duplicate their events
            return
        if self.mode == StreamProcessorMode.PROCESSING:
            self.phase = Phase.PROCESSING
            # processing scans from the start of the unreplayed suffix
            self._reader_position = (
                1 if self.last_processed_position < 0 else self.last_processed_position + 1
            )

    # -- replay --------------------------------------------------------------

    def replay_available(self) -> int:
        """Apply committed events not yet reflected in state. Returns number of
        events applied. In REPLAY mode this is the follower's steady state.

        A throwing applier (poison record, applier bug) FAILS this processor —
        replay stops, the partition reports unhealthy — instead of propagating
        into the broker pump and taking every co-hosted partition down with it
        (reference: StreamProcessor onFailure → Phase.FAILED + health DEAD)."""
        import time as _time

        if self.phase == Phase.FAILED:
            return 0
        applied = 0
        position = self._reader_position
        while True:
            logged = self.log_stream.read_at_or_after(position)
            if logged is None:
                break
            batch = self.log_stream.read_batch_containing(logged.position)
            batch_start = _time.perf_counter()
            try:
                with self.db.transaction():
                    max_source = -1
                    batch_applied = 0
                    for rec in batch:
                        if rec.position < position:
                            continue
                        # Skip events already reflected in state: their
                        # producing command's position (source backlink) is <=
                        # the recovered last-processed position. This is what
                        # makes snapshot + replay idempotent (reference:
                        # ReplayStateMachine skips up to the snapshot's
                        # processed position).
                        if rec.source_position > self.last_processed_position:
                            if rec.record.is_event:
                                self.processor.replay(rec)
                                batch_applied += 1
                                if rec.source_position > max_source:
                                    max_source = rec.source_position
                            elif rec.record.is_rejection:
                                # a rejection-only step still marks its command
                                # processed, else restart reprocesses it and
                                # duplicates the rejection + client response
                                if rec.source_position > max_source:
                                    max_source = rec.source_position
                    self._note_replay_dedupe(batch, position)
                    if max_source > self.last_processed_position:
                        self.last_processed_position = max_source
                        self._store_last_processed(max_source)
                applied += batch_applied
            except _STORAGE_CORRUPTION:
                raise  # repairable disk fault: the pump's repair seam owns it
            except Exception:  # noqa: BLE001 — the transaction rolled back
                # (the failed batch's events count for nothing); retrying the
                # same batch would throw forever
                self.phase = Phase.FAILED
                logger.exception(
                    "replay failed in batch at position %d; partition marked "
                    "unhealthy (restart or failover to recover)", position)
                return applied
            self._m_replay_duration.observe(_time.perf_counter() - batch_start)
            if max_source >= 0:
                self._m_replay_last_source.set(max_source)
            position = batch[-1].position + 1
        self._reader_position = position
        if applied:
            self.replayed_records += applied
            self._m_replayed.inc(applied)
            self._m_replay_events.inc(applied)
        return applied

    # -- replicated request dedupe (ISSUE 9) ---------------------------------
    #
    # One materialization rule, two observation points with identical final
    # state: the live paths note from the step's own builder/burst (whose
    # records become the logged batch verbatim), replay notes from the
    # logged batch. A processed command carrying a request id gets an
    # awaiting entry; every response-stamped EVENT/REJECTION frame
    # overwrites it with the stored reply; entries age out by log position.

    def _note_replay_dedupe(self, batch, resume_position: int) -> None:
        src = batch[0].source_position
        evidence = src >= 0 and src > self.last_processed_position
        noted = False
        reply_keys = None
        for rec in batch:
            if rec.position < resume_position:
                continue
            record = rec.record
            request_id = record.request_id
            if request_id < 0:
                continue
            if record.is_command:
                if not rec.processed:
                    # a client command awaiting its processing evidence (the
                    # later batch whose source backlink names this position)
                    self._replay_pending[rec.position] = (
                        record.request_stream_id, request_id)
                    while len(self._replay_pending) > 65536:
                        self._replay_pending.popitem(last=False)
                continue
            if evidence:
                self._dedupe.note_reply(src, record)
                noted = True
                if reply_keys is None:
                    reply_keys = set()
                reply_keys.add((record.request_stream_id, request_id))
        if not evidence:
            return
        pending = self._replay_pending.pop(src, None)
        if pending is not None and (reply_keys is None
                                    or pending not in reply_keys):
            # processed but not (yet) answered — await-result parks the
            # reply for a later step; live wrote the same awaiting entry at
            # processing time (its own reply, when present in this batch,
            # overwrote it there too)
            self._dedupe.note_awaiting(src, *pending)
            noted = True
        if noted:
            self._dedupe.age_out(src)

    def _note_live_dedupe(self, cmd: LoggedRecord, follow_ups) -> None:
        """Inside the step transaction, after the follow-ups are final."""
        record = cmd.record
        noted = False
        if record.request_id >= 0:
            self._dedupe.note_awaiting(cmd.position, record.request_stream_id,
                                       record.request_id)
            noted = True
        for f in follow_ups:
            fr = f.record
            if fr.request_id >= 0 and not fr.is_command:
                self._dedupe.note_reply(cmd.position, fr)
                noted = True
        if noted:
            self._dedupe.age_out(cmd.position)

    def _note_burst_dedupe(self, cmd: LoggedRecord, burst) -> None:
        """Burst fast path: the template's instantiated responses are the
        request-carrying follow-ups (build_template falls back to the slow
        path otherwise — the parity guard), so noting them here matches
        what replay derives from the patched frames."""
        record = cmd.record
        noted = False
        if record.request_id >= 0:
            self._dedupe.note_awaiting(cmd.position, record.request_stream_id,
                                       record.request_id)
            noted = True
        for _extra, resp, _stream_id, _request_id in burst.responses:
            if resp.request_id >= 0 and not resp.is_command:
                self._dedupe.note_reply(cmd.position, resp)
                noted = True
        if noted:
            self._dedupe.age_out(cmd.position)

    # -- processing ----------------------------------------------------------

    def _next_command(self) -> LoggedRecord | None:
        position = self._reader_position
        while True:
            logged, self._scan_hint, scanned = self.log_stream.next_command_with_hint(
                position, self._scan_hint
            )
            if logged is None:
                # safe to resume after batches the scan proved command-free
                self._reader_position = max(position, scanned)
                return None
            if logged.record.is_command and not logged.processed:
                self._reader_position = logged.position + 1
                return logged
            position = logged.position + 1

    def _iter_candidate_commands(self, start: int | None = None,
                                 note_head: bool = True):
        """Lazily yield pending commands in log order, stopping at the first
        the kernel backend cannot be a candidate for. Does not consume.

        Batched scan: after the hinted lookup finds a record, the rest of
        its decoded sequenced batch is walked inline — a wave-sized ingress
        batch (thousands of commands in one append) costs one slot lookup,
        not one ``next_command_with_hint`` round-trip per record.

        ``start``/``note_head``: the speculative cross-wave scan reads from
        an explicit position (the just-finished wave's end, before
        ``_reader_position`` advances) and must NOT note a sequential head —
        a discarded speculation would otherwise double-count the head when
        the next round's authoritative scan re-encounters it."""
        position = self._reader_position if start is None else start
        first = note_head
        is_candidate = self.kernel_backend.is_candidate
        while True:
            logged, self._scan_hint, _ = self.log_stream.next_command_with_hint(
                position, self._scan_hint
            )
            if logged is None:
                return
            batch = self.log_stream.read_batch_containing(logged.position)
            start = logged.position - batch[0].position if batch else -1
            if not (0 <= start < len(batch)
                    and batch[start].position == logged.position):
                batch, start = (logged,), 0  # defensive: non-contiguous batch
            for i in range(start, len(batch)):
                logged = batch[i]
                position = logged.position + 1
                if not (logged.record.is_command and not logged.processed):
                    continue
                if not is_candidate(logged.record):
                    if first:
                        # precise fallback accounting: a sequential HEAD is
                        # named by kind; an empty scan (end of log) counts
                        # nothing
                        self.kernel_backend.note_sequential_head(logged.record)
                    return
                first = False
                yield logged

    def process_available_batch(self) -> int:
        """Process a group of kernel-eligible commands in one device run and
        one transaction; returns commands consumed (0 → sequential path).

        Pipelined: the group's first device chunk is dispatched
        asynchronously (KernelBackend.begin_group), the PREVIOUS group's
        deferred post-commit side effects run in that window, and only then
        does the host block on the device (finish_group). This group's own
        side effects are deferred in turn, so device and host work run
        concurrently instead of in strict alternation."""
        if self.kernel_backend is None or self.phase != Phase.PROCESSING:
            return 0
        import time as _time

        group_start = _time.perf_counter()
        from zeebe_tpu.engine.burst_templates import PreparedBurst

        pipeline = self._m_pipeline
        cmds: list[LoggedRecord] = []
        builders: list[ProcessingResultBuilder] = []
        pending = None
        write_failed = False
        # cross-wave double buffering: pop any group speculated by the
        # PREVIOUS round — popped unconditionally so a group that fails
        # validation (or a round that fails outright) can never be consumed
        # against state its admission snapshot no longer matches
        spec, self._spec_group = self._spec_group, None
        spec_next = None
        spec_dispatched_at = 0.0
        # out-of-transaction drain point: deferred groups carrying post-commit
        # tasks (skipped by the in-transaction overlap drain below) go out here
        self._run_deferred_effects()
        overlap = 0.0
        try:
            with self.db.transaction():
                if spec is not None:
                    pg, expected_pos, epoch, t_disp = spec
                    if (expected_pos == self._reader_position
                            and epoch == self._state_epoch
                            and not self.kernel_backend.health.is_quarantined()):
                        # the admission snapshot still holds: the speculating
                        # round committed the exact state this transaction
                        # opened over, nothing processed or mutated since
                        pending = pg
                        spec_dispatched_at = t_disp
                        self._m_spec["consumed"].inc()
                    else:
                        self._m_spec["discarded"].inc()
                        # exactly-once span contract (ISSUE 19 satellite):
                        # the ONLY span a discarded speculation ever emits is
                        # this off-path marker — outcome="discarded" keeps it
                        # out of critical-path attribution, and the next
                        # round's authoritative re-scan of the same wave owns
                        # every kernel_group/kernel_command emission
                        if self._tracer.enabled:
                            self._trace_speculative(expected_pos, t_disp,
                                                    "discarded")
                if pending is None:
                    pending = self.kernel_backend.begin_group(
                        self._iter_candidate_commands())
                # the device is computing the first chunk: run the previous
                # group's deferred host work in the gap — the overlap window
                # the dispatch-overlap gauge measures
                t_overlap = _time.perf_counter()
                self._run_deferred_effects()
                overlap = _time.perf_counter() - t_overlap
                cmds, builders = self.kernel_backend.finish_group(
                    pending, ProcessingResultBuilder)
                if not cmds:
                    return 0
                # speculate wave k+1 BEFORE this wave's host tail: state is
                # materialized (the overlay this transaction will commit), so
                # admission is exact, and the dispatched chunk computes under
                # the append/commit/fsync work below. Stays local until the
                # commit succeeds — a rollback discards it with the overlay.
                if self._speculation_enabled:
                    spec_next = self._maybe_speculate(cmds[-1].position + 1)
                t_append = _time.perf_counter()
                try:
                    for cmd, result in zip(cmds, builders):
                        if isinstance(result, PreparedBurst):
                            if result.count:
                                self.last_written_position = self.writer.append_prepatched(
                                    result.buf, result.pos_offsets,
                                    result.ts_offsets, result.count,
                                    has_pending_commands=result.has_pending_commands,
                                )
                            continue
                        entries = [
                            LogAppendEntry(f.record, f.processed) for f in result.follow_ups
                        ]
                        if entries:
                            self.last_written_position = self.writer.try_write(
                                entries, source_position=cmd.position
                            )
                except Exception:
                    write_failed = True
                    raise
                self.last_processed_position = cmds[-1].position
                self._store_last_processed(self.last_processed_position)
                for cmd, result in zip(cmds, builders):
                    if isinstance(result, PreparedBurst):
                        if result.count:
                            self._note_burst_dedupe(cmd, result)
                    else:
                        self._note_live_dedupe(cmd, result.follow_ups)
                append_dur = _time.perf_counter() - t_append
                pipeline["append"].observe(append_dur)
        except _STORAGE_CORRUPTION:
            raise  # repairable disk fault: the pump's repair seam owns it
        except Exception:  # noqa: BLE001 — the fallback/rollback seam
            if write_failed:
                # a partial group append is already in the log; reprocessing
                # in-process would duplicate those records. Fail the partition
                # — restart replays the log, re-derives last-processed from
                # event source backlinks, and resumes exactly after the
                # partially-written commands (the reference treats appender
                # failures as partition-fatal the same way).
                self.phase = Phase.FAILED
                raise
            logger.exception("kernel group processing failed; falling back to sequential")
            # consolidated path accounting: the head retries sequentially,
            # so this IS one host-routed record with a runtime-only reason
            self.kernel_backend.fallbacks += 1
            self.kernel_backend.accounting.note_host("group-error")
            return 0
        self._reader_position = cmds[-1].position + 1
        # the commit succeeded: the speculative admission's state snapshot is
        # now THE committed state — promote the stash for the next round
        self._spec_group = spec_next
        # kernel-path accounting AFTER the commit: a rolled-back group that
        # re-admits next pump must not count twice (coverage/parity ruler)
        self.kernel_backend.note_group_success(pending)
        # defer this group's post-commit side effects: they run while the
        # NEXT group's device chunk computes (or at the next sequential
        # command / idle boundary, whichever comes first). Ack notes are
        # taken HERE (commit time) because the flush below may drain the
        # entry synchronously — gated notes must already ride it.
        traced = self._tracer.enabled
        notes = self._take_ack_notes(cmds) if traced else None
        self._deferred_effects.append(
            (self.last_written_position, builders,
             notes if self._ack_gated else None))
        t_flush = _time.perf_counter()
        self._group_commit_point()
        flush_dur = _time.perf_counter() - t_flush
        pipeline["flush"].observe(flush_dur)
        pipeline["decode"].observe(pending.t_admit)
        pipeline["device"].observe(pending.device_elapsed)
        pipeline["materialize"].observe(pending.t_materialize)
        self._m_batched.inc(len(cmds))
        elapsed = _time.perf_counter() - group_start
        self._m_latency.observe(elapsed)
        self._m_batch_commands.observe(len(cmds))
        self._m_batch_duration.observe(elapsed)
        # overlap receipt: for a consumed speculation, the group's device
        # work really started at the PREVIOUS round's dispatch stamp, and the
        # window from there to this round's start was all host work (the
        # speculating wave's append, dedupe notes, commit, fsync, deferred
        # effects) done while the chunk was in flight — count it as overlap
        # and widen the denominator by the same amount so the ratio stays an
        # honest fraction of this group's true wall span
        if spec_dispatched_at:
            pre = max(0.0, group_start - spec_dispatched_at)
            overlap += pre
            elapsed += pre
        self._observe_wave(pending, len(cmds), overlap, elapsed)
        if traced:
            if spec_dispatched_at:
                self._trace_speculative(cmds[0].position, spec_dispatched_at,
                                        "consumed")
            self._trace_group(cmds, elapsed, {
                "decode": pending.t_admit, "device": pending.device_elapsed,
                "materialize": pending.t_materialize, "append": append_dur,
                "flush": flush_dur, "overlap": overlap,
            }, notes)
        return len(cmds)

    def _trace_speculative(self, first_pos: int, t_disp: float,
                           outcome: str) -> None:
        """One span per speculative dispatch, emitted exactly once at
        outcome resolution on the wave's group trace. ``outcome="discarded"``
        marks it off the critical path (the extractor skips it);
        ``"consumed"`` measures how early the next wave's chunk launched."""
        import time as _time

        tracer = self._tracer
        pid = self.log_stream.partition_id
        group_trace = f"{pid}:g{first_pos}"
        # Group spans bypass head sampling: one per wave, and they are the
        # substitution substrate for EVERY sampled command's attribution —
        # a sampled command whose wave wasn't sampled would be unattributable.
        if tracer.enabled:
            tracer.emit(group_trace, "processor.speculative",
                        _time.perf_counter() - t_disp, pid,
                        attrs={"speculative": True, "outcome": outcome})

    def _maybe_speculate(self, start_pos: int) -> tuple | None:
        """Admit wave k+1 and dispatch its first device chunk while still
        inside wave k's transaction (cross-wave double buffering, ISSUE 17).

        Runs strictly after wave k materialized, so the overlay this
        admission reads is exactly the state wave k is about to commit; the
        scan starts at wave k's end position and cannot see wave k's
        follow-up appends (not yet written — they land at higher positions
        and are picked up by later scans in order). Declines silently
        (``speculative=True``) and never notes a sequential head: if the
        stash is discarded, the next round's authoritative scan owns all
        accounting. Returns (group, expected_reader_pos, state_epoch,
        dispatch_stamp) or None."""
        import time as _time

        pg = self.kernel_backend.begin_group(
            self._iter_candidate_commands(start=start_pos, note_head=False),
            speculative=True,
        )
        if pg is None:
            return None
        return (pg, start_pos, self._state_epoch, _time.perf_counter())

    def _observe_wave(self, pending, commands: int, overlap: float,
                      elapsed: float) -> None:
        """Per-wave path accounting (ISSUE 13): the dispatch-overlap gauge
        and the bounded ``kernel_wave`` flight events (wave size, chunk
        count, kernel/host path split since the last event, dominant
        fallback reason), flushed through ``wave_listener`` at most once
        per second."""
        import time as _time

        if elapsed > 0:
            ratio = min(1.0, overlap / elapsed)
            ema = self._overlap_ema
            self._overlap_ema = ratio if ema is None else ema + 0.2 * (ratio - ema)
            self._m_overlap.set(round(self._overlap_ema, 4))
        agg = self._wave_agg
        agg["waves"] += 1
        agg["commands"] += commands
        agg["chunks"] += pending.chunks_run
        if commands > agg["maxWave"]:
            agg["maxWave"] = commands
        if self.wave_listener is None:
            return
        now = _time.perf_counter()
        if now - self._wave_last_emit < 1.0 and self._wave_last_emit:
            return
        self._wave_last_emit = now
        acct = self.kernel_backend.accounting
        k_mark, h_mark, reasons_mark = self._wave_marks
        delta_reasons = {
            r: c - reasons_mark.get(r, 0)
            for r, c in acct.reasons.items() if c > reasons_mark.get(r, 0)
        }
        dominant = max(delta_reasons, key=delta_reasons.get, default=None)
        d_kernel = acct.kernel_records - k_mark
        d_host = acct.host_records - h_mark
        health = self.kernel_backend.health
        event = {
            "waves": agg["waves"],
            "commands": agg["commands"],
            "avgWave": round(agg["commands"] / max(1, agg["waves"]), 1),
            "maxWave": agg["maxWave"],
            "chunks": agg["chunks"],
            "kernelRecords": d_kernel,
            "hostRecords": d_host,
            # the EVENT's window, consistent with its own delta counters
            # (the cumulative ratio lives on /health and the gauge)
            "coverageRatio": round(d_kernel / max(1, d_kernel + d_host), 4),
            "overlapRatio": round(self._overlap_ema or 0.0, 4),
            **({"dominantFallback": dominant} if dominant else {}),
            # device-fault defense (ISSUE 15): the wave event carries the
            # ladder state + shadow counters, so a quarantine explains its
            # own coverage drop right in the flight ring
            "deviceHealth": health.state,
            "shadowChecks": health.shadow_checks,
            "shadowMismatches": health.shadow_mismatches,
        }
        self._wave_marks = (acct.kernel_records, acct.host_records,
                            dict(acct.reasons))
        self._wave_agg = {"waves": 0, "commands": 0, "chunks": 0,
                          "maxWave": 0}
        try:
            self.wave_listener(event)
        except Exception:  # noqa: BLE001 — telemetry must not wedge the pump
            logger.exception("kernel_wave listener failed")

    def _take_ack_notes(self, cmds) -> list[tuple]:
        """Consume the commands' append stamps at COMMIT time into ack
        notes ``(trace_id, position, t_append, t_commit)``. Notes are
        resolved by :meth:`_release_acks` when the reply actually releases
        — immediately when ungated, at the covering-fsync drain when gated
        — so a failed flush (rewound prefix) can never feed the ack
        histogram or emit an ack span for a reply that never went out."""
        import time as _time

        tracer = self._tracer
        pid = self.log_stream.partition_id
        t_commit = _time.perf_counter()
        notes = []
        for cmd in cmds:
            t_append = tracer.take_append(pid, cmd.position)
            fallback = (cmd.source_position if cmd.source_position >= 0
                        else cmd.position)
            root = tracer.resolve_root(pid, cmd.position, fallback)
            notes.append((f"{pid}:{root}", cmd.position, t_append, t_commit))
        return notes

    def _release_acks(self, notes: list[tuple]) -> None:
        """The ack-release seam: observe append→ack latency, emit the
        ``processor.ack`` envelope (the attribution root on gateway-less
        harnesses) and the ``processor.fsync_wait`` cover span, and feed
        the slow-exemplar observatory."""
        import time as _time

        tracer = self._tracer
        pid = self.log_stream.partition_id
        now = _time.perf_counter()
        enabled = tracer.enabled
        on_ack = self.on_ack
        for trace_id, position, t_append, t_commit in notes:
            if t_append is None:
                continue  # stamp evicted, or a burst append without one
            latency = now - t_append
            tracer.observe_ack("processor", latency)
            if enabled and tracer.sampled(trace_id):
                tracer.emit(trace_id, "processor.ack", latency, pid,
                            attrs={"position": position})
                wait = now - t_commit
                if self._ack_gated and wait > 0:
                    tracer.emit(trace_id, "processor.fsync_wait", wait, pid,
                                parent="processor.ack",
                                attrs={"position": position})
            if enabled and on_ack is not None:
                on_ack(trace_id, latency)

    def _trace_group(self, cmds: list[LoggedRecord], elapsed: float,
                     stages: dict[str, float],
                     notes: list[tuple] | None) -> None:
        """Spans for one kernel group: a group span with one child per
        pipeline stage (the per-trace view of the stream_processor_pipeline_*
        histograms), a backlog-wait span per sampled command (append → wave
        start, positioned at its REAL interval so the critical-path sweep
        charges it as queue time), plus a latency-attributed span per
        sampled command — Canopy-style: the group's wall time split evenly
        across its commands. Ungated acks release here; gated acks release
        from the covering-fsync drain. Only called from the live
        PROCESSING path."""
        import time as _time

        from zeebe_tpu.observability.span import now_us as _now_us

        tracer = self._tracer
        pid = self.log_stream.partition_id
        now = _time.perf_counter()
        anchor_us = _now_us()
        group_trace = f"{pid}:g{cmds[0].position}"
        # Group spans bypass head sampling (see _trace_speculative): ~one
        # span bundle per wave, required by every sampled command's
        # interval substitution.
        if tracer.enabled:
            tracer.emit(group_trace, "processor.kernel_group", elapsed, pid,
                        attrs={"commands": len(cmds),
                               "firstPosition": cmds[0].position,
                               "lastPosition": cmds[-1].position})
            for stage, dur in stages.items():
                tracer.emit(group_trace, f"processor.stage.{stage}", dur, pid,
                            parent="processor.kernel_group")
        share = elapsed / len(cmds)
        by_position = ({note[1]: note for note in notes} if notes else {})
        for cmd in cmds:
            note = by_position.get(cmd.position)
            trace_id = (note[0] if note is not None
                        else f"{pid}:{tracer.resolve_root(pid, cmd.position, cmd.position)}")
            if not tracer.sampled(trace_id):
                continue
            rec = cmd.record
            t_append = note[2] if note is not None else None
            if t_append is not None:
                backlog = (now - elapsed) - t_append
                if backlog > 0:
                    tracer.emit(
                        trace_id, "processor.backlog_wait", backlog, pid,
                        parent="processor.ack",
                        attrs={"position": cmd.position},
                        start_us=anchor_us - int((now - t_append) * 1e6))
            tracer.emit(trace_id, "processor.kernel_command", share, pid,
                        attrs={"position": cmd.position,
                               "valueType": rec.value_type.name,
                               "intent": rec.intent.name,
                               "group": group_trace,
                               "attributed": True})
        if notes and not self._ack_gated:
            self._release_acks(notes)

    def _emit_group_effects(self, builders: list) -> None:
        from zeebe_tpu.engine.burst_templates import PreparedBurst

        job_types: set = set()
        for result in builders:
            if isinstance(result, PreparedBurst):
                for _extra, record, stream_id, request_id in result.responses:
                    self.response_sink(ClientResponse(record, stream_id, request_id))
                job_types |= result.job_types
            else:
                self._execute_side_effects(result)
                job_types |= activatable_job_types(result.follow_ups)
        self._notify_jobs_available(job_types)

    def _group_commit_point(self) -> None:
        """Per-step flush point: advance the acked position — immediately
        when acks are not flush-gated (append = visible, the pre-pipeline
        semantics). Gated acks are fully async: ``maybe_flush`` only decides
        WHETHER the cadence fsyncs here; the ack advance and the reply drain
        happen in ``_on_journal_flush``, fired by the journal after any
        successful covering fsync — this one or anyone else's."""
        if not self._ack_gated:
            self._acked_position = self.last_written_position
        else:
            self.log_stream.journal.maybe_flush()

    def _on_journal_flush(self, covered_index: int) -> None:
        """Journal flush callback — the async ack path. Runs strictly after
        a successful fsync, so everything appended before the flush call is
        durable: advance the acked position to the last appended record and
        emit the deferred replies it releases. A FAILED fsync never reaches
        this callback (FlushFailedError propagates from flush() first), so
        no reply can ever cover an unfsynced prefix. Single-threaded with
        the pump (every flush origin runs on the processor thread), so
        ``last_written_position`` is exactly the covered prefix."""
        self._acked_position = self.last_written_position
        if self._in_flush_ack:
            return  # re-entered from a drain-triggered fsync: outer drain owns it
        self._in_flush_ack = True
        try:
            self._run_deferred_effects()
        finally:
            self._in_flush_ack = False

    def _run_deferred_effects(self) -> None:
        """Emit deferred group side effects whose appends are acked (always
        the whole queue unless a journal flush_interval gates acks on the
        covering group-commit fsync)."""
        dq = self._deferred_effects
        if not dq:
            return
        import time as _time

        from zeebe_tpu.engine.burst_templates import PreparedBurst

        t0 = _time.perf_counter()
        acked = self._acked_position
        in_txn = self.db.in_transaction
        emitted = 0
        while dq and dq[0][0] <= acked:
            if in_txn and any(
                not isinstance(b, PreparedBurst) and b.post_commit_tasks
                for b in dq[0][1]
            ):
                # post-commit tasks are an API allowed to open their own db
                # transaction — they only run at out-of-transaction drain
                # points (FIFO preserved: the queue stops at the first
                # task-bearing group; responses never overtake it)
                break
            _position, builders, notes = dq.pop(0)
            self._emit_group_effects(builders)
            if notes:
                # gated ack release: the covering fsync succeeded (this drain
                # only runs past an advanced acked position), so the
                # append→ack observation and ack/fsync-wait spans are real
                self._release_acks(notes)
            emitted += 1
        if emitted:
            # observed only when work happened: the stage breakdown stays a
            # per-group view, not inflated by empty drain attempts
            self._m_pipeline["side_effects"].observe(_time.perf_counter() - t0)

    def _flush_deferred_effects(self) -> None:
        """Pipeline boundary (idle, or a sequential command interleaving):
        everything still deferred must go out, forcing the covering
        group-commit fsync first when acks are gated on one."""
        dq = self._deferred_effects
        if not dq:
            return
        if dq[-1][0] > self._acked_position:
            # acks gated on durability: force the covering fsync. The flush
            # callback (_on_journal_flush) advances the acked position and
            # drains; the explicit advance below is the no-listener fallback
            # (a gated processor is always subscribed, but keep the boundary
            # correct even if the journal lacks the callback seam).
            self.log_stream.journal.flush()
            self._acked_position = max(self._acked_position,
                                       self.last_written_position)
        self._run_deferred_effects()

    def process_next(self) -> bool:
        """Process one command; returns False when no command is pending."""
        if self.phase != Phase.PROCESSING:
            raise RuntimeError(f"cannot process in phase {self.phase}")
        cmd = self._next_command()
        if cmd is None:
            return False
        self._process_command(cmd)
        return True

    def _process_command(self, cmd: LoggedRecord) -> None:
        import time as _time

        # sequential interleaving: responses stay in log order across the
        # batched and sequential paths. Flush-gated mode keeps the sequential
        # command's OWN effects in the deferred queue too (its ack must also
        # wait for the covering fsync), so order holds without forcing an
        # fsync per command; ungated mode drains everything immediately.
        if self._ack_gated:
            self._run_deferred_effects()
        else:
            self._flush_deferred_effects()
        start = _time.perf_counter()
        builder = ProcessingResultBuilder()
        try:
            with self.db.transaction():
                self._batch_process(cmd, builder)
                self._write_and_mark(cmd, builder)
        except _STORAGE_CORRUPTION:
            raise  # repairable disk fault: the pump's repair seam owns it
        except Exception as error:  # noqa: BLE001 — the rollback/onError seam
            logger.debug("processing error at position %s: %s", cmd.position, error, exc_info=True)
            self._m_batch_retry.inc()
            self._on_processing_error(cmd, error)
            return
        traced = self._tracer.enabled
        notes = self._take_ack_notes((cmd,)) if traced else None
        if self._ack_gated:
            # acked ⇒ durable: the response waits for the covering fsync
            # (maybe_flush cadence, or the idle-boundary flush); its ack
            # notes wait with it — a failed flush releases neither
            self._deferred_effects.append(
                (self.last_written_position, [builder], notes))
            self._group_commit_point()
            self._run_deferred_effects()
        else:
            self._execute_side_effects(builder)
            self._notify_jobs_available(activatable_job_types(builder.follow_ups))
        self._observe_follow_ups(builder.follow_ups)
        self._m_processed.inc()
        elapsed = _time.perf_counter() - start
        if traced:
            self._trace_command(cmd, builder, elapsed, notes)
        self._m_latency.observe(elapsed)
        self._m_processing_duration.observe(elapsed)
        self._m_batch_commands.observe(
            1 + sum(1 for f in builder.follow_ups
                    if f.record.is_command and f.processed))
        self._m_batch_duration.observe(elapsed)
        self._m_post_commit.observe(len(builder.post_commit_tasks))

    def _trace_command(self, cmd: LoggedRecord,
                       builder: ProcessingResultBuilder, elapsed: float,
                       notes: list[tuple] | None) -> None:
        """Spans for one sequentially processed command: the processing span,
        a backlog-wait span (append → processing start, at its real
        interval), and — when acks are ungated — the immediate ack release.
        Gated notes release from the covering-fsync drain instead. The trace
        id is the root command's position (follow-up commands inherit their
        producer's root via the batch source backlink), so the span stream
        joins to the lineage walker's trees."""
        import time as _time

        from zeebe_tpu.observability.span import now_us as _now_us

        tracer = self._tracer
        pid = self.log_stream.partition_id
        note = notes[0] if notes else None
        trace_id = (note[0] if note is not None
                    else f"{pid}:{tracer.resolve_root(pid, cmd.position, cmd.position)}")
        if tracer.sampled(trace_id):
            rec = cmd.record
            t_append = note[2] if note is not None else None
            if t_append is not None:
                now = _time.perf_counter()
                backlog = (now - elapsed) - t_append
                if backlog > 0:
                    tracer.emit(
                        trace_id, "processor.backlog_wait", backlog, pid,
                        parent="processor.ack",
                        attrs={"position": cmd.position},
                        start_us=_now_us() - int((now - t_append) * 1e6))
            tracer.emit(trace_id, "processor.command", elapsed, pid,
                        attrs={"position": cmd.position,
                               "valueType": rec.value_type.name,
                               "intent": rec.intent.name,
                               "followUps": len(builder.follow_ups)})
        if notes and not self._ack_gated:
            self._release_acks(notes)

    def _batch_process(self, cmd: LoggedRecord, builder: ProcessingResultBuilder) -> None:
        """The batchProcessing loop: the input command plus follow-up commands
        produced during the step, processed in one transaction."""
        self.processor.process(cmd, builder)
        budget = self.max_commands_in_batch - 1
        scan = 0
        while budget > 0:
            follow_up = None
            while scan < len(builder.follow_ups):
                entry = builder.follow_ups[scan]
                if entry.record.is_command and not entry.processed:
                    follow_up = entry
                    break
                scan += 1
            if follow_up is None:
                break
            follow_up.processed = True
            budget -= 1
            logged = LoggedRecord(
                record=follow_up.record,
                position=-1,  # in-batch: position assigned at write time
                source_position=cmd.position,
                processed=True,
            )
            self.processor.process(logged, builder)
            scan += 1

    def _write_and_mark(self, cmd: LoggedRecord, builder: ProcessingResultBuilder) -> None:
        entries = [LogAppendEntry(f.record, f.processed) for f in builder.follow_ups]
        if entries:
            self.last_written_position = self.writer.try_write(
                entries, source_position=cmd.position
            )
        self.last_processed_position = cmd.position
        self._store_last_processed(cmd.position)
        self._note_live_dedupe(cmd, builder.follow_ups)

    def _on_processing_error(self, cmd: LoggedRecord, error: Exception) -> None:
        builder = ProcessingResultBuilder()
        with self.db.transaction():
            handling = self.processor.on_processing_error(error, cmd, builder)
            if handling == ProcessingErrorHandling.REJECT and builder.response is None:
                rej = rejection(cmd.record.replace(position=cmd.position),
                                RejectionType.PROCESSING_ERROR, str(error)[:8192])
                builder.append_record(rej)
                if cmd.record.request_id >= 0:
                    builder.with_response(rej, cmd.record.request_stream_id, cmd.record.request_id)
            self._write_and_mark(cmd, builder)
        if self._ack_gated:
            # rejections ack like any response: after the covering fsync
            # (no ack notes — rejections never fed the ack histogram)
            self._deferred_effects.append(
                (self.last_written_position, [builder], None))
            self._group_commit_point()
            self._run_deferred_effects()
            return
        self._execute_side_effects(builder)

    def _observe_follow_ups(self, follow_ups) -> None:
        for f in follow_ups:
            rec = f.record
            if not rec.is_event:
                continue
            vt = rec.value_type
            if vt == ValueType.JOB:
                child = self._m_job_actions.get(int(rec.intent))
                if child is not None:
                    child.inc()
            elif vt == ValueType.PROCESS_INSTANCE:
                intent = int(rec.intent)
                element_type = rec.value.get("bpmnElementType")
                if element_type == "PROCESS":
                    child = self._m_pi_actions.get(intent)
                    if child is not None:
                        child.inc()
                action = _ELEMENT_ACTIONS.get(intent)
                if action is not None and element_type:
                    key = (action, element_type)
                    child = self._m_element_children.get(key)
                    if child is None:
                        child = self._m_element_events.labels(
                            str(self.log_stream.partition_id), action,
                            element_type)
                        self._m_element_children[key] = child
                    child.inc()
            elif vt == ValueType.INCIDENT:
                child = self._m_incident_actions.get(int(rec.intent))
                if child is not None:
                    child.inc()

    def _notify_jobs_available(self, job_types: set) -> None:
        if job_types and self.on_jobs_available is not None:
            try:
                self.on_jobs_available(job_types)
            except Exception:  # noqa: BLE001 — notification must not wedge processing
                logger.exception("jobs-available notification failed")

    def _execute_side_effects(self, builder: ProcessingResultBuilder) -> None:
        if builder.response is not None:
            self.response_sink(builder.response)
        for extra in builder.extra_responses:
            self.response_sink(extra)
        if builder.post_commit_tasks:
            # post-commit tasks may open their own transaction and mutate
            # state a speculative admission already read: invalidate any
            # outstanding cross-wave stash (reader-position checks cannot
            # see this — tasks move no positions)
            self._state_epoch += 1
        for task in builder.post_commit_tasks:
            try:
                task()
            except Exception:  # noqa: BLE001 — side effects must not wedge the loop
                logger.exception("post-commit task failed")

    # -- pump ----------------------------------------------------------------

    def _write_scheduled_commands(self, commands: list[Record]) -> None:
        self.writer.try_write([LogAppendEntry(c) for c in commands])

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        """Drive scheduled tasks + processing until no work remains (or, in
        REPLAY mode, replay everything available). Returns steps executed."""
        steps = 0
        if self.phase == Phase.REPLAY:
            return self.replay_available()
        while steps < max_steps:
            self.schedule_service.run_due_tasks()
            if self.kernel_backend is not None:
                consumed = self.process_available_batch()
                if consumed:
                    steps += consumed
                    continue
            if not self.process_next():
                if self.schedule_service.run_due_tasks() == 0:
                    break
            steps += 1
        # idle boundary: the last group's deferred side effects (and, when
        # acks are flush-gated, the covering group-commit fsync) go out now
        self._flush_deferred_effects()
        return steps
