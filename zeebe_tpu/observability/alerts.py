"""Alert-rule evaluation over the in-memory time-series store.

Prometheus-style ``expr for duration`` rules without Prometheus: a rule names
a stored series (exact name, or a histogram child like
``zeebe_journal_flush_duration_seconds:p99``), a threshold condition, and a
**for-duration** the condition must hold before the alert fires — the
for-duration is what separates "one slow flush" from "flushes have been slow
for five seconds". A second rule kind, ``changes``, counts value changes
inside a trailing window (raft-role flapping: the 0↔1 ``raft_role`` gauge
flipping four times in ten seconds is an election storm no threshold can
express).

State machine per (rule, series child): ``inactive → pending → firing →
inactive``. Transitions are reported to an optional listener (the broker
feeds them into the flight recorder) and mirrored into the
``zeebe_alerts_firing`` gauge (labels ``node``/``rule``, value = number of
firing children), whose total also rides ``/health`` details.

Evaluation is driven off the sampler tick (same cadence, same clock), so a
controlled-clock test advancing 6 virtual seconds fires a 5-second rule
deterministically.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

from zeebe_tpu.utils.metrics import REGISTRY as _REG

_M_FIRING = _REG.gauge(
    "alerts_firing",
    "alert rules currently firing (value = firing series per rule)",
    ("node", "rule"))

INACTIVE = "inactive"
PENDING = "pending"
FIRING = "firing"

# a threshold rule ignores (and clears on) series whose latest sample is
# older than this: an idle broker stops appending :p99 quantile points, and
# without a staleness cutoff the last high value would keep a flush-latency
# alert firing forever on a completely quiet node
STALE_AFTER_MS = 30_000


@dataclasses.dataclass(frozen=True)
class AlertRule:
    name: str
    series: str                 # store series name (exact / histogram child)
    threshold: float = 0.0
    op: str = ">"               # ">" | "<"
    for_ms: int = 5_000
    kind: str = "threshold"     # "threshold" | "changes"
    window_ms: int = 10_000     # trailing window for "changes"
    labels_contains: str = ""   # child filter, substring of the label string
    severity: str = "warning"

    def describe(self) -> str:
        if self.kind == "changes":
            return (f"{self.series} changes >= {int(self.threshold)} "
                    f"within {self.window_ms}ms")
        return f"{self.series} {self.op} {self.threshold} for {self.for_ms}ms"


def default_rules() -> list[AlertRule]:
    """The out-of-the-box rule set (ISSUE 4 + 5): exporter lag, backpressure
    drops, flush latency, raft role flapping, XLA recompile storms.
    Thresholds are deliberately conservative — a firing default alert should
    always be worth a look."""
    return [
        AlertRule(
            name="exporter_lag",
            series="zeebe_exporter_container_lag_records",
            threshold=1000.0, for_ms=5_000, severity="warning"),
        AlertRule(
            name="backpressure_drops",
            series="zeebe_dropped_request_count_total",  # stored as a rate
            threshold=1.0, for_ms=5_000, severity="warning"),
        AlertRule(
            name="journal_flush_slow",
            series="zeebe_journal_flush_duration_seconds:p99",
            threshold=0.5, for_ms=5_000, severity="critical"),
        AlertRule(
            name="raft_role_flapping",
            series="zeebe_raft_role",
            kind="changes", threshold=4.0, window_ms=10_000,
            severity="critical"),
        AlertRule(
            # the compile seam stores xla_compiles_total{cache="miss"} as a
            # rate: each cold compile is a 0→spike→0 episode (≤2 value
            # changes). Threshold 6 = ≥3 cold compiles inside a minute — a
            # recompile storm (geometry churn / redeploy loop), while the
            # expected process warmup (the two shape buckets compiling once)
            # contributes at most 4 changes and stays below it. The series
            # is process-scoped (no node label — the seam sits below the
            # broker), so like exporter lag it passes every evaluator's
            # _mine(); in an in-process multi-broker test cluster each
            # broker reports the shared storm.
            name="xla_recompile_storm",
            series="zeebe_xla_compiles_total",
            labels_contains='cache="miss"',
            kind="changes", threshold=6.0, window_ms=60_000,
            severity="warning"),
        AlertRule(
            # RSS watermark (ISSUE 8): the process self-metrics gauge (raw
            # name, un-namespaced — install_process_metrics follows the
            # prometheus_client convention) held above the watermark for
            # 10s. The default watermark is deliberately high (4 GiB);
            # deployments bound it tighter via
            # ZEEBE_ALERT_RSSWATERMARKBYTES — the scale soak wires this in
            # as an invariant monitor over the million-instance park.
            name="rss_watermark",
            series="process_resident_memory_bytes",
            threshold=float(os.environ.get(
                "ZEEBE_ALERT_RSSWATERMARKBYTES", 4 << 30)),
            for_ms=10_000, severity="critical"),
        AlertRule(
            # recovery_budget_exceeded_total is stored as a rate: a blown
            # recovery is a 0→spike→0 episode, so ANY value change inside
            # the trailing minute means a partition rebuild just ran past
            # its recovery_budget_ms (ISSUE 6). Partition-labeled only (no
            # node label — recoveries are partition-scoped), so like
            # exporter lag it passes every evaluator's _mine().
            name="recovery_budget_exceeded",
            series="zeebe_recovery_budget_exceeded_total",
            kind="changes", threshold=1.0, window_ms=60_000,
            severity="critical"),
    ]


class _SeriesState:
    __slots__ = ("state", "since_ms", "value")

    def __init__(self) -> None:
        self.state = INACTIVE
        self.since_ms = 0
        self.value = 0.0


class AlertEvaluator:
    def __init__(self, store, rules: list[AlertRule] | None = None,
                 node_id: str = "",
                 on_transition: Callable[[AlertRule, str, str, str], None] | None = None) -> None:
        self.store = store
        self.rules = rules if rules is not None else default_rules()
        self.node_id = node_id
        # (rule name, series labels) → state machine
        self._states: dict[tuple[str, str], _SeriesState] = {}
        self.on_transition = on_transition
        self._gauges = {
            r.name: _M_FIRING.labels(node_id, r.name) for r in self.rules
        }

    def add_rules(self, rules: list[AlertRule]) -> None:
        """Layer extra rules onto a live evaluator (the fleet auditor's
        burn-rate pair) — the per-rule firing gauge must exist before the
        next ``evaluate`` sweep, so appending to ``rules`` directly is not
        enough."""
        for rule in rules:
            self.rules.append(rule)
            self._gauges.setdefault(
                rule.name, _M_FIRING.labels(self.node_id, rule.name))

    # -- evaluation ------------------------------------------------------------

    def _breaches(self, rule: AlertRule, value: float) -> bool:
        return value > rule.threshold if rule.op == ">" else value < rule.threshold

    def _transition(self, rule: AlertRule, labels: str, st: _SeriesState,
                    new_state: str, now_ms: int, value: float) -> None:
        old = st.state
        st.state = new_state
        st.since_ms = now_ms
        st.value = value
        if self.on_transition is not None and old != new_state:
            try:
                self.on_transition(rule, labels, old, new_state)
            except Exception:  # noqa: BLE001 — a listener (flight recorder)
                pass           # failure must not stop rule evaluation

    def _mine(self, labels: str) -> bool:
        """Node scoping: the sampler snapshots the process-global registry,
        so in a multi-broker process every evaluator sees every broker's
        node-labeled series — evaluate only our own. Series without a
        ``node`` label (exporter lag, dropped requests) are process-scoped
        by construction and pass through (one broker per process in the
        deployed shape)."""
        if not self.node_id or 'node="' not in labels:
            return True
        return f'node="{self.node_id}"' in labels

    def _eval_threshold(self, rule: AlertRule, now_ms: int) -> None:
        for entry in self.store.latest(rule.series):
            if entry["name"] != rule.series:
                continue  # latest() prefix-matches histogram children
            labels = entry["labels"]
            if rule.labels_contains and rule.labels_contains not in labels:
                continue
            if not self._mine(labels):
                continue
            st = self._states.setdefault((rule.name, labels), _SeriesState())
            value = entry["value"]
            stale = now_ms - entry["t"] > STALE_AFTER_MS
            if stale or not self._breaches(rule, value):
                if st.state != INACTIVE:
                    self._transition(rule, labels, st, INACTIVE, now_ms, value)
                continue
            if st.state == INACTIVE:
                self._transition(rule, labels, st, PENDING, now_ms, value)
            elif st.state == PENDING and now_ms - st.since_ms >= rule.for_ms:
                self._transition(rule, labels, st, FIRING, now_ms, value)
            else:
                st.value = value

    def _eval_changes(self, rule: AlertRule, now_ms: int) -> None:
        for entry in self.store.query(rule.series, now_ms - rule.window_ms):
            if entry["name"] != rule.series:
                continue
            labels = entry["labels"]
            if rule.labels_contains and rule.labels_contains not in labels:
                continue
            if not self._mine(labels):
                continue
            samples = entry["samples"]
            changes = sum(
                1 for (_, a), (_, b) in zip(samples, samples[1:]) if a != b
            )
            st = self._states.setdefault((rule.name, labels), _SeriesState())
            if changes >= rule.threshold:
                if st.state != FIRING:
                    # changes-in-window IS the for-duration: fire immediately
                    self._transition(rule, labels, st, FIRING, now_ms,
                                     float(changes))
                else:
                    st.value = float(changes)
            elif st.state != INACTIVE:
                self._transition(rule, labels, st, INACTIVE, now_ms,
                                 float(changes))

    def evaluate(self, now_ms: int) -> None:
        for rule in self.rules:
            if rule.kind == "changes":
                self._eval_changes(rule, now_ms)
            else:
                self._eval_threshold(rule, now_ms)
        firing_per_rule: dict[str, int] = {r.name: 0 for r in self.rules}
        for (rule_name, _), st in self._states.items():
            if st.state == FIRING and rule_name in firing_per_rule:
                firing_per_rule[rule_name] += 1
        for rule_name, count in firing_per_rule.items():
            self._gauges[rule_name].set(float(count))

    # -- views -----------------------------------------------------------------

    def firing(self) -> list[dict]:
        return [a for a in self.snapshot() if a["state"] == FIRING]

    def snapshot(self) -> list[dict]:
        by_rule = {r.name: r for r in self.rules}
        out = []
        for (rule_name, labels), st in sorted(self._states.items()):
            if st.state == INACTIVE:
                continue
            rule = by_rule.get(rule_name)
            out.append({
                "rule": rule_name,
                "labels": labels,
                "state": st.state,
                "sinceMs": st.since_ms,
                "value": st.value,
                "severity": rule.severity if rule else "warning",
                "expr": rule.describe() if rule else "",
            })
        return out
