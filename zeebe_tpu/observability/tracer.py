"""Process-global tracer: the one object every instrumentation point checks.

The hot paths (writer append, stream processor command loop, exporter
delivery) each pay exactly ONE attribute read — ``if tracer.enabled:`` — when
tracing is off; everything else lives behind that guard. ``get_tracer()``
always returns the same singleton and ``configure_tracing`` mutates it in
place, so call sites may cache the reference at construction time and never
observe a stale tracer.

Three cross-cutting services ride on the tracer besides span emission:

- **append→ack latency**: ``note_append`` stamps a command position at append
  time; the stream processor takes the stamp when the command's step commits
  and feeds the ``command_ack_latency`` histogram (scope=processor). The
  gateway runtime observes the same histogram request→response
  (scope=gateway). A bounded reservoir of raw values backs ``bench.py
  --trace``'s p50/p99.
- **export dedupe**: per-(exporter, partition, position) first-seen check so
  at-least-once re-delivery after a crash-restart can never duplicate an
  ``exporter.export`` span (the zero-duplicate-spans replay contract).
- **sampling**: delegated to the seeded :class:`DeterministicSampler` so a
  chaos run replayed from its seed traces the same records.

Replay never reaches the tracer at all: spans are minted only on the live
processing path (gateway submit, client_write, PROCESSING-phase steps,
exporter delivery) — ``StreamProcessor.replay_available`` has no tracing
hooks, which is what makes crash-restart replay structurally unable to
emit duplicate spans.

Environment activation (for ``zeebe_tpu.standalone`` and friends, no code
change needed): ``ZEEBE_TRACING=1`` enables at startup;
``ZEEBE_TRACE_SAMPLE_RATE`` (default 1.0), ``ZEEBE_TRACE_SEED`` (default 0)
and ``ZEEBE_TRACE_CAPACITY`` (default 16384) tune it.
"""

from __future__ import annotations

import os
import time

from zeebe_tpu.observability.span import (
    DeterministicSampler,
    Span,
    SpanCollector,
    now_us,
)
from zeebe_tpu.utils import evict_oldest_half as _evict_oldest_half
from zeebe_tpu.utils.metrics import REGISTRY as _REG

# command→ack end-to-end histogram (the latency-attribution companion to the
# reference-parity process_instance_execution_time / job_life_time, which the
# exporter director's ExecutionLatencyObserver already serves):
#   scope=gateway   — request submitted → response received (full round trip)
#   scope=processor — command appended → step committed + response dispatched
_M_ACK_LATENCY = _REG.histogram(
    "command_ack_latency",
    "seconds from command submission/append to acknowledgment",
    ("scope",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 1.0, 2.5, 5.0, 10.0),
)

_APPEND_TABLE_LIMIT = 65536
_ROOT_TABLE_LIMIT = 131072
_EXPORT_SEEN_LIMIT = 65536
_ACK_RESERVOIR_LIMIT = 262144


class Tracer:
    __slots__ = ("enabled", "collector", "sampler", "_append_t", "_roots",
                 "_export_seen", "_ack_reservoir", "_ack_children")

    def __init__(self) -> None:
        self.enabled = False
        self.collector = SpanCollector()
        self.sampler = DeterministicSampler()
        # (partition, position) → perf_counter at append, bounded
        self._append_t: dict[tuple[int, int], float] = {}
        # (partition, position) → transitive root command position, bounded.
        # Populated at append time batch by batch (appends are ordered, so a
        # batch's source is registered before the batch itself), which keeps
        # multi-hop causal chains — a follow-up command's own follow-ups —
        # on their ORIGINAL trace id instead of fragmenting per hop
        self._roots: dict[tuple[int, int], int] = {}
        # ordered set (dict) of export-span identities already emitted
        self._export_seen: dict[tuple, None] = {}
        # raw ack latencies (seconds) for p50/p99; bounded — past the cap the
        # percentiles summarize the run's first N acks, which is fine for the
        # bench's steady-state question
        self._ack_reservoir: list[float] = []
        self._ack_children = {
            "gateway": _M_ACK_LATENCY.labels("gateway"),
            "processor": _M_ACK_LATENCY.labels("processor"),
        }

    # -- lifecycle -------------------------------------------------------------

    def enable(self, seed: int = 0, sample_rate: float = 1.0,
               capacity: int = 16384, reset: bool = True) -> None:
        if reset:
            self.clear()
        self.sampler = DeterministicSampler(seed=seed, rate=sample_rate)
        if capacity != self.collector.capacity:
            self.collector.resize(capacity)
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self.collector.clear()
        self._append_t.clear()
        self._roots.clear()
        self._export_seen.clear()
        self._ack_reservoir.clear()

    # -- sampling / emission ---------------------------------------------------

    def sampled(self, trace_id: str) -> bool:
        return self.sampler.sampled(trace_id)

    def emit(self, trace_id: str, name: str, dur_s: float,
             partition_id: int = 0, parent: str = "",
             attrs: dict | None = None, start_us: int | None = None) -> None:
        """Record a span that just finished (start is back-dated by the
        duration unless the caller positions it with ``start_us`` — waits
        that ended BEFORE emission time, like a command's backlog wait
        reported at group end, must carry their real interval or the
        critical-path sweep would charge them to the wrong segment).
        Caller is responsible for the ``enabled`` + ``sampled`` guards —
        this method only materializes the span."""
        dur_us = int(dur_s * 1e6)
        if start_us is None:
            start_us = now_us() - dur_us
        self.collector.add(Span(trace_id, name, start_us, dur_us,
                                partition_id, parent, attrs))

    # -- trace roots (transitive causal lineage) -------------------------------

    def register_batch(self, partition_id: int, first_position: int,
                       count: int, source_position: int) -> None:
        """Record each appended record's transitive ROOT command position: a
        sourced batch inherits its source's root (the source was appended —
        and registered — earlier), a source-less batch's records are their
        own roots (client/scheduled/inter-partition commands)."""
        table = self._roots
        if len(table) + count >= _ROOT_TABLE_LIMIT:
            _evict_oldest_half(table, max(_ROOT_TABLE_LIMIT, len(table)))
        if source_position >= 1:
            root = table.get((partition_id, source_position), source_position)
            for i in range(count):
                table[(partition_id, first_position + i)] = root
        else:
            for i in range(count):
                table[(partition_id, first_position + i)] = first_position + i

    def resolve_root(self, partition_id: int, position: int,
                     fallback: int) -> int:
        """The registered transitive root of ``position`` (falls back to the
        caller's one-hop guess when the table evicted it or the record
        predates tracing being enabled)."""
        return self._roots.get((partition_id, position), fallback)

    # -- command→ack latency ---------------------------------------------------

    def note_append(self, partition_id: int, position: int) -> None:
        table = self._append_t
        if len(table) >= _APPEND_TABLE_LIMIT:
            _evict_oldest_half(table, _APPEND_TABLE_LIMIT)
        table[(partition_id, position)] = time.perf_counter()

    def take_append(self, partition_id: int, position: int) -> float | None:
        return self._append_t.pop((partition_id, position), None)

    def observe_ack(self, scope: str, seconds: float) -> None:
        self._ack_children[scope].observe(seconds)
        if self.enabled and len(self._ack_reservoir) < _ACK_RESERVOIR_LIMIT:
            self._ack_reservoir.append(seconds)

    def latency_percentiles(self) -> dict:
        """p50/p99 over the collected ack latencies (milliseconds)."""
        values = sorted(self._ack_reservoir)
        if not values:
            return {"ack_count": 0}
        def pct(q: float) -> float:
            idx = min(len(values) - 1, int(q * (len(values) - 1) + 0.5))
            return round(values[idx] * 1000.0, 4)
        return {
            "ack_count": len(values),
            "ack_p50_ms": pct(0.50),
            "ack_p99_ms": pct(0.99),
        }

    # -- export dedupe ---------------------------------------------------------

    def mark_exported(self, identity: tuple) -> bool:
        """True exactly once per identity — the second delivery of the same
        (exporter, partition, position), e.g. at-least-once re-delivery after
        a crash-restart, emits no span."""
        seen = self._export_seen
        if identity in seen:
            return False
        if len(seen) >= _EXPORT_SEEN_LIMIT:
            _evict_oldest_half(seen, _EXPORT_SEEN_LIMIT)
        seen[identity] = None
        return True


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer singleton (mutated in place by
    ``configure_tracing`` — cached references never go stale)."""
    return _TRACER


def configure_tracing(enabled: bool = True, seed: int = 0,
                      sample_rate: float = 1.0, capacity: int = 16384,
                      reset: bool = True) -> Tracer:
    if enabled:
        _TRACER.enable(seed=seed, sample_rate=sample_rate, capacity=capacity,
                       reset=reset)
    else:
        _TRACER.disable()
        if reset:
            _TRACER.clear()
    return _TRACER


def _configure_from_env() -> None:
    if os.environ.get("ZEEBE_TRACING", "").lower() not in ("1", "true", "yes"):
        return
    try:
        rate = float(os.environ.get("ZEEBE_TRACE_SAMPLE_RATE", "1.0"))
        seed = int(os.environ.get("ZEEBE_TRACE_SEED", "0"))
        capacity = int(os.environ.get("ZEEBE_TRACE_CAPACITY", "16384"))
    except ValueError:
        rate, seed, capacity = 1.0, 0, 16384
    _TRACER.enable(seed=seed, sample_rate=rate, capacity=capacity)


_configure_from_env()
