"""Span model + bounded collector for the distributed-tracing subsystem.

Reference shape: Dapper (Sigelman et al., 2010) spans with Canopy-style
(Kaldor et al., SOSP '17) end-to-end latency attribution. The reference
broker has no tracing at all (SURVEY §5.1) — its only latency story is
per-actor metrics; this module is the span substrate the rest of
``zeebe_tpu.observability`` builds on.

Design constraints:

- **Bounded**: the collector is a per-process ring buffer (``deque`` with a
  ``maxlen``) — tracing can never grow memory without bound, the oldest spans
  simply fall off.
- **Deterministic**: the sampler's keep/drop decision is a pure function of
  (seed, trace id), so a chaos run replayed from its seed samples the exact
  same traces and the span stream is reproducible.
- **Cheap**: ``Span`` is a plain ``__slots__`` class (no dataclass machinery
  on the hot path) and the sampler is one crc32 over a short key.

Exports open directly in Perfetto / ``chrome://tracing`` via the Chrome
trace-event JSON format (one complete-event ``"ph": "X"`` per span), or as
JSONL for ad-hoc tooling.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import zlib
from typing import Iterable


class Span:
    """One timed operation. ``trace_id`` groups the spans of one causal
    chain (for record lineage: ``"<partition>:<root command position>"``);
    ``parent`` names the parent span within the trace (span granularity is
    coarse enough here that a name, not an id, disambiguates)."""

    __slots__ = ("trace_id", "name", "start_us", "dur_us", "partition_id",
                 "parent", "attrs")

    def __init__(self, trace_id: str, name: str, start_us: int, dur_us: int,
                 partition_id: int = 0, parent: str = "",
                 attrs: dict | None = None) -> None:
        self.trace_id = trace_id
        self.name = name
        self.start_us = start_us
        self.dur_us = dur_us
        self.partition_id = partition_id
        self.parent = parent
        self.attrs = attrs

    def to_dict(self) -> dict:
        out = {
            "traceId": self.trace_id,
            "name": self.name,
            "startUs": self.start_us,
            "durUs": self.dur_us,
            "partitionId": self.partition_id,
        }
        if self.parent:
            out["parent"] = self.parent
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class DeterministicSampler:
    """Head-based sampling whose decision is a pure function of
    (seed, trace id): crc32 over the seeded key against a rate threshold.
    Same seed + same trace ids ⇒ same sampled set, run after run — the
    property that keeps seeded chaos runs replayable with tracing on."""

    def __init__(self, seed: int = 0, rate: float = 1.0) -> None:
        self.seed = seed
        self.rate = max(0.0, min(1.0, rate))
        self._all = self.rate >= 1.0
        self._none = self.rate <= 0.0
        self._threshold = int(self.rate * 0x1_0000_0000)
        self._seed_crc = zlib.crc32(str(seed).encode("ascii"))

    def sampled(self, trace_id: str) -> bool:
        if self._all:
            return True
        if self._none:
            return False
        return zlib.crc32(trace_id.encode("utf-8"),
                          self._seed_crc) < self._threshold


class SpanCollector:
    """Bounded per-process span ring buffer. Adds take the lock — the
    ``emitted`` counter is a read-modify-write and ``resize`` swaps the
    deque, so a lock-free add could undercount or land a span on an
    orphaned buffer. The lock is only paid for spans that survived the
    enabled + sampled guards. ``emitted`` counts every span ever added —
    ``emitted - len(self)`` is the number the ring has already evicted."""

    def __init__(self, capacity: int = 16384) -> None:
        self.capacity = capacity
        self._spans: collections.deque[Span] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.emitted = 0

    def add(self, span: Span) -> None:
        with self._lock:
            self.emitted += 1
            self._spans.append(span)

    def __len__(self) -> int:
        return len(self._spans)

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.emitted = 0

    def resize(self, capacity: int) -> None:
        with self._lock:
            self.capacity = capacity
            self._spans = collections.deque(self._spans, maxlen=capacity)

    # -- export ---------------------------------------------------------------

    def to_jsonl(self, path) -> int:
        """One span JSON object per line; returns the number written."""
        spans = self.snapshot()
        with open(path, "w") as f:
            for span in spans:
                f.write(json.dumps(span.to_dict()))
                f.write("\n")
        return len(spans)

    def chrome_trace(self) -> dict:
        return chrome_trace(self.snapshot())

    def write_chrome_trace(self, path) -> int:
        spans = self.snapshot()
        with open(path, "w") as f:
            json.dump(chrome_trace(spans), f)
            f.write("\n")
        return len(spans)


def chrome_trace(spans: Iterable[Span]) -> dict:
    """Chrome trace-event JSON (the format Perfetto and ``chrome://tracing``
    open directly): one complete event per span, process = partition, one
    thread lane per trace id so a trace's spans stack together visually."""
    tids: dict[str, int] = {}
    events = []
    for span in spans:
        tid = tids.get(span.trace_id)
        if tid is None:
            tid = len(tids) + 1
            tids[span.trace_id] = tid
        args = {"traceId": span.trace_id}
        if span.parent:
            args["parent"] = span.parent
        if span.attrs:
            args.update(span.attrs)
        events.append({
            "name": span.name,
            "cat": "zeebe",
            "ph": "X",
            "ts": span.start_us,
            "dur": max(span.dur_us, 1),
            "pid": span.partition_id,
            "tid": tid,
            "args": args,
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "zeebe_tpu.observability"},
    }


def now_us() -> int:
    return int(time.time() * 1e6)
