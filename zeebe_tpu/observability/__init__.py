"""Distributed tracing, record lineage & the cluster metrics plane.

What the reference broker never had (SURVEY §5.1): a Dapper-style span layer
over the engine's own causal substrate — and what it outsourced to
Prometheus: retained metric history. Seven pieces:

- ``span``: the span model, the seeded deterministic sampler, and the
  bounded per-process collector with JSONL / Chrome-trace (Perfetto) export.
- ``tracer``: the process-global tracer every instrumentation point guards
  on; also owns the ``command_ack_latency`` end-to-end histogram and the
  export-span dedupe that keeps crash-restart replay duplicate-free.
- ``lineage``: the offline causal-tree walker — reconstructs a process
  instance's full record lineage from a journal alone, via the
  ``source_record_position`` backlinks every sequenced batch already carries.
- ``timeseries``: Gorilla-style in-memory bounded-retention time-series
  store + the registry sampler (counters→rates, histograms→p50/p99) behind
  ``GET /timeseries`` and the alert evaluator.
- ``flight_recorder``: per-partition bounded event rings (role changes,
  errors, backpressure, flush stalls, exporter transitions, batch
  summaries), dumped to ``<data-dir>/flight-<ts>.json`` on crash/unhealthy.
- ``auditor``: the fleet auditor (PR 20) — online invariant monitors
  (position monotonicity, exporter gaplessness, quarantine-latch bounds,
  replica-CRC spot checkpoints), multi-window SLO burn-rate alerting
  layered on ``alerts``, and windowed least-squares leak-trend detection
  over process resources; per-broker off the sampler tick, cross-worker
  via the status push (``ClusterAuditor``).
- ``alerts``: threshold + for-duration rules over the time-series store
  (default set: lag / backpressure / flush latency / role flapping /
  XLA recompile storms), surfaced in ``/health`` and the
  ``zeebe_alerts_firing`` gauge.
- ``critical_path``: the offline latency observatory (PR 19) — merges
  per-process span dumps by derived trace id and attributes every
  microsecond of each request's gateway-observed latency to exactly one
  edge (queue / coalesce / replicate / fsync / device / host-execute /
  reply), Canopy-style, with a conservation check; plus the in-broker
  ``LatencyObservatory`` that dumps the window's worst traces via the
  flight recorder.
- ``profiler``: the continuous profiling plane — an always-on low-rate
  folded-stack sampler (``GET /profile/continuous``), the kernel backend's
  XLA compile telemetry sink, device-memory gauges, alert-triggered profile
  capture into the flight recorder, and single-flight on-demand
  ``jax.profiler.trace()`` captures (``POST /profile/device``).

Spans are emitted ONLY on live processing (gateway request, command append,
backpressure acquire, journal group-flush, PROCESSING-phase steps and their
pipeline stages, exporter delivery). Replay emits nothing, by construction.
"""

from zeebe_tpu.observability.alerts import (
    AlertEvaluator,
    AlertRule,
    default_rules,
)
from zeebe_tpu.observability.auditor import (
    AuditorCfg,
    BrokerAuditor,
    BurnRateTracker,
    ClusterAuditor,
    TrendDetector,
)
from zeebe_tpu.observability.critical_path import (
    EDGES,
    LatencyObservatory,
    aggregate_breakdowns,
    assemble,
    breakdowns_from_spans,
    check_conservation,
    extract_trace,
    load_spans,
    top_stages,
)
from zeebe_tpu.observability.flight_recorder import FlightRecorder
from zeebe_tpu.observability.lineage import collect_lineage, format_lineage
from zeebe_tpu.observability.profiler import (
    AlertProfileCapture,
    CaptureInFlight,
    ContinuousProfiler,
    DeviceTraceCapture,
    acquire_profiler,
    observe_compile,
    release_profiler,
    sample_device_memory,
)
from zeebe_tpu.observability.span import (
    DeterministicSampler,
    Span,
    SpanCollector,
    chrome_trace,
)
from zeebe_tpu.observability.timeseries import (
    MetricsSampler,
    TimeSeriesStore,
    summarize_store,
)
from zeebe_tpu.observability.tracer import (
    Tracer,
    configure_tracing,
    get_tracer,
)

__all__ = [
    "EDGES",
    "AlertEvaluator",
    "AlertProfileCapture",
    "AlertRule",
    "AuditorCfg",
    "BrokerAuditor",
    "BurnRateTracker",
    "CaptureInFlight",
    "ClusterAuditor",
    "ContinuousProfiler",
    "DeterministicSampler",
    "DeviceTraceCapture",
    "FlightRecorder",
    "LatencyObservatory",
    "MetricsSampler",
    "Span",
    "SpanCollector",
    "TimeSeriesStore",
    "Tracer",
    "TrendDetector",
    "acquire_profiler",
    "aggregate_breakdowns",
    "assemble",
    "breakdowns_from_spans",
    "check_conservation",
    "chrome_trace",
    "collect_lineage",
    "configure_tracing",
    "default_rules",
    "extract_trace",
    "format_lineage",
    "get_tracer",
    "load_spans",
    "observe_compile",
    "release_profiler",
    "sample_device_memory",
    "summarize_store",
    "top_stages",
]
