"""Distributed tracing & record-lineage observability.

What the reference broker never had (SURVEY §5.1): a Dapper-style span layer
over the engine's own causal substrate. Three pieces:

- ``span``: the span model, the seeded deterministic sampler, and the
  bounded per-process collector with JSONL / Chrome-trace (Perfetto) export.
- ``tracer``: the process-global tracer every instrumentation point guards
  on; also owns the ``command_ack_latency`` end-to-end histogram and the
  export-span dedupe that keeps crash-restart replay duplicate-free.
- ``lineage``: the offline causal-tree walker — reconstructs a process
  instance's full record lineage from a journal alone, via the
  ``source_record_position`` backlinks every sequenced batch already carries.

Spans are emitted ONLY on live processing (gateway request, command append,
backpressure acquire, journal group-flush, PROCESSING-phase steps and their
pipeline stages, exporter delivery). Replay emits nothing, by construction.
"""

from zeebe_tpu.observability.lineage import collect_lineage, format_lineage
from zeebe_tpu.observability.span import (
    DeterministicSampler,
    Span,
    SpanCollector,
    chrome_trace,
)
from zeebe_tpu.observability.tracer import (
    Tracer,
    configure_tracing,
    get_tracer,
)

__all__ = [
    "DeterministicSampler",
    "Span",
    "SpanCollector",
    "Tracer",
    "chrome_trace",
    "collect_lineage",
    "configure_tracing",
    "format_lineage",
    "get_tracer",
]
