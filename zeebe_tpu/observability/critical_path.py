"""Canopy-style critical-path attribution over merged span dumps (ISSUE 19).

PR 17 pipelined the pump — speculative cross-wave dispatch, flush-callback
acks, coalesced ingress — and the aggregate pipeline histograms stopped being
able to say what one acked request actually *waited on*: once stages overlap,
"p99 flush is high" no longer implies "requests waited on flush". Canopy
(Kaldor et al., SOSP '17) answers this with per-request latency attribution:
walk each trace's span DAG and charge every microsecond of the observed
end-to-end latency to exactly one edge. This module is that walk, offline and
pure — it consumes span dicts (``Span.to_dict()`` shape / span-JSONL lines)
and never touches the live tracer.

The edge vocabulary (every microsecond of a root's latency lands in exactly
one of these, or in ``unattributed``):

- ``queue``      — admission/backpressure acquire, processor backlog wait
- ``coalesce``   — ingress coalesce-window wait (enqueue → batch flush)
- ``replicate``  — raft append → quorum commit
- ``fsync``      — group commit → covering journal-flush callback
- ``device``     — kernel device compute (incl. mesh-runner submit)
- ``host-execute`` — host-side decode/materialize/append/sequencing
- ``reply``      — response build + dispatch back to the gateway

Attribution is an interval sweep: the root span (``gateway.request``, or a
``processor.ack`` append→ack envelope on gateway-less harnesses) defines the
window; child spans become edge-labeled intervals clipped to it; every
elementary segment of the window is charged to the covering interval with the
LATEST start (ties: the shorter span — the most specific cause wins, exactly
Canopy's "blame the deepest blocked-on edge" rule); uncovered segments are
``unattributed``. Conservation therefore holds by construction —
``sum(edges) + unattributed == total`` — and :func:`check_conservation`
re-verifies it on any (possibly hand-built or skew-damaged) breakdown.

Clock honesty: spans from different processes carry that process's wall
clock. Merging bounds skew (same host, NTP-disciplined) but does not
eliminate it — clipping to the root window keeps a skewed child from
inflating an edge past the measured total; skew instead surfaces as
``unattributed`` residual, which the bench gates below 10% of p99.

Group-batched commands (``processor.kernel_command`` with a ``group`` attr)
are substituted with their group's real interval (``processor.kernel_group``
on the ``"<partition>:g<pos>"`` trace) and the charged time is split across
``device`` / ``fsync`` / ``host-execute`` by the group's measured stage
fractions — a request that rode a wave waited the wave's wall, not its
1/N accounting share.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

# the closed edge vocabulary — aggregation reports every edge (zero-filled)
# so scenario breakdowns are comparable across runs
EDGES = ("queue", "coalesce", "replicate", "fsync", "device", "host-execute",
         "reply")

# span name → edge. Names mapped to None are markers/roots handled specially.
_EDGE_BY_NAME = {
    "gateway.admission": "queue",
    "broker.backpressure_acquire": "queue",
    "processor.backlog_wait": "queue",
    "gateway.coalesce_wait": "coalesce",
    "raft.replicate": "replicate",
    "processor.fsync_wait": "fsync",
    "processor.stage.device": "device",
    "kernel.mesh_submit": "device",
    "processor.speculative": "device",
    "broker.command_append": "host-execute",
    "processor.command": "host-execute",
    "processor.reply_release": "reply",
    "gateway.reply": "reply",
}

# group stage → edge, for splitting a group interval's charged time; the
# overlap stage is excluded (it is an accounting view of the same wall time)
_STAGE_EDGE = {
    "processor.stage.decode": "host-execute",
    "processor.stage.device": "device",
    "processor.stage.materialize": "host-execute",
    "processor.stage.append": "host-execute",
    "processor.stage.flush": "fsync",
}

_ROOT_NAMES = ("gateway.request", "processor.ack")


# -- assembly -----------------------------------------------------------------


def load_spans(paths) -> list[dict]:
    """Read span dicts from JSONL dump files (one span object per line);
    unreadable lines are skipped — a torn final line from a killed worker
    must not void the rest of the dump."""
    spans: list[dict] = []
    for path in paths:
        try:
            text = Path(path).read_text()
        except OSError:
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                span = json.loads(line)
            except ValueError:
                continue
            if isinstance(span, dict) and "traceId" in span:
                spans.append(span)
    return spans


def assemble(span_dicts) -> dict[str, list[dict]]:
    """Merge spans (from any number of processes) into one map
    ``trace id → spans``, ordered by start time within each trace. The trace
    id is DERIVED (``"<partition>:<root position>"``) identically on both
    sides of every process boundary, so merging is a plain group-by — no
    wire-level context propagation exists to get wrong."""
    traces: dict[str, list[dict]] = {}
    for span in span_dicts:
        traces.setdefault(span["traceId"], []).append(span)
    for spans in traces.values():
        spans.sort(key=lambda s: (s.get("startUs", 0), s.get("durUs", 0)))
    return traces


# -- per-trace extraction -----------------------------------------------------


def _attr(span: dict, key: str):
    attrs = span.get("attrs")
    return attrs.get(key) if isinstance(attrs, dict) else None


def _group_fractions(group_spans: list[dict]) -> dict[str, float]:
    """Edge fractions of a kernel group's wall, from its measured stage
    spans; empty when the group dump carries no stages (charge everything
    to host-execute then — honest about what was measured)."""
    by_edge: dict[str, float] = {}
    for span in group_spans:
        edge = _STAGE_EDGE.get(span.get("name", ""))
        if edge is not None:
            by_edge[edge] = by_edge.get(edge, 0.0) + max(span.get("durUs", 0), 0)
    total = sum(by_edge.values())
    if total <= 0:
        return {}
    return {edge: dur / total for edge, dur in by_edge.items()}


def extract_trace(spans: list[dict], traces: dict | None = None) -> list[dict]:
    """All breakdowns of one trace: one per root span (a trace spanning a
    whole instance lifetime holds several ack envelopes — each is its own
    attribution window). Traces with no root (infra/group traces, or
    processor-only spans whose ack fell off the ring) yield nothing."""
    roots = [s for s in spans if s.get("name") in _ROOT_NAMES]
    # prefer the gateway view when both exist: processor.ack envelopes nest
    # inside it and double-reporting the same wait would skew aggregation
    if any(s.get("name") == "gateway.request" for s in roots):
        roots = [s for s in roots if s.get("name") == "gateway.request"]
    return [_extract_one(root, spans, traces) for root in roots]


def _extract_one(root: dict, spans: list[dict],
                 traces: dict | None) -> dict:
    r0 = root.get("startUs", 0)
    r1 = r0 + max(root.get("durUs", 0), 0)
    root_pos = _attr(root, "position")
    # (start, end, latest-start priority key, edge-or-fractions)
    intervals: list[tuple[int, int, str | dict]] = []
    for span in spans:
        if span is root or span.get("name") in _ROOT_NAMES:
            continue
        if _attr(span, "outcome") == "discarded":
            continue  # discarded speculative work is off the request's path
        pos = _attr(span, "position")
        if root_pos is not None and pos is not None and pos != root_pos:
            continue  # a processor.ack window only owns its own command
        name = span.get("name", "")
        s0 = span.get("startUs", 0)
        s1 = s0 + max(span.get("durUs", 0), 0)
        edge: str | dict | None
        if name == "processor.kernel_command":
            edge = "host-execute"
            group_id = _attr(span, "group")
            group_spans = traces.get(group_id) if traces and group_id else None
            if group_spans:
                for gspan in group_spans:
                    if gspan.get("name") == "processor.kernel_group":
                        s0 = gspan.get("startUs", s0)
                        s1 = s0 + max(gspan.get("durUs", 0), 0)
                        break
                fractions = _group_fractions(group_spans)
                if fractions:
                    edge = fractions
        else:
            edge = _EDGE_BY_NAME.get(name)
        if edge is None:
            continue
        s0, s1 = max(s0, r0), min(s1, r1)  # clip: skew can't exceed the root
        if s1 > s0:
            intervals.append((s0, s1, edge))

    edges = {edge: 0.0 for edge in EDGES}
    covered = 0.0
    bounds = sorted({r0, r1, *(i[0] for i in intervals),
                     *(i[1] for i in intervals)})
    for seg0, seg1 in zip(bounds, bounds[1:]):
        best = None
        for s0, s1, edge in intervals:
            if s0 <= seg0 and s1 >= seg1:
                # latest start wins; tie → shorter span (most specific cause)
                key = (s0, -(s1 - s0))
                if best is None or key > best[0]:
                    best = (key, edge)
        if best is None:
            continue
        length = seg1 - seg0
        covered += length
        edge = best[1]
        if isinstance(edge, dict):
            for sub_edge, frac in edge.items():
                edges[sub_edge] += length * frac
        else:
            edges[edge] += length
    total = r1 - r0
    out = {
        "traceId": root.get("traceId", ""),
        "rootName": root.get("name", ""),
        "totalUs": float(total),
        "edges": {edge: round(value, 3) for edge, value in edges.items()},
        "unattributedUs": round(max(total - covered, 0.0), 3),
    }
    if root_pos is not None:
        out["position"] = root_pos
    return out


def breakdowns_from_spans(span_dicts) -> list[dict]:
    """Assemble + extract in one shot: every rooted attribution window in a
    span dump (cluster-merged or single-process)."""
    traces = assemble(span_dicts)
    out: list[dict] = []
    for spans in traces.values():
        out.extend(extract_trace(spans, traces))
    return out


# -- conservation -------------------------------------------------------------


def check_conservation(breakdown: dict, tolerance_frac: float = 0.005,
                       floor_us: float = 2.0) -> list[str]:
    """Violations of the attribution invariant on ONE breakdown: every edge
    non-negative, and ``sum(edges) + unattributed == total`` within
    ``tolerance_frac`` of the total (``floor_us`` absorbs rounding on
    microsecond-scale roots). The extractor satisfies this by construction —
    the check exists so hand-built or post-processed breakdowns (and any
    future extractor bug) fail loudly instead of mis-reporting."""
    violations: list[str] = []
    total = breakdown.get("totalUs", 0.0)
    unatt = breakdown.get("unattributedUs", 0.0)
    if total < 0:
        violations.append(f"negative total: {total}")
    if unatt < 0:
        violations.append(f"negative unattributed: {unatt}")
    edge_sum = 0.0
    for edge, value in breakdown.get("edges", {}).items():
        if value < 0:
            violations.append(f"negative edge {edge}: {value}")
        else:
            edge_sum += value
    drift = abs(edge_sum + unatt - total)
    if drift > max(tolerance_frac * abs(total), floor_us):
        violations.append(
            f"edge sum {edge_sum:.1f} + unattributed {unatt:.1f} != "
            f"total {total:.1f} (drift {drift:.1f}us)")
    return violations


# -- aggregation --------------------------------------------------------------


def _percentile(ordered: list, q: float) -> float:
    from zeebe_tpu.testing.evidence import percentile

    return percentile(ordered, q)


def aggregate_breakdowns(breakdowns: list[dict]) -> dict:
    """Per-edge critical-path contribution p50/p99 over a set of
    breakdowns (one bench scenario, one serving window). Absent edges count
    as 0 for a trace — the percentiles answer "how much of a request's
    latency is this stage", not "how slow is this stage when it appears"."""
    if not breakdowns:
        return {"traces": 0}
    totals = sorted(b["totalUs"] for b in breakdowns)
    residuals = sorted(b["unattributedUs"] for b in breakdowns)
    out_edges = {}
    for edge in EDGES:
        values = sorted(b["edges"].get(edge, 0.0) for b in breakdowns)
        out_edges[edge] = {
            "p50Us": round(_percentile(values, 0.50), 1),
            "p99Us": round(_percentile(values, 0.99), 1),
        }
    total_p99 = _percentile(totals, 0.99)
    residual_p99 = _percentile(residuals, 0.99)
    return {
        "traces": len(breakdowns),
        "totalUs": {"p50": round(_percentile(totals, 0.50), 1),
                    "p99": round(total_p99, 1)},
        "edges": out_edges,
        "unattributed": {
            "p50Us": round(_percentile(residuals, 0.50), 1),
            "p99Us": round(residual_p99, 1),
            # the conservation headline: residual p99 as a fraction of
            # measured p99 — the bench gates this below 0.10
            "fracOfP99": round(residual_p99 / total_p99, 4) if total_p99 else 0.0,
        },
    }


def top_stages(aggregate: dict, n: int = 3) -> list[dict]:
    """The ``n`` largest critical-path contributors by p99 — the GWP loop's
    "fix the top contributor" list. Zero-contribution edges are dropped;
    ``unattributed`` is reported by the caller separately, not ranked."""
    edges = aggregate.get("edges", {})
    ranked = sorted(edges.items(), key=lambda kv: -kv[1]["p99Us"])
    return [{"stage": edge, "p99Us": stats["p99Us"], "p50Us": stats["p50Us"]}
            for edge, stats in ranked[:n] if stats["p99Us"] > 0]


# -- live observatory (slow exemplars + flight events) ------------------------


class LatencyObservatory:
    """Per-partition windowed latency watcher: tracks the N worst acked
    traces per window, and on window roll (a) records ONE bounded
    ``critical_path`` flight event with the window's top critical-path
    stages, and (b) dumps the worst traces' full span trees through the
    flight recorder (``ZEEBE_FLIGHT_MAXDUMPBYTES`` applies) — so a p99
    breach always ships its own explanation.

    ``observe`` is called at ack release under the tracer's ``enabled``
    guard; off-path cost is zero. Extraction work happens once per window
    (N≤``worst_n`` traces), never per ack.
    """

    def __init__(self, tracer, flight, partition_id: int,
                 window_s: float = 5.0, worst_n: int = 3,
                 clock=time.monotonic) -> None:
        self.tracer = tracer
        self.flight = flight
        self.partition_id = partition_id
        self.window_s = window_s
        self.worst_n = max(worst_n, 1)
        self._clock = clock
        self._window_start = clock()
        self._worst: list[tuple[float, str]] = []  # (latency_s, trace_id)
        self._acks = 0
        self.last_top_stages: list[dict] = []
        self.last_window_acks = 0
        self.last_worst_ms = 0.0

    def observe(self, trace_id: str, latency_s: float) -> None:
        now = self._clock()
        if now - self._window_start >= self.window_s:
            self.roll(now)
        self._acks += 1
        worst = self._worst
        if len(worst) < self.worst_n:
            worst.append((latency_s, trace_id))
            worst.sort(reverse=True)
        elif latency_s > worst[-1][0]:
            worst[-1] = (latency_s, trace_id)
            worst.sort(reverse=True)

    def roll(self, now: float | None = None) -> None:
        """Close the current window: flight event + exemplar dump."""
        self._window_start = self._clock() if now is None else now
        worst, acks = self._worst, self._acks
        self._worst, self._acks = [], 0
        if not worst:
            return
        exemplar_ids = {trace_id for _, trace_id in worst}
        # one snapshot per window (ring-bounded), never per ack; the full
        # assembly is needed anyway so exemplars can resolve group traces
        traces = assemble(s.to_dict()
                          for s in self.tracer.collector.snapshot())
        breakdowns: list[dict] = []
        for trace_id in exemplar_ids:
            spans = traces.get(trace_id)
            if spans:
                breakdowns.extend(extract_trace(spans, traces))
        aggregate = aggregate_breakdowns(breakdowns)
        self.last_top_stages = top_stages(aggregate)
        self.last_window_acks = acks
        self.last_worst_ms = round(worst[0][0] * 1000.0, 3)
        if self.flight is None:
            return
        self.flight.record(
            self.partition_id, "critical_path",
            windowAcks=acks,
            worstMs=[round(latency * 1000.0, 3) for latency, _ in worst],
            topStages=self.last_top_stages,
            unattributedP99Us=aggregate.get("unattributed", {}).get("p99Us"),
        )
        exemplars = {
            trace_id: [span for span in traces.get(trace_id, ())]
            for _, trace_id in worst if trace_id in traces
        }
        if exemplars:
            self.flight.dump_payload("slow-exemplars", {
                "partitionId": self.partition_id,
                "worstMs": self.last_worst_ms,
                "topStages": self.last_top_stages,
                "traces": exemplars,
            })

    def status(self) -> dict | None:
        """The ``criticalPath`` block for ``/cluster/status`` — None until a
        window has rolled with data."""
        if not self.last_top_stages:
            return None
        return {
            "topStages": self.last_top_stages,
            "windowAcks": self.last_window_acks,
            "worstMs": self.last_worst_ms,
        }
