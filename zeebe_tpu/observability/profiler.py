"""Continuous profiling plane: always-on sampling, compile & device telemetry.

Datacenter practice (Google-Wide Profiling, Ren et al., IEEE Micro 2010)
settled on two complementary capture shapes: an **always-on, low-rate
sampler** whose cost disappears into noise but whose aggregate answers
"where do the cycles go" for any past window, and **on-demand deep captures**
for the moments that deserve a microscope. This module carries both for the
broker, plus the two telemetry sources the host profiler cannot see:

- :class:`ContinuousProfiler` — a daemon thread sampling every runtime
  thread's Python stack at a low configurable rate (default ~19 Hz — a prime
  rate, so the sampler cannot alias against millisecond-periodic work),
  aggregating **folded stacks** (semicolon-joined frames, the
  flamegraph.pl / speedscope input format) into bounded time-bucketed
  windows with whole-window eviction. Served at ``GET /profile/continuous``
  and snapshotted into flight dumps.
- **XLA compile telemetry** — :func:`observe_compile` is the sink for the
  kernel backend's compile seam (engine/kernel_backend.py times the first
  dispatch of every group geometry): ``zeebe_xla_compile_seconds`` histogram
  labeled by geometry bucket, ``zeebe_xla_compiles_total{cache=hit|miss}``
  where *miss* means the wall time exceeded the persistent-cache threshold
  (utils/xla_cache.py sets ``jax_persistent_cache_min_compile_time_secs`` to
  the same constant) — i.e. XLA really compiled instead of loading from disk.
- **Device memory telemetry** — :func:`sample_device_memory` reads
  ``device.memory_stats()`` into ``zeebe_device_memory_bytes{device,kind}``
  gauges (``kind=in_use|limit``), sampled on the broker control pump at the
  metrics cadence. Resolution of the device list is guarded the same way as
  broker startup: never touch an unpinned accelerator backend that has not
  already initialized (a wedged TPU tunnel can hang ``jax.devices()``).
- :class:`AlertProfileCapture` — when the alert evaluator transitions a rule
  to firing, records a short folded-stack profile into the flight recorder
  (throttled per rule), so a dump explains not just *what* fired but *what
  the threads were doing* at that moment.
- :class:`DeviceTraceCapture` — single-flight on-demand
  ``jax.profiler.trace()`` into ``<data-dir>/jax-trace-<ts>/`` behind
  ``POST /profile/device``, so the kernel chunks' ``TraceAnnotation``s
  (tracer.py) become visible in Perfetto/TensorBoard.

Cost contract (same shape as the metrics plane): ``profiling_hz=0``
constructs nothing — one is-None check; at the default 19 Hz one sampling
tick walks every thread's stack once (tens of microseconds at typical broker
thread counts), which stays within bench noise.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Callable, Iterable

from zeebe_tpu.utils.metrics import REGISTRY as _REG

DEFAULT_HZ = 19.0
DEFAULT_WINDOW_MS = 10_000
DEFAULT_MAX_WINDOWS = 30
DEFAULT_MAX_DEPTH = 48

# every sampler daemon carries this name so samplers can exclude each other:
# an in-process multi-broker cluster runs one per broker, and N wait-loops
# sampling each other is pure noise in every broker's profile
PROFILER_THREAD_NAME = "continuous-profiler"

# wall-time boundary between "the persistent XLA cache (or a trivial
# program) served this" and "XLA really compiled": the same 1.0s that
# utils/xla_cache.py sets as jax_persistent_cache_min_compile_time_secs —
# an executable that took longer than this to produce would have been
# written to the disk cache, so seeing the time again means a cache miss
COMPILE_MISS_THRESHOLD_S = 1.0

_M_COMPILE_SECONDS = _REG.histogram(
    "xla_compile_seconds",
    "wall seconds of the first kernel dispatch per group geometry "
    "(jit trace + lowering + XLA compile or persistent-cache load)",
    ("bucket",),
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 30.0, 60.0, 120.0))
_M_COMPILES = _REG.counter(
    "xla_compiles_total",
    "first kernel dispatches per group geometry, split by persistent-cache "
    "outcome (miss = wall time above the persistent-cache threshold, i.e. "
    "XLA really compiled)",
    ("cache",))
_M_DEVICE_MEMORY = _REG.gauge(
    "device_memory_bytes",
    "accelerator memory from device.memory_stats(), kind=in_use|limit "
    "(absent on backends without memory introspection, e.g. CPU)",
    ("device", "kind"))


# -- stack sampling -----------------------------------------------------------


def sample_threads(exclude_idents: Iterable[int] = (),
                   max_depth: int = DEFAULT_MAX_DEPTH,
                   ) -> list[tuple[str, list[str]]]:
    """One snapshot of every live thread's Python stack:
    ``[(thread_name, frames root→leaf)]``. The name map is taken fresh on
    every call, so threads spawned after a profiling window began still
    report by name instead of raw ident (the one-shot ``/profile``'s
    original bug). Frames are ``file.py:function`` — stable across samples
    (no line numbers), so folded stacks aggregate instead of exploding one
    entry per bytecode offset."""
    exclude = set(exclude_idents)
    names = {t.ident: t.name for t in threading.enumerate()}
    out: list[tuple[str, list[str]]] = []
    for ident, frame in sys._current_frames().items():
        if ident in exclude:
            continue
        frames: list[str] = []
        depth = 0
        while frame is not None and depth < max_depth:
            code = frame.f_code
            frames.append(
                f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
            frame = frame.f_back
            depth += 1
        frames.reverse()  # folded stacks read root-first
        out.append((names.get(ident, f"thread-{ident}"), frames))
    return out


def fold_stacks(stacks: list[tuple[str, list[str]]]) -> dict[str, int]:
    """Fold one snapshot into ``{"thread;root;...;leaf": 1}`` counts — the
    flamegraph.pl / speedscope collapsed-stack key, thread name as the root
    frame so per-thread flames separate in the graph."""
    out: dict[str, int] = {}
    for name, frames in stacks:
        key = ";".join([name, *frames]) if frames else name
        out[key] = out.get(key, 0) + 1
    return out


def folded_text(stacks: dict[str, int]) -> str:
    """``"stack count"`` lines, heaviest first — pipe straight into
    flamegraph.pl, or load as "collapsed stacks" in speedscope."""
    return "\n".join(
        f"{stack} {count}"
        for stack, count in sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))
    )


class _Window:
    __slots__ = ("start_ms", "samples", "stacks")

    def __init__(self, start_ms: int) -> None:
        self.start_ms = start_ms
        self.samples = 0
        self.stacks: dict[str, int] = {}


class ContinuousProfiler:
    """Always-on low-rate sampling profiler over every runtime thread.

    Aggregates folded stacks into ``window_ms`` buckets; at most
    ``max_windows`` windows are retained and eviction is whole-window (the
    same bounded-memory discipline as the time-series store's blocks).
    Sampling is driven by a daemon thread with deadline pacing (sleep-only
    pacing undershoots the requested rate by the per-tick work); windows are
    bucketed by ``clock_millis`` so a controlled-clock test is deterministic
    via :meth:`sample_now`."""

    def __init__(self, hz: float = DEFAULT_HZ,
                 window_ms: int = DEFAULT_WINDOW_MS,
                 max_windows: int = DEFAULT_MAX_WINDOWS,
                 clock_millis: Callable[[], int] | None = None,
                 max_depth: int = DEFAULT_MAX_DEPTH) -> None:
        self.hz = float(hz)
        self.window_ms = int(window_ms)
        self.max_windows = int(max_windows)
        self.max_depth = max_depth
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        self._windows: OrderedDict[int, _Window] = OrderedDict()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.samples_taken = 0
        self.achieved_hz = 0.0

    # -- sampling --------------------------------------------------------------

    def sample_now(self, now_ms: int | None = None) -> None:
        """One sampling tick (the thread loop calls this; tests and
        pump-driven callers call it directly with a controlled clock). Every
        profiler daemon thread is excluded — ours AND any sibling broker's
        in the same process (an in-process cluster runs one sampler per
        broker; their wait-loops are pure mutual noise) — but a direct
        caller's stack is real work and counts."""
        now = self.clock_millis() if now_ms is None else now_ms
        bucket = now - now % self.window_ms
        skip = {t.ident for t in threading.enumerate()
                if t.name == PROFILER_THREAD_NAME}
        stacks = fold_stacks(sample_threads(
            exclude_idents=skip, max_depth=self.max_depth))
        with self._lock:
            win = self._windows.get(bucket)
            if win is None:
                win = self._windows[bucket] = _Window(bucket)
                while len(self._windows) > self.max_windows:
                    self._windows.popitem(last=False)  # whole-window eviction
            for key, count in stacks.items():
                win.stacks[key] = win.stacks.get(key, 0) + count
            win.samples += 1
            self.samples_taken += 1

    def _run(self) -> None:
        interval = 1.0 / self.hz
        started = time.monotonic()
        next_tick = started + interval
        ticks = 0
        while not self._stop.is_set():
            try:
                self.sample_now()
            except Exception:  # noqa: BLE001 — a torn frame walk must never
                pass           # kill the sampler
            ticks += 1
            elapsed = time.monotonic() - started
            if elapsed > 0:
                self.achieved_hz = round(ticks / elapsed, 2)
            # deadline pacing: schedule against the ideal timeline so the
            # per-tick work does not silently lower the achieved rate
            delay = next_tick - time.monotonic()
            if delay <= 0:
                next_tick = time.monotonic() + interval  # overran: no burst
                continue
            if self._stop.wait(delay):
                break
            next_tick += interval

    def start(self) -> None:
        if self._thread is not None or self.hz <= 0:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=PROFILER_THREAD_NAME)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None

    # -- views -----------------------------------------------------------------

    def windows(self, since_ms: int = 0) -> list[dict]:
        with self._lock:
            return [
                {"startMs": w.start_ms, "windowMs": self.window_ms,
                 "samples": w.samples, "stacks": dict(w.stacks)}
                for w in self._windows.values()
                if w.start_ms + self.window_ms > since_ms
            ]

    def aggregate(self, since_ms: int = 0) -> dict[str, int]:
        """Folded-stack counts summed over every retained window that
        overlaps ``[since_ms, now]``."""
        out: dict[str, int] = {}
        with self._lock:
            for w in self._windows.values():
                if w.start_ms + self.window_ms <= since_ms:
                    continue
                for key, count in w.stacks.items():
                    out[key] = out.get(key, 0) + count
        return out

    def folded(self, since_ms: int = 0) -> str:
        return folded_text(self.aggregate(since_ms))

    def top_stacks(self, top: int = 10, since_ms: int = 0) -> list[dict]:
        ranked = sorted(self.aggregate(since_ms).items(),
                        key=lambda kv: (-kv[1], kv[0]))[:top]
        return [{"stack": s, "samples": c} for s, c in ranked]

    def hot_frames(self, top: int = 10, since_ms: int = 0) -> list[dict]:
        """Per-frame inclusive sample counts (a frame counts once per stack
        it appears in), heaviest first — the "top functions" view."""
        by_frame: dict[str, int] = {}
        total = 0
        for stack, count in self.aggregate(since_ms).items():
            total += count
            for frame in set(stack.split(";")[1:]):  # [0] is the thread name
                by_frame[frame] = by_frame.get(frame, 0) + count
        ranked = sorted(by_frame.items(), key=lambda kv: (-kv[1], kv[0]))[:top]
        return [
            {"frame": f, "samples": c,
             "pct": round(100.0 * c / max(total, 1), 1)}
            for f, c in ranked
        ]

    def snapshot_summary(self, top: int = 15) -> dict:
        """Compact view folded into flight dumps: totals + heaviest stacks.
        Bounded (``top`` stacks), so a dump stays readable."""
        with self._lock:
            windows = len(self._windows)
        return {
            "hz": self.hz,
            "achievedHz": self.achieved_hz,
            "samples": self.samples_taken,
            "windows": windows,
            "topStacks": self.top_stacks(top=top),
        }


# -- process-global sharing ---------------------------------------------------
#
# One sampler per PROCESS, not per broker: stack sampling is inherently
# process-wide (sys._current_frames sees every thread), so an in-process
# multi-broker cluster running N samplers would pay N full-process walks
# per tick to retain N copies of the same data — the same shape
# install_process_metrics already dedupes for the self-metrics collect
# hook. Brokers lease the shared instance; the last release stops it, so
# balanced acquire/release cannot leak state across test boundaries.

_SHARED_LOCK = threading.Lock()
_SHARED: ContinuousProfiler | None = None
_SHARED_LEASES: set[object] = set()


def acquire_profiler(hz: float,
                     clock_millis: Callable[[], int] | None = None,
                     window_ms: int = DEFAULT_WINDOW_MS,
                     max_windows: int = DEFAULT_MAX_WINDOWS,
                     ) -> tuple[ContinuousProfiler, object]:
    """Lease the process-global :class:`ContinuousProfiler`, starting it on
    first acquire. The first acquirer's parameters win for the sampler's
    lifetime (per-broker attribution is by thread name, not by instance).
    Returns ``(profiler, lease)``; pass the lease to
    :func:`release_profiler` exactly once."""
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = ContinuousProfiler(hz=hz, clock_millis=clock_millis,
                                         window_ms=window_ms,
                                         max_windows=max_windows)
            _SHARED.start()
        lease: object = object()
        _SHARED_LEASES.add(lease)
        return _SHARED, lease


def release_profiler(lease: object | None) -> None:
    """Return a lease from :func:`acquire_profiler`; stops and discards the
    shared sampler when the last lease goes. ``None`` / double release are
    no-ops (close-after-hard-crash must be safe)."""
    global _SHARED
    if lease is None:
        return
    with _SHARED_LOCK:
        _SHARED_LEASES.discard(lease)
        if not _SHARED_LEASES and _SHARED is not None:
            _SHARED.stop()
            _SHARED = None


# -- XLA compile telemetry ----------------------------------------------------


def observe_compile(bucket: str, seconds: float) -> str:
    """Record one compile-seam observation (the kernel backend's first
    dispatch of a group geometry). Returns the cache classification."""
    cache = "miss" if seconds >= COMPILE_MISS_THRESHOLD_S else "hit"
    _M_COMPILE_SECONDS.labels(bucket).observe(seconds)
    _M_COMPILES.labels(cache).inc()
    return cache


# -- device memory telemetry --------------------------------------------------

# cache for the cpu-pinned path ONLY: that platform set is static, while an
# accelerator process re-walks the initialized backends every tick — cheap,
# and a backend initialized later (first kernel dispatch) must still join
_DEVICES: list | None = None


def _resolve_devices() -> list:
    """The device list for memory sampling, guarded like broker startup:
    when the platform is pinned to cpu the in-process query is safe and the
    result is cached; otherwise only ALREADY-initialized backends are
    walked, uncached — ``jax.devices()`` would resolve (and initialize) the
    DEFAULT platform in-process, and a wedged TPU tunnel hangs that forever
    (broker startup probes it in a killable subprocess instead,
    ``utils/backend_probe.py``); the broker pump must never block on
    telemetry. A backend brought up later (first kernel dispatch) joins on
    a later tick."""
    global _DEVICES
    if _DEVICES is not None:
        return _DEVICES
    try:
        import jax

        if str(jax.config.jax_platforms or "").startswith("cpu"):
            _DEVICES = list(jax.devices())
            return _DEVICES
        from jax._src import xla_bridge

        return [device
                for backend in dict(getattr(xla_bridge, "_backends", None)
                                    or {}).values()
                for device in backend.local_devices()]
    except Exception:  # noqa: BLE001 — telemetry must never take a pump down
        return []  # transient (e.g. backend mid-init): retry on a later tick


_STAT_KINDS = (("bytes_in_use", "in_use"), ("bytes_limit", "limit"))


def sample_device_memory(devices: list | None = None) -> int:
    """Update ``zeebe_device_memory_bytes`` from ``device.memory_stats()``.
    Returns the number of gauge children updated (0 on backends without
    memory introspection — CPU devices report no stats)."""
    updated = 0
    for dev in (_resolve_devices() if devices is None else devices):
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — NotImplemented on some backends
            continue
        if not stats:
            continue
        label = f"{getattr(dev, 'platform', 'device')}:{getattr(dev, 'id', 0)}"
        for stat_key, kind in _STAT_KINDS:
            value = stats.get(stat_key)
            if value is not None:
                _M_DEVICE_MEMORY.labels(label, kind).set(float(value))
                updated += 1
    return updated


# -- alert-triggered capture --------------------------------------------------

ALERT_CAPTURE_MIN_INTERVAL_MS = 30_000


class AlertProfileCapture:
    """Records a short folded-stack profile into the flight recorder when an
    alert rule transitions to firing — throttled per rule, so a flapping
    alert cannot flood the rings. With a continuous profiler attached the
    capture is its recent aggregate (zero extra sampling work); without one
    it takes a single instantaneous stack snapshot (one
    ``sys._current_frames()`` pass — safe on the pump thread)."""

    def __init__(self, recorder, profiler: ContinuousProfiler | None = None,
                 min_interval_ms: int = ALERT_CAPTURE_MIN_INTERVAL_MS,
                 clock_millis: Callable[[], int] | None = None,
                 top: int = 10) -> None:
        self.recorder = recorder
        self.profiler = profiler
        self.min_interval_ms = min_interval_ms
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        self.top = top
        self._last_ms: dict[str, int] = {}

    def on_firing(self, rule_name: str, labels: str = "") -> bool:
        now = self.clock_millis()
        last = self._last_ms.get(rule_name)
        if last is not None and now - last < self.min_interval_ms:
            return False
        self._last_ms[rule_name] = now
        if self.profiler is not None and self.profiler.samples_taken:
            source = "continuous"
            stacks = self.profiler.top_stacks(
                top=self.top, since_ms=now - 2 * self.profiler.window_ms)
        else:
            # one instantaneous snapshot, caller included: the firing pump
            # thread's stack is precisely the "what was it doing" evidence
            source = "instant"
            folded = fold_stacks(sample_threads())
            stacks = [{"stack": s, "samples": c}
                      for s, c in sorted(folded.items(),
                                         key=lambda kv: (-kv[1], kv[0]))
                      [:self.top]]
        self.recorder.record(0, "profile", rule=rule_name, labels=labels,
                             source=source, stacks=stacks)
        return True


# -- on-demand device capture -------------------------------------------------


class CaptureInFlight(RuntimeError):
    """A device trace capture is already running (single-flight guard)."""


class DeviceTraceCapture:
    """Single-flight ``jax.profiler.trace()`` capture into
    ``<base-dir>/jax-trace-<ts>/`` — the deep-capture half of the GWP shape.
    ``start()`` begins the trace and returns (a daemon thread stops it
    after ``seconds``); the first-ever call pays jax's one-time profiler
    backend init, which can take seconds. A second start while one is in
    flight raises :class:`CaptureInFlight` (the management endpoint maps
    it to 409) — instantly, even during that init. ``start_fn``/``stop_fn``
    are injectable for tests; the defaults bind
    ``jax.profiler.start_trace``/``stop_trace`` lazily."""

    def __init__(self, base_dir: str | Path,
                 start_fn: Callable[[str], None] | None = None,
                 stop_fn: Callable[[], None] | None = None) -> None:
        self.base_dir = Path(base_dir)
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._lock = threading.Lock()
        self._active_dir: Path | None = None
        self._cancel = threading.Event()
        self._thread: threading.Thread | None = None
        self.captures_taken = 0

    @property
    def active_dir(self) -> Path | None:
        return self._active_dir

    def start(self, seconds: float) -> Path:
        with self._lock:
            if self._active_dir is not None:
                raise CaptureInFlight(
                    f"device capture already in flight: {self._active_dir}")
            # monotonic nanos: unique even for back-to-back captures and
            # under a frozen test wall clock
            trace_dir = self.base_dir / f"jax-trace-{time.monotonic_ns()}"
            trace_dir.mkdir(parents=True, exist_ok=True)
            # reserve the slot before the (potentially slow) profiler start:
            # jax's first start_trace initializes the profiler backend, which
            # can take seconds — a concurrent start() must 409 instantly
            # rather than queue behind that init on this lock
            self._active_dir = trace_dir
            self._cancel.clear()
        try:
            start = self._start_fn
            if start is None:
                import jax

                start = jax.profiler.start_trace
            start(str(trace_dir))
        except Exception:
            with self._lock:
                self._active_dir = None
            try:
                trace_dir.rmdir()  # empty — don't leave a capture-shaped husk
            except OSError:
                pass
            raise

        def finish() -> None:
            self._cancel.wait(seconds)
            stop = self._stop_fn
            if stop is None:
                import jax

                stop = jax.profiler.stop_trace
            try:
                stop()
            except Exception:  # noqa: BLE001 — a failed stop must still
                pass           # release the single-flight slot
            finally:
                with self._lock:
                    self._active_dir = None
                    self.captures_taken += 1

        self._thread = threading.Thread(target=finish, daemon=True,
                                        name="device-trace-capture")
        self._thread.start()
        return trace_dir

    def wait(self, timeout: float = 10.0) -> None:
        """Block until the in-flight capture (if any) completes — tests and
        orderly shutdown."""
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)

    def cancel(self) -> None:
        """End an in-flight capture early (shutdown path)."""
        self._cancel.set()
        self.wait()
