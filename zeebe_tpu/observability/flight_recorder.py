"""Per-partition flight recorder: the broker's black box.

A crashed broker's most valuable telemetry is the few hundred events *before*
the crash — exactly what a pull-based ``/metrics`` scrape has already lost by
the time anyone looks. The flight recorder keeps a bounded ring of
operationally significant events per partition (role changes, processing
errors, backpressure rejections, slow journal flushes, exporter health
transitions, committed-batch summaries) plus a node-level ring (broker health
transitions, alert state changes), and

- serves the live rings at ``GET /flight`` on the management server, and
- **dumps them to ``<data-dir>/flight-<ts>.json``** when the broker crashes
  or turns unhealthy, so the postmortem evidence survives the process.

Events are tiny dicts appended to ``deque(maxlen=...)`` rings — recording is
O(1), allocation-light, and safe on any thread. Dumps are throttled (one per
``dump_min_interval_ms`` per reason class) so a flapping component cannot
turn the data dir into a log spool; rings are NOT cleared by a dump, so a
later, more fatal dump still carries the earlier context.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable

DEFAULT_CAPACITY = 256
DUMP_MIN_INTERVAL_MS = 5_000
# per-dump serialized-size cap (ISSUE 13 satellite): PR 12's autotune run
# committed multiple >4k-line flight JSONs — gate dumps must stay
# reviewable. Oldest ring entries drop first; the dump records how many.
DEFAULT_MAX_DUMP_BYTES = 262_144


def _max_dump_bytes() -> int:
    try:
        return int(os.environ.get("ZEEBE_FLIGHT_MAXDUMPBYTES",
                                  DEFAULT_MAX_DUMP_BYTES))
    except ValueError:
        return DEFAULT_MAX_DUMP_BYTES


class FlightRecorder:
    def __init__(self, node_id: str, data_dir: str | Path | None,
                 capacity: int = DEFAULT_CAPACITY,
                 clock_millis: Callable[[], int] | None = None,
                 max_dump_bytes: int | None = None) -> None:
        self.node_id = node_id
        self.data_dir = Path(data_dir) if data_dir is not None else None
        self.capacity = capacity
        self.max_dump_bytes = (max_dump_bytes if max_dump_bytes is not None
                               else _max_dump_bytes())
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        # partition id 0 = node-level ring (health, alerts, journal stalls)
        self._rings: dict[int, deque] = {}
        self._lock = threading.Lock()
        self._last_dump_ms: dict[str, int] = {}
        self.events_recorded = 0
        # extra context suppliers folded into dumps (alert snapshot etc.)
        self._context_providers: list[Callable[[], dict]] = []

    def add_context_provider(self, provider: Callable[[], dict]) -> None:
        self._context_providers.append(provider)

    def record(self, partition_id: int, kind: str, **detail) -> None:
        event = {"t": self.clock_millis(), "kind": kind, **detail}
        with self._lock:
            ring = self._rings.get(partition_id)
            if ring is None:
                ring = self._rings[partition_id] = deque(maxlen=self.capacity)
            ring.append(event)
            self.events_recorded += 1

    def occupancy(self) -> float:
        """Mean fill ratio across the live rings (0..1) — the fleet
        auditor's ``zeebe_flight_ring_occupancy_ratio`` source. Bounded
        rings saturate at 1.0 by design; the leak trend watches the CLIMB
        toward it, not the ceiling."""
        with self._lock:
            if not self._rings or self.capacity <= 0:
                return 0.0
            return sum(len(r) for r in self._rings.values()) / (
                len(self._rings) * self.capacity)

    def snapshot(self) -> dict:
        with self._lock:
            rings = {str(pid): list(ring)
                     for pid, ring in sorted(self._rings.items())}
        return {
            "nodeId": self.node_id,
            "capacityPerRing": self.capacity,
            "eventsRecorded": self.events_recorded,
            "partitions": rings,
        }

    def dump(self, reason: str, force: bool = False) -> Path | None:
        """Write the rings to ``<data-dir>/flight-<ts>.json``. Returns the
        path, or None when there is no data dir or the reason class dumped
        within the throttle window (``force`` bypasses the throttle — crashes
        always leave evidence)."""
        if self.data_dir is None:
            return None
        now = self.clock_millis()
        reason_class = reason.split(":", 1)[0]
        if not force:
            last = self._last_dump_ms.get(reason_class, -DUMP_MIN_INTERVAL_MS)
            if now - last < DUMP_MIN_INTERVAL_MS:
                return None
        self._last_dump_ms[reason_class] = now
        payload = self.snapshot()
        payload["reason"] = reason
        payload["dumpedAtMs"] = now
        for provider in self._context_providers:
            try:
                payload.update(provider())
            except Exception:  # noqa: BLE001 — context is best-effort; the
                pass           # rings themselves must always land on disk
        body = self._bounded_body(payload)
        # wall-clock nanos disambiguate dumps under a controlled test clock
        # (many dumps can share one frozen clock_millis value)
        path = self.data_dir / f"flight-{now}-{time.monotonic_ns()}.json"
        try:
            self.data_dir.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".json.tmp")
            tmp.write_bytes(body)
            tmp.replace(path)
        except OSError:
            return None  # a full/readonly disk must not turn a dump fatal
        return path

    def dump_payload(self, reason: str, payload: dict,
                     force: bool = False) -> Path | None:
        """Write a non-ring evidence payload (e.g. the latency observatory's
        slow-exemplar span trees) under the same data dir, per-reason-class
        throttle, and ``max_dump_bytes`` cap as ring dumps. Oversized
        payloads drop whole entries from a ``traces`` dict, largest first,
        recording ``truncatedTraces`` — a bounded dump is never mistaken
        for the full evidence."""
        if self.data_dir is None:
            return None
        now = self.clock_millis()
        reason_class = reason.split(":", 1)[0]
        if not force:
            last = self._last_dump_ms.get(reason_class, -DUMP_MIN_INTERVAL_MS)
            if now - last < DUMP_MIN_INTERVAL_MS:
                return None
        self._last_dump_ms[reason_class] = now
        doc = {"nodeId": self.node_id, "reason": reason, "dumpedAtMs": now}
        doc.update(payload)
        body = json.dumps(doc, indent=1, default=str).encode("utf-8")
        while self.max_dump_bytes > 0 and len(body) > self.max_dump_bytes:
            traces = doc.get("traces")
            if not isinstance(traces, dict) or not traces:
                break  # nothing droppable; ship what we have
            victim = max(traces, key=lambda t: len(traces[t]))
            del traces[victim]
            doc["truncatedTraces"] = doc.get("truncatedTraces", 0) + 1
            body = json.dumps(doc, indent=1, default=str).encode("utf-8")
        path = self.data_dir / f"flight-{now}-{time.monotonic_ns()}.json"
        try:
            self.data_dir.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".json.tmp")
            tmp.write_bytes(body)
            tmp.replace(path)
        except OSError:
            return None
        return path

    def _bounded_body(self, payload: dict) -> bytes:
        """Serialize a dump under ``max_dump_bytes`` (UTF-8 bytes on disk,
        not code points — non-ASCII event content must not overshoot the
        cap): oldest ring entries drop first (round-robin across the
        largest rings so one chatty partition cannot evict every other
        ring), and the dump records ``truncatedEntries`` so a bounded dump
        is never mistaken for the full evidence. Context providers are
        kept — they are small and per-dump (the rings are what grow)."""
        body = json.dumps(payload, indent=1, default=str).encode("utf-8")
        if self.max_dump_bytes <= 0 or len(body) <= self.max_dump_bytes:
            return body
        rings = {pid: list(events)
                 for pid, events in payload["partitions"].items()}
        truncated = 0
        while len(body) > self.max_dump_bytes:
            victim = max(rings, key=lambda pid: len(rings[pid]), default=None)
            if victim is None or not rings[victim]:
                break  # nothing left to drop; ship what we have
            # drop the oldest quarter of the largest ring per pass: a few
            # serialize rounds instead of one per event
            drop = max(1, len(rings[victim]) // 4)
            del rings[victim][:drop]
            truncated += drop
            payload["partitions"] = {p: r for p, r in rings.items() if r}
            payload["truncatedEntries"] = truncated
            body = json.dumps(payload, indent=1, default=str).encode("utf-8")
        return body


def install_journal_stall_listener(recorder: FlightRecorder) -> None:
    """Register the recorder on the journal module's slow-flush seam: a
    flush above ``journal.SLOW_FLUSH_THRESHOLD_S`` records a node-level
    ``flush_stall`` event (the journal is below the partition abstraction —
    it only knows its directory). The seam is module-global, so in a
    multi-broker process the recorder keeps only stalls under its own data
    directory — another broker's stalls are not this black box's evidence."""
    from zeebe_tpu.journal import journal as journal_mod

    prefix = str(recorder.data_dir) if recorder.data_dir is not None else ""

    def on_slow_flush(directory: str, seconds: float) -> None:
        if prefix and not directory.startswith(prefix):
            return
        recorder.record(0, "flush_stall", dir=directory,
                        seconds=round(seconds, 4))

    # identity-tagged so remove can find this recorder's listener
    on_slow_flush._flight_recorder = recorder  # type: ignore[attr-defined]
    journal_mod.slow_flush_listeners.append(on_slow_flush)


def remove_journal_stall_listener(recorder: FlightRecorder) -> None:
    from zeebe_tpu.journal import journal as journal_mod

    journal_mod.slow_flush_listeners[:] = [
        fn for fn in journal_mod.slow_flush_listeners
        if getattr(fn, "_flight_recorder", None) is not recorder
    ]
