"""The fleet auditor: online invariant monitoring, SLO burn-rate alerts,
and leak-trend detection (ISSUE 20).

Every safety property the repo bought so far (PRs 9/11/14/15) is verified
*offline, after* a ≤2-minute gate. "Gray Failure" (Huang et al., HotOS'17)
argues the dangerous production state is degraded-but-not-dead — invisible
to binary health checks — and the Autopilot discipline (Rzadca et al.,
EuroSys'20) that long-horizon operation must be *audited*, not assumed.
This module watches the cluster's own invariants while it runs:

- :class:`BrokerAuditor` — per-broker, ticked off the EXISTING sampler
  cadence inside ``Broker.pump_control``. Monitors:

  * **acked-position monotonicity** per partition (stream last position and
    the processor's last processed position must never move backward within
    a process life);
  * **exporter-sequence gaplessness** per (exporter, partition): the
    persisted cursor is monotone and never ahead of the log end, and the
    delivery watermark never trails the persisted cursor;
  * **quarantine-latch duration bounds**: a device-health ladder latched in
    QUARANTINED beyond the configured bound (the canary loop should have
    re-proved or kept failing a real device long before) is flagged;
  * **replica-CRC spot checkpoints**: a windowed CRC over the replicated
    log's record bytes, finalized per aligned position window — replicas
    that hold the same window MUST agree (Raft log matching), and the
    checkpoints ride the existing worker status push for the harness-side
    comparison.

  Verdicts become typed ``audit_alert`` flight events on the node ring,
  ``zeebe_audit_*`` metrics, and the ``audit`` block on
  ``/cluster/status`` (and therefore the worker status push).

- **multi-window SLO burn-rate alerting** (:class:`BurnRateTracker`),
  layered on ``alerts.py``: each auditor tick classifies the admission
  ack-p99 and goodput against the SLO, accumulates fast/slow windows in
  the auditor's OWN bucket rings (the Gorilla store's default retention is
  5 minutes — shorter than the slow window, so the store cannot back this
  signal), publishes ``zeebe_audit_burn_rate`` into the store, and lets
  the broker's :class:`~zeebe_tpu.observability.alerts.AlertEvaluator`
  fire page-vs-ticket rules over those series with its normal
  for-duration state machine.

- **resource-trend leak detection** (:class:`TrendDetector`): per-process
  RSS, fd count, thread count, flight-ring occupancy, and tracked
  tenant/table sizes, windowed least-squares slope with confidence gating
  — a genuine leak fires, a noisy flat line does not, and a one-off step
  is NOT a leak (both half-window slopes must agree with the full-window
  trend).

- :class:`ClusterAuditor` — the harness/gateway side: ingests the worker
  status rows the gateway already aggregates, joins replica-CRC
  checkpoints across workers per (partition, window), and checks
  acked-position monotonicity ACROSS pushes (a restarted worker re-serving
  an older position is visible here, not broker-side).

Honest caveats (docs/observability.md): per-broker monitors cannot see
cross-broker invariants (acked-write loss across a leader change is the
offline checker's domain); trend verdicts need at least two half-windows
of samples; the burn-rate windows default to the SRE-workbook 5m/1h but
the quick fleet-day gate shrinks them to fit minutes, not hours.
"""

from __future__ import annotations

import os
import zlib
from collections import deque
from dataclasses import dataclass, field

from zeebe_tpu.utils.metrics import REGISTRY as _REG

#: registered at import (the control-plane pattern) so the metrics-doc
#: scenario and the sampler see the families before the first verdict
_M_CHECKS = _REG.counter(
    "audit_checks_total",
    "online auditor invariant evaluations, by monitor", ("monitor",))
_M_VIOLATIONS = _REG.counter(
    "audit_violations_total",
    "online auditor invariant violations, by monitor", ("monitor",))
_M_BURN = _REG.gauge(
    "audit_burn_rate",
    "multi-window SLO burn rate (error-budget consumption multiple), by "
    "SLO and window", ("node", "slo", "window"))
_M_LEAK = _REG.gauge(
    "audit_leak_state",
    "resource-trend verdict per tracked resource (0=quiet, 1=warming, "
    "2=leak)", ("node", "resource"))
_M_ALERTS = _REG.gauge(
    "audit_alerts_active",
    "currently-latched online audit alerts on this broker", ("node",))
_M_RING = _REG.gauge(
    "flight_ring_occupancy_ratio",
    "mean fill ratio of the flight-recorder rings (0..1), sampled off the "
    "auditor tick", ("node",))


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class AuditorCfg:
    """Knobs for the online auditor. Windows default to the SRE-workbook
    multi-window pair (5m fast / 1h slow); the fleet-day quick gate
    shrinks everything to fit a minutes-long run."""

    enabled: bool = True
    #: burn-rate windows (ms) over the SLO-classified tick stream
    fast_window_ms: int = 300_000
    slow_window_ms: int = 3_600_000
    #: burn-rate thresholds (error-budget consumption multiples): page
    #: fires when BOTH windows exceed page_burn, ticket when both exceed
    #: ticket_burn (Google SRE workbook, multi-window multi-burn-rate)
    page_burn: float = 14.4
    ticket_burn: float = 6.0
    #: the availability SLO the burn rate is measured against (error
    #: budget = 1 - slo_target)
    slo_target: float = 0.999
    #: ack-p99 bound classifying a tick as SLO-bad (ms)
    slo_p99_ms: float = 5_000.0
    #: goodput floor classifying a tick as SLO-bad (acks / admitted)
    goodput_floor: float = 0.7
    #: leak-trend window (ms); verdicts need two half-windows of samples
    leak_window_ms: int = 600_000
    #: minimum samples before a trend verdict (on top of the time span)
    leak_min_samples: int = 20
    #: slope t-statistic above which a trend is significant
    leak_tstat: float = 8.0
    #: minimum relative growth over the window (fraction of the window
    #: mean) — keeps a statistically-clean but microscopic drift quiet
    leak_min_growth: float = 0.05
    #: hold-off before trend observation starts (ms since the first tick):
    #: boot-era allocation (XLA compilation, cache warmup, rings filling)
    #: is a genuine monotone climb that would otherwise read as a leak
    leak_warmup_ms: int = 60_000
    #: QUARANTINED latch bound (ms): longer trips the invariant monitor
    quarantine_max_ms: int = 300_000
    #: replica-CRC checkpoint window (positions per checkpoint)
    crc_window: int = 256
    #: records walked per tick for the CRC monitor (bounds pump cost)
    crc_batch: int = 2_000

    @classmethod
    def from_env(cls) -> "AuditorCfg":
        cfg = cls()
        cfg.enabled = os.environ.get(
            "ZEEBE_AUDIT_ENABLED", "1").lower() not in ("0", "false", "off")
        cfg.fast_window_ms = _env_int("ZEEBE_AUDIT_FASTWINDOWMS",
                                      cfg.fast_window_ms)
        cfg.slow_window_ms = _env_int("ZEEBE_AUDIT_SLOWWINDOWMS",
                                      cfg.slow_window_ms)
        cfg.leak_window_ms = _env_int("ZEEBE_AUDIT_LEAKWINDOWMS",
                                      cfg.leak_window_ms)
        cfg.leak_min_samples = _env_int("ZEEBE_AUDIT_LEAKMINSAMPLES",
                                        cfg.leak_min_samples)
        cfg.leak_min_growth = _env_float("ZEEBE_AUDIT_LEAKMINGROWTH",
                                         cfg.leak_min_growth)
        cfg.leak_warmup_ms = _env_int("ZEEBE_AUDIT_LEAKWARMUPMS",
                                      cfg.leak_warmup_ms)
        cfg.quarantine_max_ms = _env_int("ZEEBE_AUDIT_QUARANTINEMAXMS",
                                         cfg.quarantine_max_ms)
        cfg.slo_p99_ms = _env_float("ZEEBE_AUDIT_SLOP99MS", cfg.slo_p99_ms)
        cfg.slo_target = _env_float("ZEEBE_AUDIT_SLOTARGET", cfg.slo_target)
        cfg.goodput_floor = _env_float("ZEEBE_AUDIT_GOODPUTFLOOR",
                                       cfg.goodput_floor)
        cfg.crc_window = max(
            1, _env_int("ZEEBE_AUDIT_CRCWINDOW", cfg.crc_window))
        return cfg


# -- resource-trend leak detection --------------------------------------------


def least_squares_slope(samples: list[tuple[float, float]]
                        ) -> tuple[float, float]:
    """Ordinary least squares over ``(t_seconds, value)`` points: returns
    ``(slope_per_second, t_statistic)``. The t-stat is slope / stderr —
    the confidence gate that keeps a noisy flat line quiet (its slope is
    small relative to the residual scatter)."""
    n = len(samples)
    if n < 3:
        return 0.0, 0.0
    mean_t = sum(t for t, _ in samples) / n
    mean_v = sum(v for _, v in samples) / n
    sxx = sum((t - mean_t) ** 2 for t, _ in samples)
    if sxx <= 0.0:
        return 0.0, 0.0
    sxy = sum((t - mean_t) * (v - mean_v) for t, v in samples)
    slope = sxy / sxx
    residual = sum((v - mean_v - slope * (t - mean_t)) ** 2
                   for t, v in samples)
    if residual <= 0.0:
        # perfectly linear (a synthetic ramp, or a constant): infinite
        # confidence either way — report a large finite t-stat
        return slope, (1e9 if slope != 0.0 else 0.0)
    stderr = (residual / (n - 2) / sxx) ** 0.5
    return slope, (slope / stderr if stderr > 0 else 0.0)


class TrendDetector:
    """Windowed least-squares leak detector for ONE resource series.

    Feed it ``observe(t_ms, value)`` at any cadence; it keeps a bounded
    deque spanning the window and produces a verdict:

    - ``insufficient`` — fewer than ``min_samples`` points or less than
      two half-windows of time span (the documented caveat);
    - ``quiet`` — no statistically significant positive trend;
    - ``leak`` — the full-window slope is positive, significant
      (t-statistic above ``tstat``), projects at least ``min_growth``
      relative growth over the window, AND both half-windows agree the
      value is still climbing. The half-window agreement is what makes a
      one-off STEP not a leak: after a step, the later half is flat, so
      its slope collapses while the full-window slope stays large.
    """

    def __init__(self, window_ms: int, min_samples: int = 20,
                 tstat: float = 8.0, min_growth: float = 0.05) -> None:
        self.window_ms = int(window_ms)
        self.min_samples = int(min_samples)
        self.tstat = float(tstat)
        self.min_growth = float(min_growth)
        self._samples: deque[tuple[float, float]] = deque()
        self.last = None  # latest verdict dict (surfaces read it)

    def observe(self, t_ms: float, value: float) -> None:
        self._samples.append((float(t_ms), float(value)))
        horizon = t_ms - self.window_ms
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()

    def verdict(self) -> dict:
        pts = [(t / 1000.0, v) for t, v in self._samples]
        out: dict = {"state": "insufficient", "samples": len(pts),
                     "slopePerSec": 0.0, "tstat": 0.0}
        if len(pts) < self.min_samples:
            self.last = out
            return out
        span_s = pts[-1][0] - pts[0][0]
        if span_s * 1000.0 < self.window_ms * 0.5:
            # less than two half-windows of history: no verdict yet
            self.last = out
            return out
        slope, tstat = least_squares_slope(pts)
        mid = pts[0][0] + span_s / 2.0
        first = [p for p in pts if p[0] <= mid]
        second = [p for p in pts if p[0] > mid]
        slope_a, _ = least_squares_slope(first)
        slope_b, _ = least_squares_slope(second)
        mean_v = sum(v for _, v in pts) / len(pts)
        projected = slope * (self.window_ms / 1000.0)
        rel_growth = projected / mean_v if mean_v > 0 else (
            float("inf") if projected > 0 else 0.0)
        significant = (slope > 0.0 and tstat >= self.tstat
                       and rel_growth >= self.min_growth)
        # both halves must still be climbing (each at a meaningful share
        # of the full trend) — a step's later half is flat and vetoes
        halves_agree = (slope_a > 0.25 * slope and slope_b > 0.25 * slope)
        state = "leak" if (significant and halves_agree) else (
            "warming" if significant else "quiet")
        out.update({
            "state": state,
            "slopePerSec": round(slope, 6),
            "tstat": round(min(tstat, 1e9), 2),
            "relGrowthPerWindow": round(min(rel_growth, 1e9), 4),
            "halfSlopes": [round(slope_a, 6), round(slope_b, 6)],
            "spanMs": int(span_s * 1000),
        })
        self.last = out
        return out


# -- multi-window SLO burn-rate tracking --------------------------------------


class BurnRateTracker:
    """Fast/slow-window burn-rate state for ONE SLO.

    Each ``observe(now_ms, good, bad)`` adds a classified observation
    batch; windows are per-second buckets in bounded deques (the 1h slow
    window cannot ride the Gorilla store's 5-minute retention, so the
    tracker owns its history). ``evaluate`` returns the burn-rate pair and
    the page/ticket/ok state: burn rate = (bad fraction over the window) /
    error budget, the SRE-workbook error-budget-consumption multiple; an
    alert state needs BOTH windows above its threshold, which is what
    makes the fast window quick to clear after a transient."""

    def __init__(self, fast_window_ms: int, slow_window_ms: int,
                 slo_target: float = 0.999, page_burn: float = 14.4,
                 ticket_burn: float = 6.0) -> None:
        self.fast_window_ms = int(fast_window_ms)
        self.slow_window_ms = int(slow_window_ms)
        self.budget = max(1.0 - slo_target, 1e-9)
        self.page_burn = page_burn
        self.ticket_burn = ticket_burn
        # (second, good, bad) buckets, oldest first, bounded by slow window
        self._buckets: deque[list] = deque()
        self.state = "ok"

    def observe(self, now_ms: float, good: float, bad: float) -> None:
        sec = int(now_ms // 1000)
        if self._buckets and self._buckets[-1][0] == sec:
            self._buckets[-1][1] += good
            self._buckets[-1][2] += bad
        else:
            self._buckets.append([sec, float(good), float(bad)])
        horizon = sec - self.slow_window_ms // 1000 - 1
        while self._buckets and self._buckets[0][0] < horizon:
            self._buckets.popleft()

    def _rate(self, now_ms: float, window_ms: int) -> float:
        horizon = int(now_ms // 1000) - window_ms // 1000
        good = bad = 0.0
        for sec, g, b in reversed(self._buckets):
            if sec < horizon:
                break
            good += g
            bad += b
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / self.budget

    def evaluate(self, now_ms: float) -> dict:
        fast = self._rate(now_ms, self.fast_window_ms)
        slow = self._rate(now_ms, self.slow_window_ms)
        if fast >= self.page_burn and slow >= self.page_burn:
            self.state = "page"
        elif fast >= self.ticket_burn and slow >= self.ticket_burn:
            self.state = "ticket"
        else:
            self.state = "ok"
        return {"fast": round(fast, 3), "slow": round(slow, 3),
                "state": self.state}


def burn_rate_rules(node_id: str, cfg: AuditorCfg) -> list:
    """The layer onto ``alerts.py``: threshold rules over the
    ``zeebe_audit_burn_rate`` series the auditor publishes into the store,
    with page-vs-ticket severities. The series value is min(fast, slow) —
    a threshold rule over it IS the both-windows-exceed condition — and
    the evaluator's normal for-duration machine debounces it."""
    from zeebe_tpu.observability.alerts import AlertRule

    return [
        AlertRule(
            name="slo_burn_page",
            series="zeebe_audit_burn_rate",
            threshold=cfg.page_burn * 0.999, op=">", for_ms=2_000,
            labels_contains='window="both"', severity="page"),
        AlertRule(
            name="slo_burn_ticket",
            series="zeebe_audit_burn_rate",
            threshold=cfg.ticket_burn * 0.999, op=">", for_ms=5_000,
            labels_contains='window="both"', severity="ticket"),
    ]


# -- the per-broker auditor ---------------------------------------------------


@dataclass
class _PartitionCursor:
    """Per-partition CRC walk state: next position to read, the crc
    accumulated inside the current (aligned) window, and whether the walk
    entered the window at its exact start (only then is the finalized
    checkpoint comparable across replicas)."""

    next_pos: int = 0          # 0 = not aligned yet
    window: int = -1
    crc: int = 0
    aligned: bool = False


class BrokerAuditor:
    """Per-broker online invariant monitors + burn rates + leak trends,
    ticked off the sampler cadence inside ``Broker.pump_control``.

    Violations are latched into a bounded ``alerts`` ring (the ``audit``
    status block ships it), emitted as ``audit_alert`` flight events on
    the node ring, and counted in ``zeebe_audit_violations_total``."""

    MAX_ALERTS = 64
    #: resources whose leak verdict gates the fleet: true process resources
    #: only. Ring occupancy is bounded by construction (it saturates, it
    #: cannot leak) and state/tenant table sizes are workload-proportional;
    #: those trend as ``capacity_trend`` alerts instead.
    GATING_RESOURCES = ("rss_bytes", "fd_count", "thread_count")

    def __init__(self, broker, cfg: AuditorCfg | None = None) -> None:
        self.broker = broker
        self.cfg = cfg or AuditorCfg.from_env()
        self.node_id = broker.cfg.node_id
        # invariant state
        self._last_positions: dict[int, int] = {}
        self._last_processed: dict[int, int] = {}
        self._exporter_cursors: dict[tuple[int, str], int] = {}
        self._exporter_directors: dict[int, object] = {}
        self._crc_cursors: dict[int, _PartitionCursor] = {}
        #: finalized (window, crc) checkpoints per partition, newest last
        self.crc_checkpoints: dict[int, deque] = {}
        self._quarantined_since_ms: float | None = None
        self._quarantine_flagged = False
        # SLO burn tracking (admission ack-p99 + goodput, one tracker)
        self.burn = BurnRateTracker(
            self.cfg.fast_window_ms, self.cfg.slow_window_ms,
            slo_target=self.cfg.slo_target, page_burn=self.cfg.page_burn,
            ticket_burn=self.cfg.ticket_burn)
        self.burn_state: dict = {"fast": 0.0, "slow": 0.0, "state": "ok"}
        # leak trend detectors, one per tracked resource
        self.trends: dict[str, TrendDetector] = {}
        self._leak_flagged: set[str] = set()
        self._first_tick_ms: float | None = None
        self.alerts: deque[dict] = deque(maxlen=self.MAX_ALERTS)
        self.violations_total = 0
        # burn-rate rules ride the broker's normal alert evaluator
        evaluator = getattr(broker, "alerts", None)
        if evaluator is not None:
            evaluator.add_rules(burn_rate_rules(self.node_id, self.cfg))

    # -- violation plumbing ---------------------------------------------------

    def _violation(self, monitor: str, message: str, **detail) -> None:
        self.violations_total += 1
        _M_VIOLATIONS.labels(monitor).inc()
        event = {"atMs": self.broker.clock_millis(), "monitor": monitor,
                 "message": message, **detail}
        self.alerts.append(event)
        flight = getattr(self.broker, "flight_recorder", None)
        if flight is not None:
            flight.record(0, "audit_alert", monitor=monitor,
                          message=message, **detail)
        _M_ALERTS.labels(self.node_id).set(float(len(self.alerts)))

    # -- invariant monitors ---------------------------------------------------

    def _check_position_monotonicity(self) -> None:
        _M_CHECKS.labels("acked_position").inc()
        for pid, partition in list(self.broker.partitions.items()):
            pos = partition.stream.last_position
            prev = self._last_positions.get(pid)
            if prev is not None and pos < prev:
                self._violation(
                    "acked_position",
                    f"partition {pid} log position moved backward "
                    f"{prev} -> {pos}", partition=pid, prev=prev, now=pos)
            self._last_positions[pid] = pos
            processor = partition.processor
            if processor is not None:
                processed = getattr(processor, "last_processed_position", 0)
                prev_p = self._last_processed.get(pid)
                if prev_p is not None and processed < prev_p:
                    self._violation(
                        "acked_position",
                        f"partition {pid} processed position moved backward "
                        f"{prev_p} -> {processed}", partition=pid,
                        prev=prev_p, now=processed)
                self._last_processed[pid] = processed

    def _check_exporter_sequences(self) -> None:
        _M_CHECKS.labels("exporter_sequence").inc()
        for pid, partition in list(self.broker.partitions.items()):
            director = getattr(partition, "exporter_director", None)
            if director is None:
                continue
            # a new director instance (leadership regained) boots fresh
            # containers that report 0 until they restore their persisted
            # cursor — a real regression is within ONE director's life, so
            # the baseline resets with the instance
            if self._exporter_directors.get(pid) is not director:
                self._exporter_directors[pid] = director
                for key in [k for k in self._exporter_cursors
                            if k[0] == pid]:
                    del self._exporter_cursors[key]
            log_end = partition.stream.last_position
            for container in getattr(director, "containers", ()):
                key = (pid, container.exporter_id)
                pos = container.position
                prev = self._exporter_cursors.get(key)
                if prev is not None and pos < prev:
                    self._violation(
                        "exporter_sequence",
                        f"exporter {container.exporter_id} cursor moved "
                        f"backward on partition {pid}: {prev} -> {pos}",
                        partition=pid, exporter=container.exporter_id,
                        prev=prev, now=pos)
                self._exporter_cursors[key] = pos
                if pos > log_end:
                    self._violation(
                        "exporter_sequence",
                        f"exporter {container.exporter_id} acked position "
                        f"{pos} past log end {log_end} on partition {pid}",
                        partition=pid, exporter=container.exporter_id,
                        position=pos, logEnd=log_end)
                if container.last_delivered < pos:
                    self._violation(
                        "exporter_sequence",
                        f"exporter {container.exporter_id} delivery "
                        f"watermark {container.last_delivered} trails its "
                        f"persisted cursor {pos} on partition {pid} (a gap "
                        f"was acked without delivery)",
                        partition=pid, exporter=container.exporter_id)

    def _check_quarantine_latch(self, now_ms: float) -> None:
        _M_CHECKS.labels("quarantine_latch").inc()
        try:
            from zeebe_tpu.engine.device_health import (
                QUARANTINED,
                shared_device_health,
            )
        except Exception:  # noqa: BLE001 — audit must not need the engine
            return
        health = shared_device_health()
        if health.state != QUARANTINED:
            self._quarantined_since_ms = None
            self._quarantine_flagged = False
            return
        if self._quarantined_since_ms is None:
            # latch observed now; the transition record carries the true
            # start when available
            since = now_ms
            for tr in reversed(getattr(health, "transitions", [])):
                if tr.get("to") == QUARANTINED:
                    since = float(tr.get("atMs", now_ms))
                    break
            self._quarantined_since_ms = since
        held = now_ms - self._quarantined_since_ms
        if held > self.cfg.quarantine_max_ms and not self._quarantine_flagged:
            self._quarantine_flagged = True  # once per latch episode
            self._violation(
                "quarantine_latch",
                f"device QUARANTINED for {held / 1000.0:.0f}s, beyond the "
                f"{self.cfg.quarantine_max_ms / 1000.0:.0f}s bound "
                f"(canary loop is not re-proving or condemning the device)",
                heldMs=int(held))

    def _check_replica_crc(self) -> None:
        """Advance the windowed CRC walk over each partition's replicated
        log. The log below the last materialized position is committed by
        construction (the Raft path appends post-commit), so any two
        replicas holding the same aligned window must produce the same
        CRC — disagreement is detected harness-side where the status
        pushes meet (:class:`ClusterAuditor`)."""
        _M_CHECKS.labels("replica_crc").inc()
        window = self.cfg.crc_window
        budget = self.cfg.crc_batch
        for pid, partition in list(self.broker.partitions.items()):
            cursor = self._crc_cursors.get(pid)
            if cursor is None:
                cursor = self._crc_cursors[pid] = _PartitionCursor()
            if cursor.next_pos == 0:
                first = partition.stream.read_at_or_after(1)
                if first is None:
                    continue
                # start at the first window boundary at-or-after the first
                # readable record: a mid-window boot skips the incomplete
                # window instead of shipping an incomparable checkpoint
                start_window = (first.position + window - 1) // window
                if first.position == start_window * window - window + 1:
                    start_window -= 1
                cursor.window = start_window
                cursor.next_pos = start_window * window + 1
                cursor.aligned = True
            end = partition.stream.last_position
            if cursor.next_pos > end:
                continue
            reader = partition.stream.new_reader(cursor.next_pos)
            ring = self.crc_checkpoints.setdefault(pid, deque(maxlen=16))
            for logged in reader:
                if budget <= 0:
                    break
                budget -= 1
                w = (logged.position - 1) // window
                if w != cursor.window:
                    # positions are monotone, so leaving a window means no
                    # more records will ever land in it: finalize (the walk
                    # entered it from its aligned boundary by construction)
                    ring.append((cursor.window, cursor.crc))
                    cursor.window = w
                    cursor.crc = 0
                cursor.crc = zlib.crc32(
                    logged.record.to_bytes(), cursor.crc) & 0xFFFFFFFF
                cursor.next_pos = logged.position + 1

    # -- SLO + leak sampling --------------------------------------------------

    def _observe_slo(self, now_ms: float) -> None:
        """Classify this tick against the SLO from the broker's own
        series: ack-p99 from the admission latency histogram, goodput from
        the admitted-vs-shed counters (both sampled into the store by the
        tick that precedes this call)."""
        store = getattr(self.broker, "timeseries", None)
        if store is None:
            return
        node_label = f'node="{self.node_id}"'
        p99 = [e["value"]
               for e in store.latest("zeebe_admission_ack_latency_ms:p99")
               if node_label in e["labels"]
               and now_ms - e["t"] <= 15_000]
        # counters land in the store as per-second RATES (timeseries.py),
        # so the latest samples already are the goodput numerator/denominator
        admit_rate = sum(
            e["value"] for e in store.latest("zeebe_admission_admitted_total")
            if node_label in e["labels"])
        shed_rate = sum(
            e["value"] for e in store.latest("zeebe_admission_shed_total")
            if node_label in e["labels"])
        bad = 0.0
        good = 1.0
        if p99 and max(p99) > self.cfg.slo_p99_ms:
            bad = 1.0
            good = 0.0
        total = admit_rate + shed_rate
        if total > 0 and (admit_rate / total) < self.cfg.goodput_floor:
            bad = 1.0
            good = 0.0
        self.burn.observe(now_ms, good, bad)
        self.burn_state = self.burn.evaluate(now_ms)
        for window, value in (("fast", self.burn_state["fast"]),
                              ("slow", self.burn_state["slow"]),
                              ("both", min(self.burn_state["fast"],
                                           self.burn_state["slow"]))):
            _M_BURN.labels(self.node_id, "availability", window).set(value)

    _LEAK_STATE_VALUE = {"quiet": 0.0, "insufficient": 0.0, "warming": 1.0,
                         "leak": 2.0}

    def _trend(self, name: str) -> TrendDetector:
        det = self.trends.get(name)
        if det is None:
            det = self.trends[name] = TrendDetector(
                self.cfg.leak_window_ms,
                min_samples=self.cfg.leak_min_samples,
                tstat=self.cfg.leak_tstat,
                min_growth=self.cfg.leak_min_growth)
        return det

    def _sample_resources(self, now_ms: float) -> None:
        from zeebe_tpu.utils.metrics import (
            read_fd_count,
            read_thread_count,
            _read_rss_bytes,
        )

        samples = {
            "rss_bytes": _read_rss_bytes(),
            "fd_count": read_fd_count(),
            "thread_count": read_thread_count(),
        }
        flight = getattr(self.broker, "flight_recorder", None)
        if flight is not None:
            occupancy = flight.occupancy()
            samples["flight_ring"] = occupancy
            _M_RING.labels(self.node_id).set(occupancy)
        # tracked-table growth: tenants the admission plane has seen, and
        # state-table keys per broker (a forgotten cleanup shows up here
        # long before RSS does)
        store = getattr(self.broker, "timeseries", None)
        if store is not None:
            node_label = f'node="{self.node_id}"'
            keys = sum(e["value"] for e in store.latest("zeebe_state_keys")
                       if node_label in e["labels"])
            if keys:
                samples["state_keys"] = keys
            # tracked-tenant table growth: distinct (node, tenant) children
            # of the admission counter — an unbounded tenant table shows up
            # as a climbing child count long before RSS moves
            tenants = len(store.latest("zeebe_admission_admitted_total"))
            if tenants:
                samples["tracked_tenants"] = tenants
        # boot-era hold-off: compilation, cache warmup, and rings filling
        # are genuine monotone climbs; observing them would seed every
        # detector with a false ramp. Gauges above stay live regardless.
        if now_ms - self._first_tick_ms < self.cfg.leak_warmup_ms:
            return
        for name, value in samples.items():
            det = self._trend(name)
            det.observe(now_ms, value)
            verdict = det.verdict()
            _M_LEAK.labels(self.node_id, name).set(
                self._LEAK_STATE_VALUE.get(verdict["state"], 0.0))
            if verdict["state"] == "leak":
                # process resources gate the fleet (monitor resource_leak);
                # workload-proportional series (ring occupancy, state/tenant
                # table sizes) are capacity trends: same detector, same
                # alert plumbing, but they never flip the leak VERDICT —
                # a busy fleet legitimately grows them
                monitor = ("resource_leak" if name in self.GATING_RESOURCES
                           else "capacity_trend")
                if name not in self._leak_flagged:  # once per episode
                    self._leak_flagged.add(name)
                    self._violation(
                        monitor,
                        f"{name} trending up: "
                        f"{verdict['slopePerSec']:+.3f}/s over "
                        f"{verdict['spanMs'] / 1000.0:.0f}s "
                        f"(t={verdict['tstat']})", resource=name, **{
                            k: v for k, v in verdict.items()
                            if k != "state"})
            else:
                self._leak_flagged.discard(name)

    # -- the tick + surfaces --------------------------------------------------

    def tick(self, now_ms: float) -> None:
        if not self.cfg.enabled:
            return
        if self._first_tick_ms is None:
            self._first_tick_ms = now_ms
        self._check_position_monotonicity()
        self._check_exporter_sequences()
        self._check_quarantine_latch(now_ms)
        self._check_replica_crc()
        self._observe_slo(now_ms)
        self._sample_resources(now_ms)

    def leak_verdicts(self) -> dict:
        return {name: det.last for name, det in sorted(self.trends.items())
                if det.last is not None}

    def snapshot(self) -> dict:
        """The ``audit`` block on a broker's /cluster/status row (and
        therefore the worker status push): latched alerts, burn-rate
        state, leak verdicts, and the replica-CRC checkpoints the
        harness-side auditor joins across workers."""
        leaks = self.leak_verdicts()
        return {
            "enabled": self.cfg.enabled,
            "violations": self.violations_total,
            "alerts": list(self.alerts)[-8:],
            "burn": dict(self.burn_state),
            "leaks": {
                name: {"state": v["state"],
                       "slopePerSec": v.get("slopePerSec", 0.0)}
                for name, v in leaks.items()},
            "leakVerdict": ("leak" if any(
                v["state"] == "leak" for name, v in leaks.items()
                if name in self.GATING_RESOURCES) else "clean"),
            "crc": {str(pid): [[w, c] for w, c in ring]
                    for pid, ring in sorted(self.crc_checkpoints.items())
                    if ring},
        }


# -- the harness/gateway-side auditor -----------------------------------------


class ClusterAuditor:
    """Cross-worker auditing over the worker status pushes the gateway
    already aggregates: replica-CRC spot agreement per (partition,
    window), acked-position monotonicity ACROSS pushes (per worker life),
    and a merged view of every worker's audit block.

    Fed by the fleet-day harness (``runtime._worker_status``) or any
    caller holding /cluster/status rows; pure and clock-free, so tests
    drive it with synthetic rows."""

    def __init__(self) -> None:
        #: (partition, window) -> {crc -> set(worker)}
        self._crc_seen: dict[tuple[int, int], dict[int, set]] = {}
        #: (worker, pid, partition) -> last pushed log position
        self._push_positions: dict[tuple, int] = {}
        self.violations: list[dict] = []
        self._flagged: set = set()
        self.worker_audits: dict[str, dict] = {}
        self.rows_ingested = 0

    def ingest(self, rows: dict) -> list[dict]:
        """Consume ``{worker_id: status_row}``; returns NEW violations."""
        fresh: list[dict] = []
        for worker, row in sorted(rows.items()):
            if not isinstance(row, dict):
                continue
            self.rows_ingested += 1
            audit = row.get("audit")
            if isinstance(audit, dict):
                self.worker_audits[worker] = audit
                for pid_s, checkpoints in audit.get("crc", {}).items():
                    pid = int(pid_s)
                    for window, crc in checkpoints:
                        key = (pid, int(window))
                        seen = self._crc_seen.setdefault(key, {})
                        seen.setdefault(int(crc), set()).add(worker)
                        if len(seen) > 1 and key not in self._flagged:
                            self._flagged.add(key)
                            fresh.append({
                                "monitor": "replica_crc",
                                "message": (
                                    f"replica CRC disagreement on partition "
                                    f"{pid} window {window}: " + ", ".join(
                                        f"{sorted(ws)}={c:#010x}"
                                        for c, ws in sorted(seen.items()))),
                                "partition": pid, "window": int(window)})
            worker_pid = row.get("workerPid", 0)
            for pid_s, pinfo in row.get("partitions", {}).items():
                pos = pinfo.get("lastPosition")
                if pos is None:
                    continue
                key = (worker, worker_pid, int(pid_s))
                prev = self._push_positions.get(key)
                if prev is not None and pos < prev:
                    flag = ("push_monotonicity", key, prev)
                    if flag not in self._flagged:
                        self._flagged.add(flag)
                        fresh.append({
                            "monitor": "acked_position",
                            "message": (
                                f"{worker} (pid {worker_pid}) pushed "
                                f"partition {pid_s} position {pos} after "
                                f"{prev}"),
                            "worker": worker, "partition": int(pid_s),
                            "prev": prev, "now": pos})
                self._push_positions[key] = pos
        self.violations.extend(fresh)
        return fresh

    def flagged_monitors(self) -> set:
        """Monitor classes with at least one online flag, merged across
        this auditor and every worker's own audit block — the recall
        cross-check joins the offline checker's findings against this."""
        out = {v["monitor"] for v in self.violations}
        for audit in self.worker_audits.values():
            for alert in audit.get("alerts", []):
                out.add(alert.get("monitor", ""))
            if audit.get("leakVerdict") == "leak":
                out.add("resource_leak")
        return out - {""}

    def snapshot(self) -> dict:
        return {
            "rowsIngested": self.rows_ingested,
            "violations": list(self.violations),
            "crcWindowsCompared": sum(
                1 for seen in self._crc_seen.values()
                if sum(len(ws) for ws in seen.values()) > 1),
            "workers": {w: {"burn": a.get("burn", {}),
                            "leakVerdict": a.get("leakVerdict", "unknown"),
                            "violations": a.get("violations", 0)}
                        for w, a in sorted(self.worker_audits.items())},
        }
