"""In-memory bounded-retention time-series store + registry sampler.

What the reference outsources to Prometheus, a self-contained TPU-native
broker must carry itself: *history*. A point-in-time ``/metrics`` scrape
cannot answer "what was the exporter lag over the last minute", cannot feed a
for-duration alert rule, and retains nothing for a postmortem. Gorilla
(Pelkonen et al., VLDB'15) and Monarch (Adams et al., VLDB'20) both argue for
an in-memory, bounded-retention time-series layer close to the target as the
substrate for alerting and debugging — this module is that layer:

- :class:`TimeSeriesStore` — per-series append-only blocks of delta-encoded
  timestamps (``array('i')`` millisecond gaps) + packed float values
  (``array('d')``), Gorilla's timestamp-compression idea without the
  bit-level XOR stage (block overhead already amortizes to ~12 bytes/sample;
  the win that matters here is bounded memory, not wire size). Old blocks
  fall off by retention; the open block seals at ``block_samples``.
- :class:`MetricsSampler` — snapshots the :class:`MetricsRegistry` every
  ``interval_ms``: **counters become rates** (d(value)/dt between consecutive
  samples), **histograms become p50/p99 estimates** plus an observation rate
  (``<name>:p50``/``:p99``/``:rate`` series), gauges record raw. Tick-driven
  (``maybe_sample`` from the broker's control pump — deterministic under the
  test clock) with an optional background thread for hosts without a pump
  (``bench.py --sample-metrics``).

Cost contract (ISSUE 4): nothing measurable when disabled — the sampler
simply isn't constructed, leaving one ``is not None`` check per control pump
— and <1% on ``bench.py --quick`` when enabled (a few hundred child series
snapshot in ~1ms, every 250ms, off the hot path).
"""

from __future__ import annotations

import threading
from array import array
from typing import Callable, Iterable, Iterator

DEFAULT_RETENTION_MS = 5 * 60 * 1000
DEFAULT_BLOCK_SAMPLES = 120
DEFAULT_INTERVAL_MS = 250

# one delta is an i32 of milliseconds: a gap beyond ~24 days would overflow;
# seal the block instead and start a fresh epoch
_MAX_DELTA_MS = 2**31 - 1


class _Block:
    """One sealed-or-open run of samples: epoch timestamp + ms deltas."""

    __slots__ = ("t0", "deltas", "values", "last_t")

    def __init__(self, t0: int, value: float) -> None:
        self.t0 = t0
        self.last_t = t0
        self.deltas = array("i")       # gap to the PREVIOUS sample, ms
        self.values = array("d", (value,))

    def append(self, t_ms: int, value: float) -> None:
        self.deltas.append(t_ms - self.last_t)
        self.values.append(value)
        self.last_t = t_ms

    def __len__(self) -> int:
        return len(self.values)

    def samples(self) -> Iterator[tuple[int, float]]:
        t = self.t0
        yield t, self.values[0]
        for delta, value in zip(self.deltas, self.values[1:]):
            t += delta
            yield t, value


class Series:
    __slots__ = ("name", "labels", "kind", "blocks", "_block_samples")

    def __init__(self, name: str, labels: str, kind: str,
                 block_samples: int = DEFAULT_BLOCK_SAMPLES) -> None:
        self.name = name
        self.labels = labels  # rendered label string, e.g. '{node="broker-0"}'
        self.kind = kind      # "gauge" | "rate" | "quantile"
        self.blocks: list[_Block] = []
        self._block_samples = block_samples

    def append(self, t_ms: int, value: float) -> None:
        if self.blocks:
            tail = self.blocks[-1]
            if (len(tail) < self._block_samples
                    and 0 <= t_ms - tail.last_t <= _MAX_DELTA_MS):
                tail.append(t_ms, value)
                return
        self.blocks.append(_Block(t_ms, value))

    def evict_before(self, cutoff_ms: int) -> None:
        # whole sealed blocks only: per-sample eviction would force re-basing
        # the delta chain; a block is at most block_samples stale
        while len(self.blocks) > 1 and self.blocks[0].last_t < cutoff_ms:
            self.blocks.pop(0)

    def samples(self, since_ms: int = 0) -> list[tuple[int, float]]:
        out = []
        for block in self.blocks:
            if block.last_t < since_ms:
                continue
            out.extend((t, v) for t, v in block.samples() if t >= since_ms)
        return out

    def latest(self) -> tuple[int, float] | None:
        if not self.blocks:
            return None
        tail = self.blocks[-1]
        return tail.last_t, tail.values[-1]

    def __len__(self) -> int:
        return sum(len(b) for b in self.blocks)


class TimeSeriesStore:
    """Bounded in-memory store keyed by ``(name, label_str)``. Thread-safe:
    the sampler appends from the control pump while management HTTP threads
    query."""

    def __init__(self, retention_ms: int = DEFAULT_RETENTION_MS,
                 block_samples: int = DEFAULT_BLOCK_SAMPLES,
                 max_series: int = 8192) -> None:
        self.retention_ms = retention_ms
        self.block_samples = block_samples
        self.max_series = max_series
        self._series: dict[tuple[str, str], Series] = {}
        self._lock = threading.Lock()
        self.dropped_series = 0  # over max_series: new series are refused

    def append(self, name: str, labels: str, kind: str, t_ms: int,
               value: float) -> None:
        key = (name, labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return
                series = Series(name, labels, kind, self.block_samples)
                self._series[key] = series
            series.append(t_ms, value)

    def evict(self, now_ms: int) -> None:
        cutoff = now_ms - self.retention_ms
        with self._lock:
            for series in self._series.values():
                series.evict_before(cutoff)

    # -- queries ---------------------------------------------------------------

    def _matching(self, name: str) -> list[Series]:
        """Exact name match, plus derived children (``name:p50`` …) so
        querying a histogram's base name returns its whole family."""
        prefix = name + ":"
        return [s for (n, _), s in self._series.items()
                if n == name or n.startswith(prefix)]

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted({n for n, _ in self._series})

    def query(self, name: str, since_ms: int = 0,
              step_ms: int = 0) -> list[dict]:
        """Samples per matching series; ``step_ms`` downsamples by keeping
        the last sample of each step bucket (rate/gauge semantics: the value
        that was current at the bucket's end)."""
        with self._lock:
            matching = self._matching(name)
            out = []
            for series in matching:
                samples = series.samples(since_ms)
                if step_ms > 0 and samples:
                    by_bucket: dict[int, tuple[int, float]] = {}
                    for t, v in samples:
                        by_bucket[t // step_ms] = (t, v)
                    samples = [by_bucket[b] for b in sorted(by_bucket)]
                out.append({
                    "name": series.name,
                    "labels": series.labels,
                    "kind": series.kind,
                    "samples": [[t, v] for t, v in samples],
                })
        return out

    def latest(self, name: str) -> list[dict]:
        with self._lock:
            out = []
            for series in self._matching(name):
                latest = series.latest()
                if latest is not None:
                    out.append({"name": series.name, "labels": series.labels,
                                "kind": series.kind,
                                "t": latest[0], "value": latest[1]})
        return out

    def rate(self, name: str, window_ms: int, now_ms: int,
             labels_contains: str = "") -> float:
        """Per-second increase of a monotonic gauge over the trailing window,
        summed across matching children — the headline-rate helper for
        ``/cluster/status`` (counters already store rates; this serves the
        position-style gauges like ``stream_processor_last_processed_position``)."""
        total = 0.0
        with self._lock:
            matching = [s for s in self._matching(name)
                        if labels_contains in s.labels]
            for series in matching:
                samples = series.samples(now_ms - window_ms)
                if len(samples) >= 2:
                    (t0, v0), (t1, v1) = samples[0], samples[-1]
                    if t1 > t0 and v1 >= v0:
                        total += (v1 - v0) / ((t1 - t0) / 1000.0)
        return total

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "samples": sum(len(s) for s in self._series.values()),
                "droppedSeries": self.dropped_series,
                "retentionMs": self.retention_ms,
            }


class MetricsSampler:
    """Snapshots a :class:`MetricsRegistry` into a :class:`TimeSeriesStore`.

    Counters are stored as per-second **rates** between consecutive samples
    (the raw monotonic total is recoverable from ``/metrics``; the question
    history answers is "how fast", not "how many"). Histograms are distilled
    to ``:p50``/``:p99`` bucket-interpolated estimates over the deltas since
    the previous sample (so the percentiles describe *recent* observations,
    not the lifetime distribution) plus a ``:rate`` of observations/s.
    Gauges record raw values.
    """

    def __init__(self, registry, store: TimeSeriesStore,
                 interval_ms: int = DEFAULT_INTERVAL_MS,
                 clock_millis: Callable[[], int] | None = None) -> None:
        import time

        self.registry = registry
        self.store = store
        self.interval_ms = interval_ms
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        self._last_sample_ms = 0
        # per-series previous snapshot for rate/delta derivation
        self._prev_counter: dict[tuple[str, str], tuple[int, float]] = {}
        self._prev_hist: dict[tuple[str, str], tuple[int, int, float, list]] = {}
        self.samples_taken = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- tick-driven (broker control pump) -------------------------------------

    def maybe_sample(self, now_ms: int | None = None) -> bool:
        now = self.clock_millis() if now_ms is None else now_ms
        if now - self._last_sample_ms < self.interval_ms:
            return False
        self.sample_once(now)
        return True

    def sample_once(self, now_ms: int | None = None) -> None:
        now = self.clock_millis() if now_ms is None else now_ms
        # a series first observed BETWEEN two ticks gets a synthesized zero
        # baseline at the previous tick (counters start at 0) — without it
        # every new series would lose its first interval of rate history
        prev_tick = self._last_sample_ms if self.samples_taken else None
        self._last_sample_ms = now
        store = self.store
        for name, kind, labels, value in self.registry.snapshot():
            key = (name, labels)
            if kind == "counter":
                prev = self._prev_counter.get(key)
                if prev is None and prev_tick is not None and prev_tick < now:
                    prev = (prev_tick, 0.0)
                self._prev_counter[key] = (now, value)
                if prev is not None and now > prev[0]:
                    dt = (now - prev[0]) / 1000.0
                    # a counter reset (restart/clear) would read as a huge
                    # negative rate; clamp to "unknown this interval"
                    if value >= prev[1]:
                        store.append(name, labels, "rate", now,
                                     (value - prev[1]) / dt)
            elif kind == "gauge":
                store.append(name, labels, "gauge", now, value)
            else:  # histogram
                count, total, bucket_counts, buckets = value
                prev = self._prev_hist.get(key)
                if prev is None and prev_tick is not None and prev_tick < now:
                    prev = (prev_tick, 0, 0.0, [0] * len(bucket_counts))
                self._prev_hist[key] = (now, count, total, bucket_counts)
                if prev is None or now <= prev[0]:
                    continue
                prev_t, prev_count, _prev_sum, prev_buckets = prev
                delta_count = count - prev_count
                dt = (now - prev_t) / 1000.0
                store.append(name, labels, "rate", now,
                             max(delta_count, 0) / dt)
                if delta_count <= 0 or len(prev_buckets) != len(bucket_counts):
                    continue
                from zeebe_tpu.utils.metrics import estimate_quantile

                delta_buckets = [c - p for c, p
                                 in zip(bucket_counts, prev_buckets)]
                store.append(name + ":p50", labels, "quantile", now,
                             estimate_quantile(buckets, delta_buckets, 0.50))
                store.append(name + ":p99", labels, "quantile", now,
                             estimate_quantile(buckets, delta_buckets, 0.99))
        store.evict(now)
        self.samples_taken += 1

    # -- thread-driven (no pump available: bench, ad-hoc tooling) --------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.interval_ms / 1000.0):
                try:
                    self.sample_once()
                except Exception:  # noqa: BLE001 — a torn registry read must
                    pass           # not kill the sampling loop
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="metrics-sampler")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None


def summarize_store(store: TimeSeriesStore,
                    headline: Iterable[str] = ()) -> dict:
    """Compact store summary for the BENCH extra: volume stats plus, per
    requested headline series, the latest retained value and the retained
    peak (the last sample of a bench run lands after the workload went idle,
    so "last" alone would read 0 for every rate series)."""
    out = store.stats()
    series = {}
    for name in headline:
        for entry in store.query(name):
            if entry["name"] != name:
                continue  # query() prefix-matches histogram children
            samples = entry["samples"]
            if not samples:
                continue
            series[f"{name}{entry['labels']}"] = {
                "last": round(samples[-1][1], 4),
                "max": round(max(v for _, v in samples), 4),
            }
    if series:
        out["headline"] = series
    return out
