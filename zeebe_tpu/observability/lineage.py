"""Record-lineage walker: the causal tree of a process instance, from the
journal alone.

Every follow-up record in the stream back-links to the command that produced
it (``source_record_position``, carried as the sequenced batch's source
position — the same backlink replay uses for its lastProcessedPosition
tracking). That makes the committed log a complete causal-lineage substrate:
no tracing needs to have been enabled, no state db needs to be open — a
journal directory is enough to answer "where did this process instance's
records come from, in what order, triggered by which gateway request?".

The walk reconstructs a *forest*: one tree per root command (a record whose
batch has no source — a client/gateway command, a scheduled command, or an
inter-partition command). A one_task instance typically yields two trees:
the CREATE command's (instance activation through job creation) and the job
COMPLETE command's (task completion through instance completion). Roots
carrying a gateway request id are annotated with it, closing the
gateway-request → command end of the chain; pass ``exported_position`` (an
exporter's acked watermark) to close the → exporter-export end.

Surfaced via ``python -m zeebe_tpu.cli trace <instance key>`` (offline, over
a journal directory) and importable for tests/tools.
"""

from __future__ import annotations

from typing import Any


def collect_lineage(stream, instance_key: int,
                    exported_position: int | None = None,
                    from_position: int = 1) -> dict:
    """Reconstruct the causal forest of ``instance_key`` from ``stream``
    (a :class:`zeebe_tpu.logstreams.LogStream`).

    A record belongs to the instance when its key IS the instance key or its
    value's ``processInstanceKey`` names it; each tree additionally keeps the
    ancestor chain up to its root command (a JOB_BATCH ACTIVATE serving many
    instances appears as a partial root with only this instance's branch).
    """
    # pass 1: flat metadata for every record, plus the child index
    info: dict[int, dict[str, Any]] = {}
    children: dict[int, list[int]] = {}
    members: list[int] = []
    for view in stream.scan(from_position):
        rec = view.record  # lineage is a debug tool: full decode is fine
        value = rec.value if isinstance(rec.value, dict) else {}
        node = {
            "position": view.position,
            "sourcePosition": view.source_position,
            "recordType": rec.record_type.name,
            "valueType": rec.value_type.name,
            "intent": rec.intent.name,
            "key": rec.key,
            "timestamp": rec.timestamp,
        }
        if rec.record_type.name == "COMMAND_REJECTION":
            node["rejectionType"] = rec.rejection_type.name
            node["rejectionReason"] = rec.rejection_reason
        if rec.request_id >= 0:
            node["gatewayRequestId"] = rec.request_id
        element_id = value.get("elementId") or value.get("bpmnProcessId")
        if element_id:
            node["elementId"] = element_id
        info[view.position] = node
        if view.source_position >= 1:
            children.setdefault(view.source_position, []).append(view.position)
        if rec.key == instance_key \
                or value.get("processInstanceKey") == instance_key:
            members.append(view.position)

    # pass 2: causal closure — members plus every ancestor up to the roots
    included: set[int] = set(members)
    roots: list[int] = []
    for position in members:
        cursor = position
        while True:
            source = info[cursor]["sourcePosition"]
            if source < 1 or source not in info:
                if cursor not in roots:
                    roots.append(cursor)
                break
            included.add(source)
            cursor = source
    roots.sort()

    def build(position: int) -> dict:
        node = dict(info[position])
        node.pop("sourcePosition", None)
        if exported_position is not None:
            node["exported"] = position <= exported_position
        kids_all = children.get(position, ())
        kids = [build(p) for p in kids_all if p in included]
        if len(kids) < len(kids_all):
            # some follow-ups of this node belong to OTHER instances (e.g. a
            # JOB_BATCH ACTIVATE serving many instances) — flag the pruning
            # so consumers know this branch was filtered, not complete
            node["pruned"] = True
        if kids:
            node["children"] = kids
        return node

    trees = []
    for root in roots:
        tree = build(root)
        tree["sourcePosition"] = info[root]["sourcePosition"]
        trees.append(tree)

    return {
        "processInstanceKey": instance_key,
        "partitionId": stream.partition_id,
        "recordsScanned": len(info),
        "recordsInLineage": len(included),
        "roots": trees,
    }


def format_lineage(lineage: dict) -> str:
    """Human-readable ASCII rendering of :func:`collect_lineage`'s forest."""
    lines = [
        f"process instance {lineage['processInstanceKey']} "
        f"(partition {lineage['partitionId']}, "
        f"{lineage['recordsInLineage']}/{lineage['recordsScanned']} records)"
    ]

    def walk(node: dict, depth: int) -> None:
        request = node.get("gatewayRequestId")
        label = (
            f"#{node['position']} {node['recordType']} "
            f"{node['valueType']}.{node['intent']}"
        )
        if node.get("elementId"):
            label += f" [{node['elementId']}]"
        if request is not None:
            label += f" (gateway request {request})"
        if node.get("pruned"):
            label += " (pruned: other instances' follow-ups omitted)"
        if "exported" in node:
            label += " exported" if node["exported"] else " NOT-exported"
        lines.append("  " * depth + ("└─ " if depth else "") + label)
        for child in node.get("children", ()):
            walk(child, depth + 1)

    for tree in lineage["roots"]:
        walk(tree, 0)
    return "\n".join(lines)
