"""Dynamic cluster topology: gossiped versioned state + change coordination.

Reference: topology/src/main/java/io/camunda/zeebe/topology/
ClusterTopologyManager.java, state/ClusterTopology (versioned MemberState/
PartitionState), gossip/ClusterTopologyGossiper.java:34, changes/ (MemberJoin/
MemberLeave/PartitionJoin/PartitionLeave appliers) and
TopologyChangeCoordinatorImpl.

Redesigned for the tick-driven runtime: the topology is a plain versioned
document gossiped through the SWIM membership's property map (higher version
wins — the coordinator serializes changes, so versions are totally ordered in
practice); a change is an ordered list of operations, each applied BY ITS
TARGET MEMBER when it observes that the operation is next. Completing an
operation bumps the version and gossips the advanced plan, which is what
hands the baton to the next operation's target. Raft-level membership moves
use single-step reconfiguration (cluster/raft.py reconfigure): PARTITION_JOIN
starts a replica on the target, asks the leader to add it, and completes once
the new replica has caught up to the leader's commit; PARTITION_LEAVE removes
the member from the raft group, then stops the local replica.
"""

from __future__ import annotations

import copy
from typing import Any, Callable

# operation kinds (reference: topology/changes/ appliers)
MEMBER_JOIN = "MEMBER_JOIN"
MEMBER_LEAVE = "MEMBER_LEAVE"
PARTITION_JOIN = "PARTITION_JOIN"
PARTITION_LEAVE = "PARTITION_LEAVE"

# member / partition-replica states (reference: state/MemberState, PartitionState)
ACTIVE = "active"
JOINING = "joining"
LEAVING = "leaving"
LEFT = "left"


class ClusterTopology:
    """The gossiped document. Plain-dict representation so it serializes
    through the membership gossip unchanged:

    {"version": N,
     "members": {member_id: {"state": ..., "partitions": {pid: {"state": ...,
                                                          "priority": P}}}},
     "change": {"id": N, "index": i, "operations": [op, ...]} | None}

    where op = {"op": KIND, "member": id, "partition": pid?, "priority": P?,
                "members": [...]?}.
    """

    def __init__(self, doc: dict | None = None) -> None:
        self.doc = doc or {"version": 0, "members": {}, "change": None}

    # -- views ---------------------------------------------------------------

    @property
    def version(self) -> int:
        return self.doc["version"]

    @property
    def members(self) -> dict:
        return self.doc["members"]

    @property
    def change(self) -> dict | None:
        return self.doc.get("change")

    def partition_members(self, partition_id: int) -> list[str]:
        """Members hosting a replica of the partition (any replica state)."""
        out = []
        for member_id, member in self.members.items():
            if str(partition_id) in member.get("partitions", {}):
                out.append(member_id)
        return sorted(out)

    def active_partition_members(self, partition_id: int) -> list[str]:
        out = []
        for member_id, member in self.members.items():
            p = member.get("partitions", {}).get(str(partition_id))
            if p is not None and p.get("state") == ACTIVE:
                out.append(member_id)
        return sorted(out)

    def next_operation(self) -> dict | None:
        change = self.change
        if not change:
            return None
        ops = change["operations"]
        idx = change["index"]
        return ops[idx] if idx < len(ops) else None

    def summary(self) -> dict:
        """Compact read-only view for ``GET /cluster/status``: version,
        member/replica states and priorities, and whether a change plan is
        mid-flight (operators care that a move is in progress, not about the
        operation list's internals)."""
        members = {}
        for member_id, member in sorted(self.members.items()):
            members[member_id] = {
                "state": member.get("state", ACTIVE),
                "partitions": {
                    pid: {"state": p.get("state", ACTIVE),
                          "priority": p.get("priority", 1)}
                    for pid, p in sorted(member.get("partitions", {}).items())
                },
            }
        return {
            "version": self.version,
            "members": members,
            "changeInProgress": self.change is not None,
        }

    # -- construction ---------------------------------------------------------

    @classmethod
    def initial(cls, distribution: dict[int, list[str]], members: list[str],
                priorities: dict[tuple[str, int], int] | None = None) -> "ClusterTopology":
        topo = cls()
        for m in members:
            topo.members[m] = {"state": ACTIVE, "partitions": {}}
        for pid, hosts in distribution.items():
            for i, m in enumerate(hosts):
                topo.members.setdefault(m, {"state": ACTIVE, "partitions": {}})
                prio = (priorities or {}).get((m, pid), len(hosts) - i)
                topo.members[m]["partitions"][str(pid)] = {
                    "state": ACTIVE, "priority": prio,
                }
        return topo

    def copy(self) -> "ClusterTopology":
        return ClusterTopology(copy.deepcopy(self.doc))


class TopologyManager:
    """Per-broker topology participant (and coordinator for locally-proposed
    changes). Hooks decouple it from the broker:

    - start_replica(partition_id, members, priority): bootstrap a local
      replica whose raft group is ``members``
    - stop_replica(partition_id): tear down the local replica
    - raft_of(partition_id) -> RaftNode | None
    - request_reconfigure(partition_id, change): deliver a reconfigure
      intent ({"add": member} or {"remove": member}) to the partition's
      current leader, which computes the new member list from its OWN
      configuration (a requester with a stale view must not be able to
      drop other replicas)
    """

    GOSSIP_PROPERTY = "topology"

    def __init__(self, member_id: str, membership,
                 start_replica: Callable[[int, list[str], int], None],
                 stop_replica: Callable[[int], None],
                 raft_of: Callable[[int], Any],
                 request_reconfigure: Callable[[int, dict], None],
                 persist: Callable[[dict], None] | None = None) -> None:
        self.member_id = member_id
        self.membership = membership
        self.start_replica = start_replica
        self.stop_replica = stop_replica
        # optional per-partition ownership guard (context-manager factory),
        # wired by the broker when ownership threads exist
        self.partition_guard = None
        self.raft_of = raft_of
        self.request_reconfigure = request_reconfigure
        self.persist = persist or (lambda doc: None)
        self.topology = ClusterTopology()
        self._dirty = True
        # local progress markers for the in-flight operation (avoid repeating
        # side effects every tick while waiting for completion)
        self._op_started: tuple[int, int] | None = None  # (change id, index)
        # partition id → membership confirmed by the leader's reconfigure reply
        self._reconfigure_confirmations: dict[int, list[str]] = {}

    # -- lifecycle -------------------------------------------------------------

    def bootstrap(self, distribution: dict[int, list[str]], members: list[str]) -> None:
        self.topology = ClusterTopology.initial(distribution, members)
        self._dirty = True

    def restore(self, doc: dict) -> None:
        """Boot from a persisted topology document (a restart must not forget
        partitions that were moved onto this member at runtime)."""
        self.topology = ClusterTopology(copy.deepcopy(doc))
        self._dirty = True

    def own_partitions(self) -> dict[int, tuple[list[str], int]]:
        """partition id → (replica member list, priority) for every partition
        this member hosts per the topology document."""
        me = self.topology.members.get(self.member_id, {})
        out = {}
        for pid_str, p in me.get("partitions", {}).items():
            pid = int(pid_str)
            out[pid] = (self.topology.partition_members(pid), p.get("priority", 1))
        return out

    def coordinator(self) -> str | None:
        """The change coordinator: the lowest active member id (reference
        designates a single coordinator; enforcing it here is what keeps
        versions totally ordered under concurrent proposals)."""
        active = [m for m, s in self.topology.members.items()
                  if s.get("state") == ACTIVE]
        return min(active) if active else None

    # -- change proposal (coordinator API) ------------------------------------

    def propose(self, operations: list[dict]) -> bool:
        """Install a change plan (reference: TopologyChangeCoordinator). One
        at a time, and only on the coordinator member — both rejections keep
        topology versions totally ordered."""
        if self.topology.change is not None:
            return False
        if self.coordinator() != self.member_id:
            return False
        topo = self.topology
        topo.doc["change"] = {
            "id": topo.version + 1,
            "index": 0,
            "operations": operations,
        }
        self._bump()
        return True

    def join_member(self, member_id: str) -> dict:
        return {"op": MEMBER_JOIN, "member": member_id}

    def leave_member(self, member_id: str) -> dict:
        return {"op": MEMBER_LEAVE, "member": member_id}

    def join_partition(self, member_id: str, partition_id: int, priority: int = 1) -> dict:
        return {"op": PARTITION_JOIN, "member": member_id,
                "partition": partition_id, "priority": priority}

    def leave_partition(self, member_id: str, partition_id: int) -> dict:
        return {"op": PARTITION_LEAVE, "member": member_id,
                "partition": partition_id}

    # -- gossip ----------------------------------------------------------------

    def _bump(self) -> None:
        self.topology.doc["version"] += 1
        self._dirty = True

    def _merge_remote(self) -> None:
        best = self.topology
        for member in self.membership.members.values():
            doc = member.properties.get(self.GOSSIP_PROPERTY)
            if doc and doc.get("version", 0) > best.version:
                best = ClusterTopology(copy.deepcopy(doc))
        if best is not self.topology:
            self.topology = best
            self._dirty = True
            self._op_started = None

    def _publish(self) -> None:
        if self._dirty:
            self.membership.set_property(self.GOSSIP_PROPERTY,
                                         copy.deepcopy(self.topology.doc))
            self.persist(self.topology.doc)
            self._dirty = False

    # -- tick ------------------------------------------------------------------

    def tick(self) -> None:
        self._merge_remote()
        self._apply_next_operation()
        self._publish()

    def _apply_next_operation(self) -> None:
        topo = self.topology
        op = topo.next_operation()
        change = topo.change
        if op is None:
            if change is not None:
                # all operations applied: the LAST op's target retires the plan
                topo.doc["change"] = None
                self._bump()
            return
        if op["member"] != self.member_id:
            return  # someone else's move
        marker = (change["id"], change["index"])
        guard = (self.partition_guard(op["partition"])
                 if self.partition_guard is not None and "partition" in op
                 else None)
        if guard is None:
            done = self._execute(op, first=self._op_started != marker)
        else:
            # partition-scoped operations mutate that partition's raft state
            # (reconfigure, replica bootstrap/teardown) — they must hold the
            # partition's ownership lock so they never race its pump thread
            with guard:
                done = self._execute(op, first=self._op_started != marker)
        self._op_started = marker
        if done:
            change["index"] += 1
            if change["index"] >= len(change["operations"]):
                topo.doc["change"] = None
            self._op_started = None
            self._bump()

    # -- operation appliers ----------------------------------------------------

    def _execute(self, op: dict, first: bool) -> bool:
        kind = op["op"]
        topo = self.topology
        me = topo.members.setdefault(self.member_id,
                                     {"state": JOINING, "partitions": {}})
        if kind == MEMBER_JOIN:
            me["state"] = ACTIVE
            return True
        if kind == MEMBER_LEAVE:
            if me.get("partitions"):
                return False  # partitions must be moved away first
            me["state"] = LEFT
            return True
        if kind == PARTITION_JOIN:
            return self._partition_join(op, me, first)
        if kind == PARTITION_LEAVE:
            return self._partition_leave(op, me, first)
        return True  # unknown op: skip rather than wedge the plan

    def _partition_join(self, op: dict, me: dict, first: bool) -> bool:
        pid = op["partition"]
        raft = self.raft_of(pid)
        if raft is None:
            # start the local replica against the current replica set + self
            members = sorted(set(self.topology.partition_members(pid))
                             | {self.member_id})
            me["partitions"][str(pid)] = {
                "state": JOINING, "priority": op.get("priority", 1),
            }
            self.start_replica(pid, members, op.get("priority", 1))
            self._dirty = True
            return False
        if raft.leader_commit_hint == 0 and raft.commit_index == 0:
            # the group's leader has not contacted us yet — our own member
            # list already contains us (we bootstrapped with it), so the only
            # reliable join signal is an append from the leader. Keep asking
            # for the reconfiguration until then (idempotent on the leader:
            # adding an existing member is a no-op).
            self.request_reconfigure(pid, {"add": self.member_id})
            return False
        # in contact: complete once caught up with the leader's commit
        if raft.commit_index < raft.leader_commit_hint:
            return False
        me["partitions"][str(pid)] = {
            "state": ACTIVE, "priority": op.get("priority", 1),
        }
        return True

    def _partition_leave(self, op: dict, me: dict, first: bool) -> bool:
        pid = op["partition"]
        raft = self.raft_of(pid)
        if raft is None:
            me.get("partitions", {}).pop(str(pid), None)
            return True
        confirmed = self._reconfigure_confirmations.get(pid)
        removed = (
            self.member_id not in raft.members
            or (confirmed is not None and self.member_id not in confirmed)
        )
        if not removed:
            if len(raft.members) == 1:
                return False  # refuse to orphan the partition
            if raft.role.name == "LEADER":
                raft.reconfigure(sorted(
                    m for m in raft.members if m != self.member_id
                ))
            else:
                # retry every tick (idempotent on the leader): the request is
                # dropped when no leader is known, and the config entry that
                # tells us we left can be lost — the leader's confirmation
                # reply (on_reconfigure_confirmed) is the durable signal
                self.request_reconfigure(pid, {"remove": self.member_id})
            if str(pid) in me.get("partitions", {}):
                me["partitions"][str(pid)]["state"] = LEAVING
                self._dirty = True
            return False
        # out of the group: stop the replica and drop the entry
        self.stop_replica(pid)
        self._reconfigure_confirmations.pop(pid, None)
        me.get("partitions", {}).pop(str(pid), None)
        return True

    def on_reconfigure_confirmed(self, partition_id: int, members: list[str]) -> None:
        """The partition leader's reply to a reconfigure request: the
        authoritative membership after the change (lets a removed replica
        complete PARTITION_LEAVE even if it never received the config entry)."""
        self._reconfigure_confirmations[partition_id] = list(members)
