"""Raft consensus, one instance per partition, over the segmented journal.

Reference: atomix/cluster/src/main/java/io/atomix/raft/ — RaftContext.java:105,
roles/{LeaderRole.java:593-707, FollowerRole, CandidateRole, PassiveRole},
LeaderAppender.java (replication loop), pre-vote + priority election
(RaftElectionConfig), snapshot replication to lagging followers (PassiveRole +
FileBasedReceivedSnapshot), and the Zeebe write ingress
LeaderRole.appendEntry(lowestPos, highestPos, data, listener) (:655-685).

TPU-native re-design: no actor threads — a RaftNode is a deterministic state
machine advanced by ``tick(now)`` and delivered messages, identical under the
loopback test network and the TCP backend. Entries carry opaque ``bytes`` (the
log-stream batch payloads) plus an ``asqn`` (application sequence number =
stream position of the batch's first record), so the log stream can seek after
recovery exactly like the reference (journal asqn-seek, SURVEY §2.3).

Persistent per-node state: the journal itself plus a small meta file
(currentTerm, votedFor) — the reference's MetaStore.
"""

from __future__ import annotations

import enum
import json
import os
import random
from pathlib import Path
from time import perf_counter as _perf_counter
from typing import Any, Callable

from zeebe_tpu.cluster.messaging import MessagingService
from zeebe_tpu.journal import SegmentedJournal
from zeebe_tpu.journal.journal import CorruptedJournalError
from zeebe_tpu.protocol.msgpack import packb, unpackb

HEARTBEAT_INTERVAL_MS = 250
ELECTION_TIMEOUT_MS = 2_500
MAX_ENTRIES_PER_APPEND = 64
SNAPSHOT_CHUNK_BYTES = 512 * 1024
# last-resort window for corruption-repaired nodes (ISSUE 14): a node whose
# log was truncated below its own commit index abstains from elections —
# but if NO leader has been heard for this long, every replica may be in
# that state (rot hit a quorum) and abstention would wedge the cluster
# forever. Past the window the node re-enters elections under the standard
# longest-log-wins rule; what rot destroyed on every replica is gone either
# way (the documented caveat), and a healthy leader's heartbeats make the
# window unreachable in normal operation.
LAST_RESORT_ELECTION_MS = 10 * ELECTION_TIMEOUT_MS


class RaftRole(enum.Enum):
    INACTIVE = "inactive"
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"


class RaftNode:
    """One member of one partition's replication group."""

    def __init__(
        self,
        messaging: MessagingService,
        partition_id: int,
        members: list[str],
        directory: str | Path,
        clock_millis: Callable[[], int],
        priority: int = 1,
        seed: int | None = None,
        flush_policy: str = "immediate",
        flush_interval_s: float = 0.0,
        max_unflushed_bytes: int = 1 << 20,
    ) -> None:
        self.messaging = messaging
        self.member_id = messaging.member_id
        self.partition_id = partition_id
        from zeebe_tpu.utils.metrics import REGISTRY

        pid = str(partition_id)
        self._m_elections = REGISTRY.counter(
            "raft_elections_total", "elections started", ("partition",)
        ).labels(pid)
        self._m_election_latency = REGISTRY.histogram(
            "election_latency_in_ms", "candidate -> leader in ms",
            ("partition",), buckets=(1, 5, 10, 50, 100, 500, 1000, 5000),
        ).labels(pid)
        self._m_leader_transition = REGISTRY.histogram(
            "leader_transition_latency",
            "leader election to first commit, seconds", ("partition",)
        ).labels(pid)
        self._m_role = REGISTRY.gauge(
            "role", "raft role (3=leader 2=candidate 1=follower)", ("partition",)
        ).labels(pid)
        self._m_heartbeat_miss = REGISTRY.counter(
            "heartbeat_miss_count", "election timeouts from missed heartbeats",
            ("partition",)).labels(pid)
        self._m_heartbeat_time = REGISTRY.gauge(
            "heartbeat_time_in_s", "last heartbeat seen, epoch seconds",
            ("partition",)).labels(pid)
        self._m_msg_send = REGISTRY.counter(
            "raft_messages_send", "raft rpcs sent", ("partition", "type"))
        self._m_msg_recv = REGISTRY.counter(
            "raft_messages_received", "raft rpcs received", ("partition", "type"))
        self._m_append_index = REGISTRY.gauge(
            "partition_raft_append_index", "last raft log index", ("partition",)
        ).labels(pid)
        self._m_commit_index = REGISTRY.gauge(
            "partition_raft_commit_index", "raft commit index", ("partition",)
        ).labels(pid)
        self._m_non_committed = REGISTRY.gauge(
            "non_committed_entries", "entries appended but not committed",
            ("partition",)).labels(pid)
        self._m_non_replicated = REGISTRY.gauge(
            "non_replicated_entries",
            "entries not yet replicated to the slowest follower",
            ("partition",)).labels(pid)
        self._m_append_rate = REGISTRY.counter(
            "append_entries_rate", "AppendEntries rpcs sent", ("partition",)
        ).labels(pid)
        self._m_append_data = REGISTRY.counter(
            "append_entries_data_rate", "entry bytes shipped in AppendEntries",
            ("partition",)).labels(pid)
        self._m_append_latency = REGISTRY.histogram(
            "append_entries_latency", "local leader append seconds",
            ("partition",)).labels(pid)
        self._m_commit_rate = REGISTRY.counter(
            "commit_entries_rate", "entries committed", ("partition",)
        ).labels(pid)
        self._m_snapshot_repl = REGISTRY.counter(
            "snapshot_replication_count",
            "snapshot installs sent to lagging followers", ("partition",)
        ).labels(pid)
        self._m_snapshot_repl_ms = REGISTRY.histogram(
            "snapshot_replication_duration_milliseconds",
            "ms to build+send one snapshot install", ("partition",),
            buckets=(1, 5, 10, 50, 100, 500, 1000, 5000),
        ).labels(pid)
        self._m_flush_duration = REGISTRY.histogram(
            "flush_duration_seconds",
            "seconds per raft journal fsync", ("partition",)).labels(pid)
        self._m_deferred_appends = REGISTRY.counter(
            "deferred_append_count_total",
            "appends acked before fsync (delayed flush policy)",
            ("partition",)).labels(pid)
        self._election_started_ms: int | None = None
        self._leader_since_ms: int | None = None
        self.members = sorted(members)
        self._bootstrap_members = sorted(members)
        # configuration in effect at the journal's base (snapshot boundary):
        # the truncation-rollback fallback when no config entry survives in
        # the log suffix
        self._config_base = sorted(members)
        self._last_config_index = 0
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.clock_millis = clock_millis
        self.priority = priority
        # deterministic jitter per member (tests are reproducible)
        self._rng = random.Random(
            seed if seed is not None else hash((self.member_id, partition_id)) & 0xFFFF
        )

        self.journal = SegmentedJournal(self.directory / "raft-log",
                                        max_unflushed_bytes=max_unflushed_bytes)
        # "immediate": fsync before acking appends / advancing own match —
        # the reference's default (journal flush-before-ack, SURVEY §2.2);
        # "delayed": fsync on the next tick (reference DelayedFlusher);
        # "none": never fsync (tests).
        if flush_policy not in ("immediate", "delayed", "none"):
            raise ValueError(f"unknown flush_policy {flush_policy!r}")
        self.flush_policy = flush_policy
        # group-commit pacing over the "immediate" policy (ISSUE 12): with
        # flush_interval_s > 0 the fsync is DEFERRED up to the interval (or
        # the journal's max_unflushed_bytes), and the *acknowledgement*
        # waits for it — _ack_index() holds at the flushed prefix, so
        # unlike "delayed" nothing is ever acked/committed before its
        # covering fsync; a power loss costs only unacked entries. Several
        # appends inside the window share one fsync: the classic
        # group-commit latency/throughput trade, and the journal-flush
        # controller's knob (zeebe_tpu/control — the single runtime write
        # path for it).
        self.flush_interval_s = max(float(flush_interval_s), 0.0)
        self._last_flush_perf = _perf_counter()
        # trust only the journal's flush marker on open: entries beyond it may
        # sit in the OS page cache (a process crash reopens them readable, but
        # a later power loss would drop them), so they get re-fsynced before
        # this node acks anything
        self._flushed_index = min(self.journal.last_flushed_index,
                                  self.journal.last_index)
        self._flush_dirty = False
        # boot-time rot suspicion (ISSUE 14): the open() scan truncates the
        # journal at the first corrupt frame — safe for a torn UNFSYNCED
        # tail (those bytes were never acked), but at-rest bit rot can land
        # BELOW the persisted flush marker, i.e. below bytes this node
        # promised were durable (and possibly voted into a commit). The
        # marker is written only after a successful fsync, so marker >
        # last_index on open means flushed history was LOST: the node boots
        # SUSPECT and abstains from elections (see _election_safe) until a
        # leader re-converges it past the marker — without this, a
        # restarted replica with a silently-shortened log can win an
        # election and re-mint different bytes at committed positions
        # (caught as export split-brain by the torture gate). RF=1 has no
        # one to re-converge from: the loss is accepted (documented caveat).
        marker = self.journal.last_flushed_index
        self._suspect_index = (
            marker if (marker > self.journal.last_index
                       and len(self.members) > 1) else 0)
        self._meta_path = self.directory / "raft-meta.json"
        self.current_term = 0
        self.voted_for: str | None = None
        self._load_meta()

        self.role = RaftRole.FOLLOWER
        self.leader_id: str | None = None
        self.commit_index = 0
        # snapshot bookkeeping (log prefix replaced by a snapshot)
        self.snapshot_index = 0
        self.snapshot_term = 0
        self._snapshot_bytes: bytes | None = None
        self._pending_snapshot: dict[str, Any] | None = None
        self._snapshot_sent_ms: dict[str, int] = {}

        # leader volatile state
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self._pending_appends: dict[int, Callable[[int], None]] = {}

        # election timers
        self._last_heartbeat_ms = clock_millis()
        self._election_deadline_ms = self._next_election_deadline()
        self._last_heartbeat_sent_ms = 0
        self._votes: set[str] = set()
        self._prevotes: set[str] = set()

        self.leader_commit_hint = 0
        self.role_listeners: list[Callable[[RaftRole, int], None]] = []
        self.commit_listeners: list[Callable[[int], None]] = []
        # snapshot provider: () -> (index, term, bytes) | None — installed by
        # the partition owner so lagging followers receive state snapshots
        self.snapshot_provider: Callable[[], tuple[int, int, bytes] | None] | None = None
        self.snapshot_receiver: Callable[[bytes], None] | None = None
        # storage-fault plane (ISSUE 14): called with (event, detail) on
        # journal corruption repairs and fsync failures so the partition can
        # flight-record them; repairs are throttled against hot loops
        self.storage_listener: Callable[[str, dict], None] | None = None
        self._last_repair_perf = -60.0

        t = f"raft-{partition_id}"

        def _counted(suffix, handler):
            child = self._m_msg_recv.labels(str(partition_id), suffix)

            def wrapped(sender, payload):
                child.inc()
                try:
                    handler(sender, payload)
                except CorruptedJournalError as exc:
                    # at-rest rot surfaced on a read inside an rpc handler:
                    # repair (truncate at the corrupt frame) instead of
                    # letting the error poison the messaging poll loop —
                    # the raft append path re-converges the lost suffix
                    self.repair_journal_corruption(exc)
                except OSError as exc:
                    # storage trouble inside an rpc handler (failed fsync,
                    # write fault): nothing was acked beyond the flushed
                    # prefix — note it and let the protocol retry
                    self._note_storage_error(exc)

            return wrapped

        messaging.subscribe(f"{t}-vote", _counted("vote", self._on_vote_request))
        messaging.subscribe(f"{t}-vote-resp", _counted("vote-resp", self._on_vote_response))
        messaging.subscribe(f"{t}-append", _counted("append", self._on_append_request))
        messaging.subscribe(f"{t}-append-resp", _counted("append-resp", self._on_append_response))
        messaging.subscribe(f"{t}-snapshot", _counted("snapshot", self._on_install_snapshot))
        messaging.subscribe(f"{t}-timeout-now", _counted("timeout-now", self._on_timeout_now))
        messaging.subscribe(f"{t}-snapshot-req",
                            _counted("snapshot-req", self._on_snapshot_request))

    # -- persistence ----------------------------------------------------------

    def _load_meta(self) -> None:
        if self._meta_path.exists():
            meta = json.loads(self._meta_path.read_text())
            self.current_term = meta["term"]
            self.voted_for = meta["votedFor"]
            # a reconfigured membership survives restart (the bootstrap list
            # is only the initial configuration)
            if meta.get("members"):
                self.members = sorted(meta["members"])

    def _store_meta(self) -> None:
        # temp-file + fsync + atomic rename: a crash mid-write must never
        # leave a torn meta file, and a persisted vote must survive the crash
        # (double-vote safety) — reference MetaStore semantics
        tmp = self._meta_path.with_suffix(".json.tmp")
        with open(tmp, "w") as f:
            f.write(json.dumps({"term": self.current_term, "votedFor": self.voted_for,
                                "members": self.members}))
            f.flush()
            if self.flush_policy != "none":
                os.fsync(f.fileno())
        os.replace(tmp, self._meta_path)
        if self.flush_policy != "none":
            # the rename itself must be durable before a vote response leaves
            dir_fd = os.open(self.directory, os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)

    def _after_local_append(self) -> None:
        """Durability barrier after appending entries, before acknowledging
        them (follower ack, or leader counting itself toward the quorum)."""
        if self.flush_policy == "immediate":
            if self.flush_interval_s <= 0:
                self._flush_journal()
                return
            # group-commit posture: defer the fsync up to flush_interval_s
            # or the byte bound; _ack_index() holds at the flushed prefix,
            # so deferral delays the ack — it never precedes the fsync
            self._flush_dirty = True
            if self._group_flush_due():
                self._flush_journal()
        elif self.flush_policy == "delayed":
            self._flush_dirty = True
            self._m_deferred_appends.inc()

    def _group_flush_due(self) -> bool:
        return (self.journal.unflushed_bytes
                >= self.journal.max_unflushed_bytes
                or _perf_counter() - self._last_flush_perf
                >= self.flush_interval_s)

    def _ack_index(self) -> int:
        """Highest index this node may acknowledge (follower ack, or the
        leader's own quorum vote). Under the group-commit posture that is
        the durably flushed prefix — never an unfsynced entry; every other
        posture keeps its existing semantics (notably "delayed", which
        deliberately acks before fsync). The ``_flush_dirty`` clause keeps
        the hold when the journal-flush actuator narrows the interval back
        to 0 WHILE a deferral is pending — the suffix stays unackable
        until the next tick drains it (dropping the hold on the knob
        change alone would ack entries whose fsync never happened)."""
        if self.flush_policy == "immediate" and (self.flush_interval_s > 0
                                                 or self._flush_dirty):
            return min(self._last_log_index(),
                       max(self._flushed_index, self.snapshot_index))
        return self._last_log_index()

    def _flush_journal(self) -> None:
        if self.journal.last_index != self._flushed_index:
            start = _perf_counter()
            try:
                self.journal.flush()
            except OSError as exc:
                # fsyncgate (ISSUE 14): the journal already failed the
                # segment hard — fresh fd, file re-verified from the last
                # known-flushed offset, suffix discarded. Our job is the
                # consensus side of the contract: nothing the failed fsync
                # covered may be acked, and a LEADER whose own log just
                # rewound must stop leading (re-appending at reused indexes
                # in the same term would hand followers conflicting entries
                # the protocol cannot detect). The surviving cluster
                # re-elects; this node re-converges as a follower.
                self._flushed_index = min(self._flushed_index,
                                          self.journal.last_index)
                self._flush_dirty = False
                self._last_flush_perf = _perf_counter()
                self._note_storage_error(exc)
                if self.role == RaftRole.LEADER:
                    self._become(RaftRole.FOLLOWER)
                return
            self._m_flush_duration.observe(_perf_counter() - start)
            self._flushed_index = self.journal.last_index
        self._flush_dirty = False
        self._last_flush_perf = _perf_counter()

    def _truncate_after(self, index: int) -> None:
        had_config_after = any(
            e.get("config") for e in self._entries_from(index + 1)
        )
        self.journal.truncate_after(index)
        # conflicting entries re-appended on top of a truncation must be
        # fsynced again even when the log lands back on the old flushed index
        self._flushed_index = min(self._flushed_index, index)
        if had_config_after:
            # configs apply on APPEND; truncating one away must revert to the
            # last surviving configuration (Raft single-step change rule)
            members, config_index = self._latest_logged_config()
            self._last_config_index = config_index
            self._apply_config(members)

    def _entries_from(self, from_index: int) -> list[dict]:
        out = []
        for rec in self.journal.read_from(from_index):
            entry = unpackb(rec.data)
            entry["index"] = rec.index
            out.append(entry)
        return out

    def _latest_logged_config(self) -> tuple[list[str], int]:
        latest, index = self._config_base, 0
        for entry in self._entries_from(self.snapshot_index + 1):
            if entry.get("config"):
                latest, index = entry["config"], entry["index"]
        return latest, index

    def _reset_journal(self, next_index: int) -> None:
        self.journal.reset(next_index)
        self._flushed_index = min(self._flushed_index, next_index - 1)
        # the log prefix (and any config entries in it) is gone: the current
        # membership becomes the configuration base for rollbacks
        self._config_base = list(self.members)

    # -- storage-fault repair (ISSUE 14) --------------------------------------

    def repair_journal_corruption(self, exc: Exception | None = None) -> dict:
        """At-rest corruption in the raft journal (bit rot caught by the
        scrubber, or a checksum mismatch hit on a live read): truncate at
        the corrupt frame and let the protocol re-converge — the leader
        backs up to the survivors' end and resends, exactly the divergent-
        follower repair Raft already owns. A LEADER repairing its own log
        steps down first (leader completeness: the committed suffix lives
        on a quorum; a single-replica cluster can only truncate — that
        caveat is documented, not hidden). Throttled: a second repair
        within 5s reports ``journal_unrepairable`` through the storage
        listener (the partition fails its processor) instead of looping a
        hot unrepairable fault — and never raises: the callers are rpc
        handlers and tick(), whose escape path is the worker's whole poll
        loop."""
        now = _perf_counter()
        if now - self._last_repair_perf < 5.0:
            # unrepairable by this seam: surface it through the listener —
            # NEVER raise from here, the callers are rpc handlers and
            # tick() whose escape path is the worker's whole poll loop;
            # the partition listener contains it like a poison record
            evidence = {"journal": "raft", "member": self.member_id,
                        "gaveUp": True,
                        "reason": f"repair looping on {self.directory}"
                                  f" ({exc})"}
            if self.storage_listener is not None:
                self.storage_listener("journal_unrepairable", evidence)
            return evidence
        self._last_repair_perf = now
        evidence = self.journal.repair_corruption()
        self._flushed_index = min(self._flushed_index, self.journal.last_index)
        if (len(self.members) <= 1
                and self.journal.last_index < self.commit_index):
            # single-replica cluster: there is no leader to re-fetch the
            # truncated committed suffix from — the disk ate it (the
            # documented RF=1 caveat). Rewind the commit index so the node
            # keeps serving what survives instead of abstaining forever
            # (_election_safe would otherwise never clear).
            evidence["rewoundCommitIndex"] = self.commit_index
            self.commit_index = self.journal.last_index
        evidence["journal"] = "raft"
        evidence["member"] = self.member_id
        evidence["wasLeader"] = self.role == RaftRole.LEADER
        if exc is not None:
            evidence["trigger"] = str(exc)
        if self.role == RaftRole.LEADER:
            self._become(RaftRole.FOLLOWER)
        if self.storage_listener is not None:
            self.storage_listener("journal_repair", evidence)
        return evidence

    def _note_storage_error(self, exc: OSError) -> None:
        if self.storage_listener is not None:
            self.storage_listener("storage_error", {
                "journal": "raft", "member": self.member_id,
                "error": f"{type(exc).__name__}: {exc}"})

    def request_snapshot(self) -> bool:
        """Follower-side snapshot re-fetch (ISSUE 14): ask the current
        leader to stream its snapshot install — the repair path for a
        follower whose at-rest snapshot chain is corrupt. The existing
        install machinery does the rest (reset journal past the snapshot,
        persist, rebuild the vertical). Returns False when there is no
        known leader to ask (retry on a later scrub pass)."""
        if self.role == RaftRole.LEADER or self.leader_id is None:
            return False
        self._send(self.leader_id, "snapshot-req",
                   {"term": self.current_term, "follower": self.member_id})
        return True

    def _on_snapshot_request(self, sender: str, req: dict) -> None:
        if req.get("term", 0) > self.current_term:
            # like every raft rpc: a higher term deposes a stale leader
            self._set_term(req["term"])
            self._become(RaftRole.FOLLOWER)
            return
        if self.role != RaftRole.LEADER:
            return
        self._send_snapshot(sender)

    def close(self) -> None:
        if self.flush_policy != "none":
            self._flush_journal()  # drain a pending delayed flush on shutdown
        self.journal.close()

    # -- log accessors --------------------------------------------------------

    def _last_log_index(self) -> int:
        return max(self.journal.last_index, self.snapshot_index)

    def _entry_term(self, index: int) -> int:
        if index == 0:
            return 0
        if index == self.snapshot_index:
            return self.snapshot_term
        rec = self.journal.read_entry(index)
        if rec is None:
            return -1
        return unpackb(rec.data)["term"]

    def _last_log_term(self) -> int:
        return self._entry_term(self._last_log_index())

    def _read_entries(self, from_index: int, limit: int) -> list[dict]:
        out = []
        for rec in self.journal.read_from(from_index):
            entry = unpackb(rec.data)
            entry["index"] = rec.index
            out.append(entry)
            if len(out) >= limit:
                break
        return out

    # -- timers ---------------------------------------------------------------

    def _next_election_deadline(self) -> int:
        # priority shortens the timeout so preferred members win elections
        # (reference: RaftElectionConfig priority election)
        jitter = self._rng.randrange(ELECTION_TIMEOUT_MS // 2)
        bias = ELECTION_TIMEOUT_MS // (2 * max(self.priority, 1))
        return self.clock_millis() + bias + jitter

    def tick(self, now_millis: int | None = None) -> None:
        try:
            self._tick_inner(now_millis)
        except CorruptedJournalError as exc:
            # journal reads ride the tick (heartbeat entry reads, election
            # up-to-date terms): rot there repairs exactly like rot inside
            # an rpc handler
            self.repair_journal_corruption(exc)
        except OSError as exc:
            # deliberately broad: storage faults AND transport errors that
            # escape a tick are both contained here — the caller is the
            # worker's whole poll loop, and the next tick (~one pump round
            # away) redoes any work this one dropped
            self._note_storage_error(exc)

    def _tick_inner(self, now_millis: int | None = None) -> None:
        now = self.clock_millis() if now_millis is None else now_millis
        if self._flush_dirty:
            if self.flush_policy == "immediate":
                # group-commit posture: drain when due — or immediately
                # when the actuator narrowed the interval to 0 mid-deferral
                # — then release the acks the deferral was holding: the
                # leader re-counts its own durable vote, a follower
                # proactively acks the leader (waiting for the next
                # heartbeat would add up to HEARTBEAT_INTERVAL_MS to every
                # deferred commit)
                if self.flush_interval_s <= 0 or self._group_flush_due():
                    self._flush_journal()
                    if self.role == RaftRole.LEADER:
                        self._advance_commit()
                    elif (self.role == RaftRole.FOLLOWER
                          and self.leader_id is not None
                          and self.leader_id != self.member_id):
                        self._send(self.leader_id, "append-resp", {
                            "term": self.current_term, "success": True,
                            "lastIndex": self._ack_index(),
                            "follower": self.member_id,
                        })
            else:
                self._flush_journal()  # delayed flush policy drains here
        if self.role == RaftRole.LEADER:
            if now - self._last_heartbeat_sent_ms >= HEARTBEAT_INTERVAL_MS:
                self._broadcast_appends()
        elif now >= self._election_deadline_ms:
            self._start_prevote()

    # -- elections ------------------------------------------------------------

    def _election_safe(self) -> bool:
        """Raft's quorum-intersection safety argument assumes stable
        storage. A node whose journal was truncate-REPAIRED below its own
        known commit index (at-rest corruption, ISSUE 14) holds a log that
        LIES about history: letting it start elections — or grant votes
        against its shortened log — can elect a leader missing committed
        entries (commit majority {A,B}, election majority {A,C}, A is the
        corrupted intersection). Until the leader re-converges this node
        past its commit index, it ABSTAINS from elections entirely. Healthy
        operation always satisfies the check (commit ≤ last log index), so
        this costs nothing outside a repair window. The same rule covers
        BOOT-time rot: a journal that opened below its own flush marker
        (``_suspect_index``) lost flushed — possibly committed — history
        and must not lead or judge until refilled past the marker."""
        return self._last_log_index() >= max(self.commit_index,
                                             self._suspect_index)

    def _last_resort_due(self) -> bool:
        """True when no leader has been heard for LAST_RESORT_ELECTION_MS:
        the abstention rule yields to liveness (rot on a quorum would
        otherwise wedge the cluster with every replica waiting for a
        leader that can never be elected)."""
        return (self.clock_millis() - self._last_heartbeat_ms
                >= LAST_RESORT_ELECTION_MS)

    def _start_prevote(self) -> None:
        """Pre-vote phase: probe electability without disturbing the term
        (reference: raft pre-vote, PreVoteRequest). A candidate whose election
        timed out retries the election directly — prevote responses are only
        collected while still a follower."""
        if not self._election_safe() and not self._last_resort_due():
            # corruption-repaired log below our own commit: wait for the
            # leader to refill it (see _election_safe) instead of electing
            self._election_deadline_ms = self._next_election_deadline()
            return
        if self.role == RaftRole.CANDIDATE:
            self._start_election()
            return
        self._election_deadline_ms = self._next_election_deadline()
        self._prevotes = {self.member_id}
        if self._quorum(len(self._prevotes)):
            self._start_election()
            return
        for m in self._other_members():
            self._send(m, "vote", {
                "term": self.current_term + 1,
                "candidate": self.member_id,
                "lastLogIndex": self._last_log_index(),
                "lastLogTerm": self._last_log_term(),
                "prevote": True,
            })

    def _start_election(self) -> None:
        self._prevotes = set()  # stale grants must not re-trigger elections
        self._m_elections.inc()
        self._m_heartbeat_miss.inc()
        self._election_started_ms = self.clock_millis()
        self._set_term(self.current_term + 1, vote_for=self.member_id)
        self._become(RaftRole.CANDIDATE)
        self._votes = {self.member_id}
        self._election_deadline_ms = self._next_election_deadline()
        if self._quorum(len(self._votes)):
            self._become_leader()
            return
        for m in self._other_members():
            self._send(m, "vote", {
                "term": self.current_term,
                "candidate": self.member_id,
                "lastLogIndex": self._last_log_index(),
                "lastLogTerm": self._last_log_term(),
                "prevote": False,
            })

    def _on_vote_request(self, sender: str, req: dict) -> None:
        if sender not in self.members:
            # an ex-member removed by reconfiguration (possibly before it
            # learned of the removal) must not be able to bump our terms
            return
        term = req["term"]
        standard_up_to_date = (
            req["lastLogTerm"] > self._last_log_term()
            or (req["lastLogTerm"] == self._last_log_term()
                and req["lastLogIndex"] >= self._last_log_index())
        )
        if self._election_safe():
            up_to_date = standard_up_to_date
        else:
            # corruption-repaired log below our own commit index: our
            # shortened history cannot judge candidates — it would grant
            # votes to candidates missing committed entries (see
            # _election_safe). But the REMEMBERED commit index still can:
            # a candidate whose log covers it cannot be missing anything
            # we know committed. Past the last-resort window (no leader
            # for 10x the election timeout — rot hit a quorum and nobody
            # can satisfy the commit-index bar) fall back to the standard
            # longest-log-wins rule: the best surviving log leads, and
            # what rot destroyed everywhere is gone either way.
            bar = max(self.commit_index, self._suspect_index)
            up_to_date = (req["lastLogIndex"] >= bar
                          or (self._last_resort_due()
                              and standard_up_to_date))
        if req.get("prevote"):
            # leader stickiness: deny pre-votes while we hear from a live
            # leader, so a rejoining partitioned node cannot depose a healthy
            # one (raft pre-vote + check-quorum semantics)
            heard_recently = (
                self.leader_id is not None
                and self.clock_millis() - self._last_heartbeat_ms < ELECTION_TIMEOUT_MS
            )
            granted = term > self.current_term and up_to_date and not heard_recently
            self._send(sender, "vote-resp", {
                "term": self.current_term, "granted": granted, "prevote": True,
                "voter": self.member_id,
            })
            return
        if term > self.current_term:
            self._set_term(term)
            self._become(RaftRole.FOLLOWER)
        granted = (
            term == self.current_term
            and self.voted_for in (None, req["candidate"])
            and up_to_date
        )
        if granted:
            self.voted_for = req["candidate"]
            self._store_meta()
            self._election_deadline_ms = self._next_election_deadline()
        self._send(sender, "vote-resp", {
            "term": self.current_term, "granted": granted, "prevote": False,
            "voter": self.member_id,
        })

    def _on_vote_response(self, sender: str, resp: dict) -> None:
        if resp.get("prevote"):
            # only followers collect pre-votes; once the election started the
            # round is over (stale grants otherwise burn terms + reset votes)
            if resp["granted"] and self.role == RaftRole.FOLLOWER:
                self._prevotes.add(resp["voter"])
                if self._quorum(len(self._prevotes)):
                    self._start_election()
            return
        if resp["term"] > self.current_term:
            self._set_term(resp["term"])
            self._become(RaftRole.FOLLOWER)
            return
        if self.role != RaftRole.CANDIDATE or resp["term"] != self.current_term:
            return
        if resp["granted"]:
            self._votes.add(resp["voter"])
            if self._quorum(len(self._votes)):
                self._become_leader()

    def transfer_leadership(self, target: str) -> bool:
        """Best-effort leadership transfer (raft leadership-transfer
        extension; reference: RaftContext#transferLeadership backing the
        actuator's RebalancingEndpoint): replicate to the target, then tell
        it to start an election IMMEDIATELY (timeout-now). If the target's
        log is behind it simply loses and we stay leader; if it wins, its
        higher term deposes us on the next message."""
        if (self.role != RaftRole.LEADER or target == self.member_id
                or target not in self.members):
            return False
        self._send_append(target)  # close any replication gap first
        self._send(target, "timeout-now", {"term": self.current_term})
        return True

    def _on_timeout_now(self, sender: str, req: dict) -> None:
        """The current leader asked us to depose it: skip the pre-vote phase
        (the leader itself initiated this, so stickiness must not block it)
        and start an election at once."""
        if sender not in self.members or req["term"] < self.current_term:
            return
        if self.role == RaftRole.LEADER:
            return
        self._start_election()

    def _become_leader(self) -> None:
        now = self.clock_millis()
        if self._election_started_ms is not None:
            self._m_election_latency.observe(now - self._election_started_ms)
            self._election_started_ms = None
        self._leader_since_ms = now
        self._become(RaftRole.LEADER)
        self.leader_id = self.member_id
        last = self._last_log_index()
        self.next_index = {m: last + 1 for m in self._other_members()}
        self.match_index = {m: 0 for m in self._other_members()}
        # commit an initial entry to finalize entries from previous terms
        # (reference: InitialEntry appended on leader transition)
        self._append_local({"term": self.current_term, "init": True, "asqn": -1,
                            "data": b""})
        self._after_local_append()
        self._broadcast_appends()

    # -- write ingress (ZeebeLogAppender.appendEntry equivalent) ---------------

    def reconfigure(self, new_members: list[str]) -> bool:
        """Leader-only single-step membership change (reference: Raft §4.1
        single-server changes; the atomix ConfigurationEntry): appends a
        config entry and applies it IMMEDIATELY on append — both leader and
        followers switch to the new configuration as soon as the entry is in
        their log, not at commit (the Raft paper's rule). One change at a
        time is the coordinator's job (topology change plans are serialized),
        which is what makes single-step changes safe."""
        if self.role != RaftRole.LEADER:
            return False
        new_members = sorted(new_members)
        if new_members == self.members:
            return True
        if self._last_config_index > self.commit_index:
            # single-step changes are only safe one at a time: the previous
            # configuration must commit before the next is appended (callers
            # retry on their next tick)
            return False
        self._last_config_index = self._last_log_index() + 1
        self._append_local({
            "term": self.current_term, "init": False, "asqn": -1, "data": b"",
            "config": new_members,
        })
        self._after_local_append()
        # broadcast BEFORE applying: members being removed must still receive
        # the config entry (it is how they learn they left); only then shrink
        # the replication targets
        self._broadcast_appends()
        self._apply_config(new_members)
        return True

    def _apply_config(self, members: list[str]) -> None:
        self.members = sorted(members)
        self._store_meta()
        if self.role == RaftRole.LEADER:
            last = self._last_log_index()
            for m in self._other_members():
                self.next_index.setdefault(m, last + 1)
                self.match_index.setdefault(m, 0)
            for m in list(self.next_index):
                if m not in self.members:
                    del self.next_index[m]
                    self.match_index.pop(m, None)
            if self.member_id not in self.members:
                # removed myself: hand off by reverting to follower; the rest
                # of the group elects among themselves
                self._become(RaftRole.FOLLOWER)
            else:
                self._advance_commit()  # quorum size may have shrunk

    def append(self, data: bytes, asqn: int = -1,
               on_commit: Callable[[int], None] | None = None) -> int | None:
        """Leader-only append; returns the raft index (None if not leader).
        ``on_commit`` fires with the index once the entry is replicated to a
        quorum (reference: AppendListener.onCommit)."""
        if self.role != RaftRole.LEADER:
            return None
        index = self._append_local({
            "term": self.current_term, "init": False, "asqn": asqn, "data": data,
        })
        self._after_local_append()
        if self.role != RaftRole.LEADER:
            # a failed fsync inside the append stepped this leader down and
            # rewound the suffix — the caller must treat this as not-leader
            return None
        if on_commit is not None:
            self._pending_appends[index] = on_commit
        self._broadcast_appends()
        return index

    def _append_local(self, entry: dict) -> int:
        import time as _time

        start = _time.perf_counter()
        asqn = entry.get("asqn", -1)
        rec = self.journal.append(
            packb({k: v for k, v in entry.items() if k != "index"}),
            asqn=asqn if asqn is not None and asqn >= 0 else -1,  # ASQN_IGNORE
        )
        self._m_append_latency.observe(_time.perf_counter() - start)
        self._m_append_index.set(rec.index)
        return rec.index

    # -- replication ----------------------------------------------------------

    def _broadcast_appends(self) -> None:
        self._last_heartbeat_sent_ms = self.clock_millis()
        for m in self._other_members():
            self._send_append(m)
        self._advance_commit()  # single-node cluster commits immediately

    def _send_append(self, member: str) -> None:
        next_idx = self.next_index.get(member, self._last_log_index() + 1)
        if next_idx <= self.snapshot_index:
            self._send_snapshot(member)
            return
        prev_index = next_idx - 1
        prev_term = self._entry_term(prev_index)
        entries = self._read_entries(next_idx, MAX_ENTRIES_PER_APPEND)
        self._m_append_rate.inc()
        self._m_append_data.inc(sum(len(e.get("data", b"") or b"") for e in entries))
        others = self._other_members()
        if others:
            slowest = min(self.match_index.get(m, 0) for m in others)
            self._m_non_replicated.set(
                max(0, self._last_log_index() - slowest))
        self._send(member, "append", {
            "term": self.current_term,
            "leader": self.member_id,
            "prevIndex": prev_index,
            "prevTerm": prev_term,
            "entries": entries,
            "commit": self.commit_index,
        })

    def _on_append_request(self, sender: str, req: dict) -> None:
        if req["term"] < self.current_term:
            self._send(sender, "append-resp", {
                "term": self.current_term, "success": False,
                "lastIndex": self._last_log_index(), "follower": self.member_id,
            })
            return
        if req["term"] > self.current_term:
            self._set_term(req["term"])
        if self.role != RaftRole.FOLLOWER:
            self._become(RaftRole.FOLLOWER)
        self.leader_id = req["leader"]
        self._last_heartbeat_ms = self.clock_millis()
        self._m_heartbeat_time.set(self._last_heartbeat_ms / 1000.0)
        self._election_deadline_ms = self._next_election_deadline()

        prev_index, prev_term = req["prevIndex"], req["prevTerm"]
        local_prev_term = self._entry_term(prev_index)
        if prev_index > 0 and local_prev_term != prev_term:
            # consistency check failed: ask leader to back up
            self._send(sender, "append-resp", {
                "term": self.current_term, "success": False,
                "lastIndex": min(self._last_log_index(), prev_index - 1),
                "follower": self.member_id,
            })
            return
        for entry in req["entries"]:
            index = entry["index"]
            local_term = self._entry_term(index)
            if local_term == -1 or index > self._last_log_index():
                self._append_at(index, entry)
            elif local_term != entry["term"]:
                self._truncate_after(index - 1)
                self._append_at(index, entry)
        self._after_local_append()  # flush BEFORE acking (Raft durability)
        # the leader's commit index as last advertised — lets a joining
        # replica detect when it has fully caught up (topology PARTITION_JOIN)
        self.leader_commit_hint = max(self.leader_commit_hint, req["commit"])
        if req["commit"] > self.commit_index:
            self._set_commit(min(req["commit"], self._last_log_index()))
        self._send(sender, "append-resp", {
            "term": self.current_term, "success": True,
            # group-commit posture acks only the flushed prefix; the leader
            # resends the (already stored, idempotently skipped) suffix and
            # the deferred-flush tick proactively acks when it drains
            "lastIndex": self._ack_index(), "follower": self.member_id,
        })

    def _append_at(self, index: int, entry: dict) -> None:
        expected = self.journal.last_index + 1
        if index != expected:
            if index <= self.journal.last_index:
                self._truncate_after(index - 1)
            else:
                # gap after snapshot install: reset the journal base
                self._reset_journal(index)
        self._append_local(entry)
        if entry.get("config"):
            self._last_config_index = index
            self._apply_config(entry["config"])

    def _on_append_response(self, sender: str, resp: dict) -> None:
        if resp["term"] > self.current_term:
            self._set_term(resp["term"])
            self._become(RaftRole.FOLLOWER)
            return
        if self.role != RaftRole.LEADER:
            return
        follower = resp["follower"]
        if resp["success"]:
            self.match_index[follower] = resp["lastIndex"]
            self.next_index[follower] = resp["lastIndex"] + 1
            self._advance_commit()
        else:
            # back up (follower hints with its last index)
            self.next_index[follower] = max(1, min(
                self.next_index.get(follower, 1) - 1, resp["lastIndex"] + 1
            ))
            self._send_append(follower)

    def _advance_commit(self) -> None:
        """Advance commit index to the highest index replicated on a quorum
        whose entry is from the current term (Raft §5.4.2)."""
        last = self._last_log_index()
        # under the group-commit posture the leader's own vote counts only
        # up to its flushed prefix (every other posture: the whole log)
        own = self._ack_index()
        for candidate in range(last, self.commit_index, -1):
            count = (1 if own >= candidate else 0) + sum(
                1 for m in self._other_members()
                if self.match_index.get(m, 0) >= candidate)
            if self._quorum(count) and self._entry_term(candidate) == self.current_term:
                self._set_commit(candidate)
                break

    def _set_commit(self, index: int) -> None:
        if index <= self.commit_index:
            return
        self._m_commit_rate.inc(index - self.commit_index)
        self.commit_index = index
        self._m_commit_index.set(index)
        self._m_non_committed.set(max(0, self._last_log_index() - index))
        if self._leader_since_ms is not None:
            self._m_leader_transition.observe(
                (self.clock_millis() - self._leader_since_ms) / 1000.0)
            self._leader_since_ms = None
        for pending_index in sorted(self._pending_appends):
            if pending_index <= index:
                self._pending_appends.pop(pending_index)(pending_index)
        for listener in self.commit_listeners:
            listener(index)

    # -- snapshot install ------------------------------------------------------

    def set_snapshot(self, index: int, term: int,
                     data: bytes | None) -> None:
        """Owner took a state snapshot: the log up to ``index`` can compact
        (reference: snapshot → Raft compacts log up to snapshot index).
        ``data=None``: no stored fallback payload — installs are served only
        by the live ``snapshot_provider`` (durable-state mode), and when it
        declines, nothing is sent."""
        self.snapshot_index = index
        self.snapshot_term = term
        self._snapshot_bytes = data
        self.journal.compact(index + 1)

    def entry_term(self, index: int) -> int:
        """Term of the entry at ``index`` (snapshot boundary aware)."""
        return self._entry_term(index)

    def _send_snapshot(self, member: str) -> None:
        # throttle: a full snapshot per heartbeat per lagging follower is
        # O(snapshot bytes) of redundant work; resend only after a quiet period
        now = self.clock_millis()
        last_sent = self._snapshot_sent_ms.get(member, -ELECTION_TIMEOUT_MS)
        if now - last_sent < ELECTION_TIMEOUT_MS:
            return
        self._snapshot_sent_ms[member] = now
        import time as _time

        _repl_start = _time.perf_counter()
        self._m_snapshot_repl.inc()
        snap = None
        if self.snapshot_provider is not None:
            snap = self.snapshot_provider()
        if snap is None and self._snapshot_bytes is not None:
            snap = (self.snapshot_index, self.snapshot_term, self._snapshot_bytes)
        if snap is None:
            return
        index, term, data = snap
        for offset in range(0, max(len(data), 1), SNAPSHOT_CHUNK_BYTES):
            chunk = data[offset:offset + SNAPSHOT_CHUNK_BYTES]
            self._send(member, "snapshot", {
                "term": self.current_term, "leader": self.member_id,
                "index": index, "snapTerm": term,
                "offset": offset, "chunk": chunk,
                "done": offset + SNAPSHOT_CHUNK_BYTES >= len(data),
            })
        self._m_snapshot_repl_ms.observe((_time.perf_counter() - _repl_start) * 1000.0)

    def _on_install_snapshot(self, sender: str, req: dict) -> None:
        if req["term"] < self.current_term:
            return
        if req["term"] > self.current_term:
            self._set_term(req["term"])
        self._become(RaftRole.FOLLOWER)
        self.leader_id = req["leader"]
        self._last_heartbeat_ms = self.clock_millis()
        self._election_deadline_ms = self._next_election_deadline()
        if req["offset"] == 0:
            self._pending_snapshot = {"index": req["index"], "term": req["snapTerm"],
                                      "data": bytearray()}
        if self._pending_snapshot is None:
            return
        # continuity check: a dropped middle chunk must abort reassembly and
        # wait for a fresh offset-0 retransmit, never install torn bytes
        if (req["offset"] != len(self._pending_snapshot["data"])
                or req["index"] != self._pending_snapshot["index"]):
            self._pending_snapshot = None
            return
        self._pending_snapshot["data"] += req["chunk"]
        if req["done"]:
            snap = self._pending_snapshot
            self._pending_snapshot = None
            self.snapshot_index = snap["index"]
            self.snapshot_term = snap["term"]
            self._snapshot_bytes = bytes(snap["data"])
            self._reset_journal(snap["index"] + 1)
            self.commit_index = max(self.commit_index, snap["index"])
            if self.snapshot_receiver is not None:
                self.snapshot_receiver(bytes(snap["data"]))
            self._send(sender, "append-resp", {
                "term": self.current_term, "success": True,
                "lastIndex": snap["index"], "follower": self.member_id,
            })

    # -- helpers ---------------------------------------------------------------

    def _other_members(self) -> list[str]:
        return [m for m in self.members if m != self.member_id]

    def _quorum(self, count: int) -> bool:
        return count >= len(self.members) // 2 + 1

    def _set_term(self, term: int, vote_for: str | None = None) -> None:
        if term > self.current_term or vote_for is not None:
            self.current_term = term
            self.voted_for = vote_for
            self._store_meta()

    def _become(self, role: RaftRole) -> None:
        if self.role is role:
            return
        self.role = role
        self._m_role.set({RaftRole.LEADER: 3, RaftRole.CANDIDATE: 2}.get(role, 1))
        if role != RaftRole.LEADER:
            # a stepped-down leader must not emit leader_transition_latency
            # samples from follower-side commit advances
            self._leader_since_ms = None
        if role != RaftRole.LEADER:
            self._pending_appends.clear()
        for listener in self.role_listeners:
            listener(role, self.current_term)

    def _send(self, member: str, suffix: str, payload: dict) -> None:
        self._m_msg_send.labels(str(self.partition_id), suffix).inc()
        self.messaging.send(member, f"raft-{self.partition_id}-{suffix}", payload)

    # -- committed-entry reader (log storage integration) ----------------------

    def committed_entries(self, from_index: int) -> list[dict]:
        """Entries up to the commit index (application entries only carry data)."""
        out = []
        for rec in self.journal.read_from(from_index):
            if rec.index > self.commit_index:
                break
            entry = unpackb(rec.data)
            entry["index"] = rec.index
            out.append(entry)
        return out
