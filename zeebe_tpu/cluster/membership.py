"""SWIM-lite cluster membership with property gossip.

Reference: atomix/cluster/src/main/java/io/atomix/cluster/protocol/
SwimMembershipProtocol.java:67 — probe/suspect/alive states with incarnation
numbers, bootstrap member discovery (BootstrapDiscoveryProvider), and broadcast
of member properties (BrokerInfo rides these properties to the gateway,
gateway/impl/broker/BrokerTopologyManager).

Deterministic design: the protocol advances on explicit ``tick(now_millis)``
calls and reacts to delivered messages — no internal threads — so it runs
identically under the loopback test network and the TCP backend (driven by a
periodic timer there).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable

from zeebe_tpu.cluster.messaging import MessagingService

PROBE_TOPIC = "swim-probe"
ACK_TOPIC = "swim-ack"
GOSSIP_TOPIC = "swim-gossip"

PROBE_INTERVAL_MS = 1_000
SUSPECT_TIMEOUT_MS = 3_000
DEAD_TIMEOUT_MS = 10_000


class MemberState(enum.Enum):
    ALIVE = "alive"
    SUSPECT = "suspect"
    DEAD = "dead"


@dataclass
class Member:
    member_id: str
    state: MemberState = MemberState.ALIVE
    incarnation: int = 0
    properties: dict[str, Any] = field(default_factory=dict)
    last_heard_ms: int = 0


class MembershipService:
    """One instance per node; all nodes bootstrap from the same seed list."""

    def __init__(self, messaging: MessagingService, seed_members: list[str],
                 clock_millis: Callable[[], int]) -> None:
        self.messaging = messaging
        self.member_id = messaging.member_id
        self.clock_millis = clock_millis
        self.incarnation = 0
        from zeebe_tpu.utils.metrics import REGISTRY

        self._m_incarnation = REGISTRY.gauge(
            "smp_members_incarnation_number",
            "this member's SWIM incarnation number", ("member",)
        ).labels(self.member_id)
        self.properties: dict[str, Any] = {}
        self.members: dict[str, Member] = {
            m: Member(m, last_heard_ms=clock_millis()) for m in seed_members
        }
        self.members.setdefault(self.member_id, Member(self.member_id))
        self._listeners: list[Callable[[Member], None]] = []
        self._probe_rr = 0
        self._last_probe_ms = clock_millis()
        messaging.subscribe(PROBE_TOPIC, self._on_probe)
        messaging.subscribe(ACK_TOPIC, self._on_ack)
        messaging.subscribe(GOSSIP_TOPIC, self._on_gossip)

    # -- public API -----------------------------------------------------------

    def add_listener(self, listener: Callable[[Member], None]) -> None:
        self._listeners.append(listener)

    def set_property(self, key: str, value: Any) -> None:
        """Property changes bump the incarnation and gossip immediately
        (BrokerInfo updates propagate this way)."""
        self.properties[key] = value
        self.incarnation += 1
        self._m_incarnation.set(self.incarnation)
        self._broadcast_gossip()

    def alive_members(self) -> list[Member]:
        return [m for m in self.members.values() if m.state == MemberState.ALIVE]

    def get(self, member_id: str) -> Member | None:
        return self.members.get(member_id)

    # -- protocol -------------------------------------------------------------

    def tick(self, now_millis: int | None = None) -> None:
        now = self.clock_millis() if now_millis is None else now_millis
        if now - self._last_probe_ms >= PROBE_INTERVAL_MS:
            self._last_probe_ms = now
            self._probe_next(now)
        for member in self.members.values():
            if member.member_id == self.member_id:
                continue
            silent = now - member.last_heard_ms
            if member.state == MemberState.ALIVE and silent > SUSPECT_TIMEOUT_MS:
                self._transition(member, MemberState.SUSPECT)
            elif member.state == MemberState.SUSPECT and silent > DEAD_TIMEOUT_MS:
                self._transition(member, MemberState.DEAD)

    def _probe_next(self, now: int) -> None:
        others = sorted(m for m in self.members if m != self.member_id)
        if not others:
            return
        target = others[self._probe_rr % len(others)]
        self._probe_rr += 1
        self.messaging.send(target, PROBE_TOPIC, self._digest())

    def _digest(self) -> dict:
        return {
            "incarnation": self.incarnation,
            "properties": self.properties,
            "members": {
                m.member_id: {"state": m.state.value, "incarnation": m.incarnation}
                for m in self.members.values()
            },
        }

    def _on_probe(self, sender: str, payload: dict) -> None:
        self._heard_from(sender, payload)
        self.messaging.send(sender, ACK_TOPIC, self._digest())

    def _on_ack(self, sender: str, payload: dict) -> None:
        self._heard_from(sender, payload)

    def _on_gossip(self, sender: str, payload: dict) -> None:
        self._heard_from(sender, payload)

    def _heard_from(self, sender: str, digest: dict) -> None:
        now = self.clock_millis()
        member = self.members.setdefault(sender, Member(sender))
        member.last_heard_ms = now
        inc = digest.get("incarnation", 0)
        if inc >= member.incarnation:
            member.incarnation = inc
            member.properties = dict(digest.get("properties", {}))
        if member.state != MemberState.ALIVE:
            self._transition(member, MemberState.ALIVE)
        # refute rumors about ourselves with a higher incarnation (SWIM)
        rumored = digest.get("members", {}).get(self.member_id)
        if rumored and rumored.get("state") != MemberState.ALIVE.value:
            self.incarnation = max(self.incarnation, rumored.get("incarnation", 0)) + 1
            self._m_incarnation.set(self.incarnation)
            self._broadcast_gossip()

    def _broadcast_gossip(self) -> None:
        for m in self.members:
            if m != self.member_id:
                self.messaging.send(m, GOSSIP_TOPIC, self._digest())

    def _transition(self, member: Member, state: MemberState) -> None:
        if member.state is state:
            return
        member.state = state
        for listener in self._listeners:
            listener(member)
