"""Cluster messaging: topic-addressed request/reply between members.

Reference: atomix/cluster/src/main/java/io/atomix/cluster/messaging/impl/
NettyMessagingService.java — topic-addressed (`consume`/`send`) request/reply
over TCP. Two implementations:

- ``LoopbackNetwork``: in-process, deterministic, with drop/partition fault
  injection — the unit-test substrate (the reference tests Raft the same way,
  atomix/cluster/src/test with local transports).
- ``TcpMessagingService``: asyncio TCP with length-prefixed msgpack frames —
  the real multi-host backend (DCN path; ICI carries only in-kernel jax
  collectives, never these control messages).
"""

from __future__ import annotations

import asyncio
import dataclasses as _dataclasses
import logging
import re
import struct
import threading
from collections import deque
from typing import Any, Callable

from zeebe_tpu.protocol.msgpack import packb, unpackb

logger = logging.getLogger("zeebe_tpu.messaging")

# slow-client / zombie-client protection (ISSUE 11): a connected peer that
# stops reading must not wedge this process's send path or buffer frames
# without bound — once a connection's outbound transport buffer exceeds
# this, the connection is dropped (the peer reconnects when it recovers;
# Raft retries, the gateway resend loop re-sends) and a metric counts it
DEFAULT_MAX_OUTBOUND_BUFFER_BYTES = 8 * 1024 * 1024


def _max_outbound_buffer_bytes() -> int:
    import os

    try:
        return int(os.environ.get(
            "ZEEBE_BROKER_NETWORK_MAXOUTBOUNDBUFFERBYTES", ""))
    except ValueError:
        return DEFAULT_MAX_OUTBOUND_BUFFER_BYTES


from zeebe_tpu.utils.metrics import REGISTRY as _REG  # noqa: E402

_M_STREAM_OVERFLOW = _REG.counter(
    "messaging_stream_overflow_disconnects_total",
    "outbound connections dropped because the peer stopped reading and the "
    "buffered frames exceeded the per-stream bound (zombie-client "
    "protection)", ("peer",))

# a topic's first embedded integer is its partition id (raft-3-append,
# inter-partition-3, command-api-3, raft-reconfigure-3); control topics
# (swim-probe, gateway-response, …) carry none
_TOPIC_PARTITION = re.compile(r"(\d+)")

# handler(sender_id, payload) -> reply payload | None
Handler = Callable[[str, Any], Any]


class MessagingService:
    """Interface: subscribe to topics, send one-way messages."""

    member_id: str

    def subscribe(self, topic: str, handler: Handler) -> None:
        raise NotImplementedError

    def unsubscribe(self, topic: str) -> None:
        """Drop a topic's handler (stopping a partition replica must not
        leave handlers that dispatch into closed journals)."""
        raise NotImplementedError

    def send(self, member_id: str, topic: str, payload: Any) -> None:
        """Fire-and-forget (Raft piggybacks replies as separate messages)."""
        raise NotImplementedError


class LoopbackMessaging(MessagingService):
    def __init__(self, network: "LoopbackNetwork", member_id: str) -> None:
        self.network = network
        self.member_id = member_id
        self.handlers: dict[str, Handler] = {}

    def subscribe(self, topic: str, handler: Handler) -> None:
        self.handlers[topic] = handler

    def unsubscribe(self, topic: str) -> None:
        self.handlers.pop(topic, None)

    def send(self, member_id: str, topic: str, payload: Any) -> None:
        self.network.enqueue(self.member_id, member_id, topic, payload)


class LoopbackNetwork:
    """Deterministic in-process network with fault injection.

    Messages are queued and delivered only on ``deliver_all`` / ``deliver_one``
    so tests control interleaving exactly. ``partition(a, b)`` drops traffic
    between two members (both directions) until ``heal()``.

    With ``lanes=N`` the queue splits by partition: a topic's first embedded
    integer selects its lane (raft-3-append, command-api-3 → lane 3; topics
    with no partition id → the control lane 0), and ``deliver_lane`` drains
    one lane — the per-partition ownership threads' delivery path (each lane's
    handlers touch only that partition's state, so lanes never need a shared
    lock). ``lanes=0`` (default) keeps the single deterministic queue.
    """

    def __init__(self, lanes: int = 0) -> None:
        self.members: dict[str, LoopbackMessaging] = {}
        self.lanes = lanes
        self._queues: list[deque[tuple[str, str, str, Any]]] = [
            deque() for _ in range(lanes + 1)
        ]
        self._partitions: set[frozenset[str]] = set()
        self.dropped: int = 0
        self._handler_fail_logged: set[str] = set()

    @property
    def queue(self):
        """All pending messages (compat view; prefer per-lane delivery)."""
        if self.lanes == 0:
            return self._queues[0]
        return [m for q in self._queues for m in q]

    def lane_of(self, topic: str) -> int:
        if self.lanes == 0:
            return 0
        if topic.startswith("raft-reconfigure-done-"):
            # topology-plane confirmation: its handler mutates the topology
            # manager's state, which the control thread owns
            return 0
        m = _TOPIC_PARTITION.search(topic)
        if m is None:
            return 0
        lane = int(m.group(1))
        return lane if 1 <= lane <= self.lanes else 0

    def join(self, member_id: str) -> LoopbackMessaging:
        svc = LoopbackMessaging(self, member_id)
        self.members[member_id] = svc
        return svc

    def leave(self, member_id: str) -> None:
        """Remove a member (crashed broker): in-flight traffic to it drops
        like to a dead host, and its stale handlers can never dispatch into
        closed journals. A later ``join`` re-registers fresh handlers."""
        self.members.pop(member_id, None)

    # -- fault injection ------------------------------------------------------

    def partition(self, a: str, b: str) -> None:
        self._partitions.add(frozenset((a, b)))

    def isolate(self, member_id: str) -> None:
        for other in self.members:
            if other != member_id:
                self.partition(member_id, other)

    def heal(self, a: str | None = None, b: str | None = None) -> None:
        if a is None:
            self._partitions.clear()
        elif b is None:
            self._partitions = {p for p in self._partitions if a not in p}
        else:
            self._partitions.discard(frozenset((a, b)))

    def _blocked(self, a: str, b: str) -> bool:
        return frozenset((a, b)) in self._partitions

    # -- delivery -------------------------------------------------------------

    def enqueue(self, sender: str, target: str, topic: str, payload: Any) -> None:
        self._queues[self.lane_of(topic)].append((sender, target, topic, payload))

    def deliver_one(self, lane: int = 0) -> bool:
        q = self._queues[lane]
        if not q:
            return False
        try:
            sender, target, topic, payload = q.popleft()
        except IndexError:  # raced with another consumer of this lane
            return False
        if self._blocked(sender, target) or target not in self.members:
            self.dropped += 1
            return True
        handler = self.members[target].handlers.get(topic)
        if handler is not None:
            try:
                handler(sender, payload)
            except Exception:  # noqa: BLE001 — a crashed member whose handler
                # still dispatches into closed state loses the message (same
                # observable behavior as a real dead host); delivery to every
                # healthy member continues
                self.dropped += 1
                if target not in self._handler_fail_logged:
                    self._handler_fail_logged.add(target)
                    logger.exception(
                        "dropping message %s -> %s on topic %r: handler failed "
                        "(further drops for this member logged at debug)",
                        sender, target, topic,
                    )
                else:
                    logger.debug("dropping message %s -> %s on topic %r",
                                 sender, target, topic)
        return True

    def deliver_lane(self, lane: int, max_messages: int = 100_000) -> int:
        count = 0
        while self._queues[lane] and count < max_messages:
            if not self.deliver_one(lane):
                break
            count += 1
        return count

    def deliver_all(self, max_messages: int = 100_000) -> int:
        count = 0
        for lane in range(len(self._queues)):
            count += self.deliver_lane(lane, max_messages - count)
            if count >= max_messages:
                break
        return count


_FRAME = struct.Struct("<I")


@_dataclasses.dataclass
class TlsConfig:
    """Cluster-messaging TLS (reference: atomix Netty TLS — zeebe.broker.
    network.security.*): every member presents cert_file/key_file; with
    ca_file set, peers are verified against it in BOTH directions (mutual
    TLS). Hostname checks are off — cluster certs are per-node identities
    verified by the shared CA, not by DNS names."""

    cert_file: str
    key_file: str
    ca_file: str | None = None

    def server_context(self):
        if getattr(self, "_server_ctx", None) is None:
            self._server_ctx = self._build_server_context()
        return self._server_ctx

    def client_context(self):
        if getattr(self, "_client_ctx", None) is None:
            self._client_ctx = self._build_client_context()
        return self._client_ctx

    def _build_server_context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if self.ca_file:
            ctx.load_verify_locations(self.ca_file)
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx

    def _build_client_context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.load_cert_chain(self.cert_file, self.key_file)
        if self.ca_file:
            ctx.load_verify_locations(self.ca_file)
            ctx.verify_mode = ssl.CERT_REQUIRED
        else:
            ctx.verify_mode = ssl.CERT_NONE
        return ctx


class TcpMessagingService(MessagingService):
    """asyncio TCP messaging: one connection per peer, frames are
    ``len | msgpack{topic, sender, payload}`` (the NettyMessagingService
    protocol-v2 shape without the compression/TLS options).

    Thread model: the IO loop only *enqueues* received frames; the application
    thread dispatches them to handlers via ``poll()`` — so RaftNode /
    MembershipService state machines are mutated from exactly one thread,
    identical to the loopback network's ``deliver_all`` (single-writer per
    partition, the same discipline the reference enforces with actors)."""

    def __init__(self, member_id: str, bind: tuple[str, int],
                 peers: dict[str, tuple[str, int]],
                 tls: "TlsConfig | None" = None) -> None:
        self.member_id = member_id
        self.bind = bind
        self.peers = dict(peers)
        self.tls = tls
        self.handlers: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self._writers: dict[str, asyncio.StreamWriter] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._inbox: deque[tuple[str, str, Any]] = deque()
        self._inbox_lock = threading.Lock()
        # per-stream outbound bound: read once (env) so the send hot path
        # never touches os.environ
        self.max_outbound_buffer_bytes = _max_outbound_buffer_bytes()
        self.stream_overflow_disconnects = 0

    def subscribe(self, topic: str, handler: Handler) -> None:
        self.handlers[topic] = handler

    def unsubscribe(self, topic: str) -> None:
        self.handlers.pop(topic, None)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        """Run the event loop on a daemon thread (the host control plane;
        reference brokers likewise run messaging on dedicated Netty threads)."""
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"messaging-{self.member_id}")
        self._thread.start()
        self._started.wait(timeout=10)

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._serve())
        self._loop.run_forever()

    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.bind[0], self.bind[1],
            ssl=self.tls.server_context() if self.tls else None,
        )
        self._started.set()

    def stop(self) -> None:
        if self._loop is not None:
            # close cached outbound writers on the loop before stopping it:
            # a long-lived gateway process that cycles runtimes (tests, the
            # consistency harness) must not leak one fd per former peer
            def _close_writers() -> None:
                for writer in list(self._writers.values()):
                    try:
                        writer.close()
                    except Exception:  # noqa: BLE001 — already broken
                        pass
                self._writers.clear()
                self._loop.stop()

            self._loop.call_soon_threadsafe(_close_writers)
        if self._thread is not None:
            self._thread.join(timeout=5)

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                header = await reader.readexactly(_FRAME.size)
                (length,) = _FRAME.unpack(header)
                frame = unpackb(await reader.readexactly(length))
                with self._inbox_lock:
                    self._inbox.append(
                        (frame["topic"], frame["sender"], frame["payload"])
                    )
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()

    def poll(self, max_messages: int = 10_000) -> int:
        """Dispatch queued frames to handlers on the calling thread. Drive this
        from the same loop that calls tick() on the protocol state machines."""
        count = 0
        while count < max_messages:
            with self._inbox_lock:
                if not self._inbox:
                    break
                topic, sender, payload = self._inbox.popleft()
            handler = self.handlers.get(topic)
            if handler is not None:
                try:
                    handler(sender, payload)
                except Exception:  # noqa: BLE001 — a bad frame or a handler
                    # racing a closed component must not kill the pump thread
                    logger.exception("handler for %s failed", topic)
            count += 1
        return count

    async def _watch_peer(self, member_id: str, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        """Drain the outbound connection's read side until EOF/error (peers
        never send on it), then close and evict the writer so stale
        connections to a restarted peer are detected eagerly."""
        try:
            while await reader.read(65536):
                pass
        except Exception:  # noqa: BLE001 — any transport error = dead peer
            pass
        if self._writers.get(member_id) is writer:
            self._writers.pop(member_id, None)
        try:
            writer.close()
        except Exception:  # noqa: BLE001 — already broken
            pass

    def send(self, member_id: str, topic: str, payload: Any) -> None:
        if member_id == self.member_id:
            # self-delivery via the inbox, not TCP: a worker leading BOTH
            # sides of an inter-partition send (deployment distribution,
            # message correlation) addresses itself — it is never in its own
            # peers table, and the loopback network's self-delivery is the
            # semantics every caller was written against. Dropping these
            # silently stalled cross-partition distribution whenever two
            # partitions' leaderships landed on one worker.
            with self._inbox_lock:
                self._inbox.append((topic, member_id, payload))
            return
        if self._loop is None:
            raise RuntimeError("messaging not started")
        self._loop.call_soon_threadsafe(
            lambda: self._loop.create_task(self._send(member_id, topic, payload))
        )

    async def _send(self, member_id: str, topic: str, payload: Any) -> None:
        data = packb({"topic": topic, "sender": self.member_id, "payload": payload})
        # one reconnect retry: a cached writer to a RESTARTED peer (e.g. a
        # supervisor-respawned worker) only reveals its death on the first
        # write — without the retry that first message after every restart
        # was silently dropped, which a one-shot request path (gateway
        # submit) cannot absorb the way Raft's retries can
        for attempt in (0, 1):
            try:
                writer = self._writers.get(member_id)
                if writer is None or writer.is_closing():
                    if member_id not in self.peers:
                        return
                    host, port = self.peers[member_id]
                    reader, writer = await asyncio.open_connection(
                        host, port,
                        ssl=self.tls.client_context() if self.tls else None,
                    )
                    self._writers[member_id] = writer
                    # watch for peer EOF: a cleanly-died peer half-closes the
                    # socket, which does NOT make write()/drain() raise — the
                    # frame would vanish into the half-open connection and
                    # the reconnect retry below would never fire. Evicting
                    # the writer at EOF makes the NEXT send reconnect.
                    self._loop.create_task(
                        self._watch_peer(member_id, reader, writer))
                writer.write(_FRAME.pack(len(data)) + data)
                # NO drain(): a peer that stops reading (zombie client)
                # would park this task — and every later send's task —
                # forever while the transport buffer grows without bound.
                # Instead the buffer is checked against a hard per-stream
                # cap: past it the connection is aborted (frames dropped,
                # counted) and the peer gets a fresh connection when it
                # reads again. Write errors surface via the peer watcher's
                # EOF eviction + the reconnect retry above.
                if (writer.transport.get_write_buffer_size()
                        > self.max_outbound_buffer_bytes):
                    self.stream_overflow_disconnects += 1
                    _M_STREAM_OVERFLOW.labels(member_id).inc()
                    logger.warning(
                        "dropping outbound connection to %s: peer stopped "
                        "reading (%d bytes buffered > %d bound)",
                        member_id,
                        writer.transport.get_write_buffer_size(),
                        self.max_outbound_buffer_bytes)
                    if self._writers.get(member_id) is writer:
                        self._writers.pop(member_id, None)
                    writer.transport.abort()
                return
            except (ConnectionError, OSError):
                stale = self._writers.pop(member_id, None)
                if stale is not None:
                    try:  # release the dead transport's fd now, not at GC
                        stale.close()
                    except Exception:  # noqa: BLE001 — already broken
                        pass
                if attempt:  # peer really down: drop (Raft retries)
                    return
