"""Cluster substrate: membership, messaging, Raft consensus.

Reference: atomix/ (SURVEY §2.2) — SwimMembershipProtocol, NettyMessagingService,
RaftContext/roles. TPU-native re-design: the control plane is host-side Python
(asyncio TCP for real deployments, a deterministic loopback network for tests);
device-side data never rides this path — partitions replicate *logs*, and device
state is recomputed from the log (SURVEY §2.13 replication row).
"""

from zeebe_tpu.cluster.messaging import LoopbackNetwork, MessagingService, TcpMessagingService
from zeebe_tpu.cluster.membership import Member, MembershipService, MemberState
from zeebe_tpu.cluster.raft import RaftNode, RaftRole

__all__ = [
    "LoopbackNetwork",
    "MessagingService",
    "TcpMessagingService",
    "Member",
    "MemberState",
    "MembershipService",
    "RaftNode",
    "RaftRole",
]
