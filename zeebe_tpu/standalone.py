"""Standalone broker+gateway app (reference: dist/…/StandaloneBroker.java with
embedded gateway): boots a cluster runtime and serves the gRPC client API.

Two deployment shapes:

- in-process (default): N brokers in ONE process over the loopback network —
  the single-machine / dev shape.
  ``python -m zeebe_tpu.standalone --brokers 3 --partitions 3``

- multi-process over TCP: ONE broker per process; Raft, membership gossip,
  inter-partition commands, and gateway request routing all ride TCP
  (reference: a real deployed cluster of StandaloneBroker instances).
  ``python -m zeebe_tpu.standalone --node-id broker-0 \
       --bind 127.0.0.1:26601 \
       --contact broker-0=127.0.0.1:26601,broker-1=127.0.0.1:26602,... \
       --partitions 3 --replication 3 --port 26500 --data-dir /data/b0``
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def _gateway_oauth():
    """ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_* → OAuthValidator (mode
    `identity` enables the JWT interceptor; reference: gateway security
    authentication config + IdentityInterceptor)."""
    import os

    mode = os.environ.get("ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_MODE", "none")
    if mode != "identity":
        return None
    from zeebe_tpu.gateway.oauth import OAuthValidator, OAuthValidatorConfig

    secret = os.environ.get("ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_SECRET")
    if not secret:
        raise SystemExit(
            "ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_MODE=identity requires "
            "ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_SECRET")
    return OAuthValidator(OAuthValidatorConfig(
        mode="identity",
        secret=secret,
        audience=os.environ.get("ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_AUDIENCE"),
    ))



def _parse_contacts(spec: str) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}
    for part in spec.split(","):
        name, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        out[name.strip()] = (host.strip(), int(port))
    return out


def main(argv: list[str] | None = None) -> int:
    from zeebe_tpu.utils.zlogging import configure_logging

    # ZEEBE_LOG_APPENDER=stackdriver selects the JSON layout; ZEEBE_LOG_LEVEL
    # binds the zeebe_tpu logger hierarchy (reference: dist log4j2.xml)
    configure_logging()
    parser = argparse.ArgumentParser(prog="zeebe-tpu-broker")
    parser.add_argument("--port", type=int, default=26500)
    parser.add_argument("--partitions", type=int, default=1)
    parser.add_argument("--brokers", type=int, default=1)
    parser.add_argument("--replication", type=int, default=1)
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--management-port", type=int, default=0,
                        help="health/metrics/admin HTTP port (0 = disabled)")
    parser.add_argument("--node-id", default=None,
                        help="this broker's member id (enables the "
                             "multi-process TCP cluster mode)")
    parser.add_argument("--bind", default=None,
                        help="host:port for cluster TCP messaging")
    parser.add_argument("--contact", default=None,
                        help="comma-separated member=host:port initial "
                             "contact points (including this node)")
    args = parser.parse_args(argv)

    from zeebe_tpu.utils.xla_cache import enable_persistent_cache

    enable_persistent_cache()
    from zeebe_tpu.broker.config import load_broker_cfg
    from zeebe_tpu.gateway import ClusterRuntime, Gateway

    if args.node_id is not None:
        if not args.bind or not args.contact:
            parser.error("--node-id requires --bind and --contact")
        from zeebe_tpu.gateway.tcp_runtime import TcpClusterRuntime

        from zeebe_tpu.backup import backup_store_from_env

        host, port = args.bind.rsplit(":", 1)
        contacts = _parse_contacts(args.contact)
        peers = {m: a for m, a in contacts.items() if m != args.node_id}
        # cluster-messaging TLS (reference: zeebe.broker.network.security.*)
        tls = None
        import os as _os

        if _os.environ.get("ZEEBE_BROKER_NETWORK_SECURITY_ENABLED", "").lower() in (
                "1", "true", "yes"):
            from zeebe_tpu.cluster.messaging import TlsConfig

            cert = _os.environ.get("ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATECHAINPATH")
            key = _os.environ.get("ZEEBE_BROKER_NETWORK_SECURITY_PRIVATEKEYPATH")
            if not cert or not key:
                raise SystemExit(
                    "ZEEBE_BROKER_NETWORK_SECURITY_ENABLED requires "
                    "ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATECHAINPATH and "
                    "ZEEBE_BROKER_NETWORK_SECURITY_PRIVATEKEYPATH")
            tls = TlsConfig(
                cert_file=cert, key_file=key,
                ca_file=_os.environ.get(
                    "ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATEAUTHORITYPATH"),
            )
        from zeebe_tpu.utils.external_code import (
            exporters_factory_from_env,
            gateway_interceptors_from_env,
        )

        runtime = TcpClusterRuntime(
            args.node_id, (host, int(port)), peers, tls=tls,
            partition_count=args.partitions,
            replication_factor=args.replication,
            directory=args.data_dir,
            backup_store=backup_store_from_env(),
            kernel_backend=load_broker_cfg().base.kernel_backend,
            exporters_factory=exporters_factory_from_env(),
        )
        runtime.start()
        gateway = Gateway(runtime, bind=f"0.0.0.0:{args.port}",
                      oauth=_gateway_oauth(),
                      extra_interceptors=gateway_interceptors_from_env())
        gateway.start()
        print(f"[{args.node_id}] gateway on {gateway.address}, cluster bind "
              f"{args.bind}", file=sys.stderr, flush=True)
        management = None
        if args.management_port:
            from zeebe_tpu.broker.management import ManagementServer

            management = ManagementServer(
                runtime.broker, bind=("0.0.0.0", args.management_port),
            )
            management.start()
            print(f"management on :{management.port}", file=sys.stderr, flush=True)
        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        stop.wait()
        if management is not None:
            management.stop()
        gateway.stop()
        runtime.stop()
        return 0

    # ZEEBE_BROKER_* env vars bind first; explicit CLI flags override
    overrides = {}
    if "--partitions" in (argv or sys.argv):
        overrides["base.partition_count"] = args.partitions
    if "--replication" in (argv or sys.argv):
        overrides["base.replication_factor"] = args.replication
    from zeebe_tpu.backup import backup_store_from_env

    from zeebe_tpu.utils.external_code import (
        exporters_factory_from_env,
        gateway_interceptors_from_env,
    )

    cfg = load_broker_cfg(overrides=overrides)
    runtime = ClusterRuntime(
        backup_store=backup_store_from_env(),
        exporters_factory=exporters_factory_from_env(),
        kernel_backend=cfg.base.kernel_backend,
        broker_count=args.brokers,
        partition_count=(args.partitions if "base.partition_count" in overrides
                         else cfg.base.partition_count),
        replication_factor=(args.replication if "base.replication_factor" in overrides
                            else cfg.base.replication_factor),
        directory=args.data_dir,
        backpressure_algorithm=cfg.backpressure.algorithm,
        backpressure_enabled=cfg.backpressure.enabled,
        disk_min_free_bytes=(cfg.disk.min_free_bytes
                             if cfg.disk.enable_monitoring and args.data_dir else 0),
    )
    runtime.start()
    gateway = Gateway(runtime, bind=f"0.0.0.0:{args.port}",
                  oauth=_gateway_oauth(),
                  extra_interceptors=gateway_interceptors_from_env())
    gateway.start()
    print(f"gateway listening on {gateway.address} "
          f"({args.brokers} broker(s), {runtime.partition_count} partition(s))",
          file=sys.stderr)
    management = None
    if args.management_port:
        from zeebe_tpu.broker.management import ManagementServer

        management = ManagementServer(
            next(iter(runtime.brokers.values())),
            bind=("0.0.0.0", args.management_port),
            runtime=runtime,  # /cluster/status fans out over every broker
        )
        management.start()
        print(f"management on :{management.port}", file=sys.stderr)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    if management is not None:
        management.stop()
    gateway.stop()
    runtime.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
