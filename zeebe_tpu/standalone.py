"""Standalone broker+gateway app (reference: dist/…/StandaloneBroker.java with
embedded gateway): boots an in-process cluster runtime and serves the gRPC
client API.

Usage: python -m zeebe_tpu.standalone [--port 26500] [--partitions 3]
       [--brokers 1] [--replication 1] [--data-dir DIR]
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="zeebe-tpu-broker")
    parser.add_argument("--port", type=int, default=26500)
    parser.add_argument("--partitions", type=int, default=1)
    parser.add_argument("--brokers", type=int, default=1)
    parser.add_argument("--replication", type=int, default=1)
    parser.add_argument("--data-dir", default=None)
    args = parser.parse_args(argv)

    from zeebe_tpu.gateway import ClusterRuntime, Gateway

    runtime = ClusterRuntime(
        broker_count=args.brokers, partition_count=args.partitions,
        replication_factor=args.replication, directory=args.data_dir,
    )
    runtime.start()
    gateway = Gateway(runtime, bind=f"0.0.0.0:{args.port}")
    gateway.start()
    print(f"gateway listening on {gateway.address} "
          f"({args.brokers} broker(s), {args.partitions} partition(s), "
          f"replication {args.replication})", file=sys.stderr)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    gateway.stop()
    runtime.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
