"""Standalone broker+gateway app (reference: dist/…/StandaloneBroker.java with
embedded gateway): boots a cluster runtime and serves the gRPC client API.

Two deployment shapes:

- in-process (default): N brokers in ONE process over the loopback network —
  the single-machine / dev shape.
  ``python -m zeebe_tpu.standalone --brokers 3 --partitions 3``

- multi-process over TCP: ONE broker per process; Raft, membership gossip,
  inter-partition commands, and gateway request routing all ride TCP
  (reference: a real deployed cluster of StandaloneBroker instances).
  ``python -m zeebe_tpu.standalone --node-id broker-0 \
       --bind 127.0.0.1:26601 \
       --contact broker-0=127.0.0.1:26601,broker-1=127.0.0.1:26602,... \
       --partitions 3 --replication 3 --port 26500 --data-dir /data/b0``

- supervised per-core workers (ISSUE 7 scale-out shape): this process runs
  ONLY the gateway; a supervisor spawns one broker worker process per core
  (``zeebe_tpu/multiproc/``), partitions distribute round-robin over them,
  and crash-restarted workers recover via snapshots+replay.
  ``python -m zeebe_tpu.standalone --workers 8 --partitions 8 \
       --port 26500 --data-dir /data --management-port 9600``
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def _gateway_oauth():
    """ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_* → OAuthValidator (mode
    `identity` enables the JWT interceptor; reference: gateway security
    authentication config + IdentityInterceptor)."""
    import os

    mode = os.environ.get("ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_MODE", "none")
    if mode != "identity":
        return None
    from zeebe_tpu.gateway.oauth import OAuthValidator, OAuthValidatorConfig

    secret = os.environ.get("ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_SECRET")
    if not secret:
        raise SystemExit(
            "ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_MODE=identity requires "
            "ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_SECRET")
    return OAuthValidator(OAuthValidatorConfig(
        mode="identity",
        secret=secret,
        audience=os.environ.get("ZEEBE_GATEWAY_SECURITY_AUTHENTICATION_AUDIENCE"),
    ))



def _parse_contacts(spec: str) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}
    for part in spec.split(","):
        name, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        out[name.strip()] = (host.strip(), int(port))
    return out


def _free_ports(n: int) -> list[int]:
    """n distinct OS-assigned loopback ports (bound briefly, then released).

    Bind-then-release is racy by construction: another process can claim a
    port in the gap, which surfaces as the worker crash-looping on bind (see
    its worker.log) and boot failing at await_leaders. Acceptable for the
    single-operator single-host shape this mode targets; fixed ports via a
    real config are the answer when two clusters share a host."""
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _run_workers_mode(args) -> int:
    """``--workers N``: this process hosts ONLY the gateway (+ management);
    N broker worker processes are spawned and supervised, one per core
    (zeebe_tpu/multiproc/). Partitions distribute round-robin over the
    workers via the standard distribution; the client-visible surface
    (gRPC API, topology, /cluster/status) is unchanged."""
    from pathlib import Path

    from zeebe_tpu.gateway import Gateway
    from zeebe_tpu.multiproc import (
        MultiProcClusterRuntime,
        WorkerSpec,
        WorkerSupervisor,
    )
    from zeebe_tpu.multiproc.supervisor import worker_cmd
    from zeebe_tpu.utils.external_code import gateway_interceptors_from_env

    gateway_member = "gateway-0"
    worker_names = [f"worker-{i}" for i in range(args.workers)]
    ports = _free_ports(args.workers + 1)
    contacts = {m: ("127.0.0.1", p) for m, p in zip(worker_names, ports)}
    contacts[gateway_member] = ("127.0.0.1", ports[-1])
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))
    specs = []
    for name in worker_names:
        data_dir = (str(Path(args.data_dir) / name)
                    if args.data_dir else None)
        specs.append(WorkerSpec(
            node_id=name,
            cmd=worker_cmd(
                name, f"127.0.0.1:{contacts[name][1]}", contact_str,
                gateway_member, args.partitions, args.replication,
                data_dir=data_dir),
            data_dir=data_dir,
        ))
    supervisor = WorkerSupervisor(specs)
    runtime = MultiProcClusterRuntime(
        gateway_member,
        {m: a for m, a in contacts.items() if m != gateway_member},
        partition_count=args.partitions,
        replication_factor=args.replication,
        bind=contacts[gateway_member],
        supervisor=supervisor,
    )
    # signal handlers BEFORE anything spawns: a SIGTERM during the (long —
    # probe deadline + jax import) boot window must run the teardown below,
    # not the default action that would orphan the detached workers
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    gateway = None
    management = None
    try:
        # runtime.start() spawns the workers (via the supervisor) — it must
        # sit INSIDE the teardown scope: a thread-start failure after the
        # spawn would otherwise orphan the detached worker processes
        runtime.start()
        # worker boot pays the killable device probe BEFORE binding
        # messaging (up to ZEEBE_PROBE_TIMEOUT_S on a wedged host), then
        # jax import + broker recovery: budget for all of it, in short
        # slices so a stop signal interrupts the wait
        import time as _time

        from zeebe_tpu.utils.backend_probe import probe_timeout_secs

        boot_deadline = _time.monotonic() + probe_timeout_secs() + 120.0
        while not stop.is_set():
            try:
                runtime.await_leaders(timeout_s=2.0)
                break
            except RuntimeError:
                if _time.monotonic() >= boot_deadline:
                    raise
        if stop.is_set():
            raise SystemExit(143)  # stopped during boot: teardown below
        gateway = Gateway(runtime, bind=f"0.0.0.0:{args.port}",
                          oauth=_gateway_oauth(),
                          extra_interceptors=gateway_interceptors_from_env())
        gateway.start()
        print(f"gateway listening on {gateway.address} "
              f"({args.workers} worker process(es), {args.partitions} "
              f"partition(s))", file=sys.stderr, flush=True)
        if args.management_port:
            from zeebe_tpu.broker.management import ManagementServer

            management = ManagementServer(
                None, bind=("0.0.0.0", args.management_port), runtime=runtime)
            management.start()
            print(f"management on :{management.port}", file=sys.stderr,
                  flush=True)
    except BaseException:
        # ANY boot failure (leader timeout, gateway/management port in use)
        # must tear the supervisor down: the workers are detached processes
        # (start_new_session) and would otherwise outlive the failed boot
        if management is not None:
            management.stop()
        if gateway is not None:
            gateway.stop()
        runtime.stop()  # stops the supervisor (SIGTERM→SIGKILL) too
        raise
    stop.wait()
    # shutdown must reach runtime.stop() even if a front-end stop raises:
    # the workers are detached processes and only the supervisor (stopped
    # by runtime.stop) can tear them down
    try:
        if management is not None:
            management.stop()
    finally:
        try:
            gateway.stop()
        finally:
            runtime.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    from zeebe_tpu.utils.zlogging import configure_logging

    # ZEEBE_LOG_APPENDER=stackdriver selects the JSON layout; ZEEBE_LOG_LEVEL
    # binds the zeebe_tpu logger hierarchy (reference: dist log4j2.xml)
    configure_logging()
    parser = argparse.ArgumentParser(prog="zeebe-tpu-broker")
    parser.add_argument("--port", type=int, default=26500)
    parser.add_argument("--partitions", type=int, default=1)
    parser.add_argument("--brokers", type=int, default=1)
    parser.add_argument("--replication", type=int, default=1)
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--management-port", type=int, default=0,
                        help="health/metrics/admin HTTP port (0 = disabled)")
    parser.add_argument("--workers", type=int, default=0,
                        help="spawn N supervised broker worker processes "
                             "(one per core) behind this gateway process "
                             "(0 = host brokers in-process)")
    parser.add_argument("--node-id", default=None,
                        help="this broker's member id (enables the "
                             "multi-process TCP cluster mode)")
    parser.add_argument("--bind", default=None,
                        help="host:port for cluster TCP messaging")
    parser.add_argument("--contact", default=None,
                        help="comma-separated member=host:port initial "
                             "contact points (including this node)")
    args = parser.parse_args(argv)

    from zeebe_tpu.utils.xla_cache import enable_persistent_cache

    enable_persistent_cache()
    from zeebe_tpu.broker.config import load_broker_cfg
    from zeebe_tpu.gateway import ClusterRuntime, Gateway

    if args.workers > 0:
        return _run_workers_mode(args)

    if args.node_id is not None:
        if not args.bind or not args.contact:
            parser.error("--node-id requires --bind and --contact")
        from zeebe_tpu.gateway.tcp_runtime import TcpClusterRuntime

        from zeebe_tpu.backup import backup_store_from_env

        host, port = args.bind.rsplit(":", 1)
        contacts = _parse_contacts(args.contact)
        peers = {m: a for m, a in contacts.items() if m != args.node_id}
        # cluster-messaging TLS (reference: zeebe.broker.network.security.*)
        tls = None
        import os as _os

        if _os.environ.get("ZEEBE_BROKER_NETWORK_SECURITY_ENABLED", "").lower() in (
                "1", "true", "yes"):
            from zeebe_tpu.cluster.messaging import TlsConfig

            cert = _os.environ.get("ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATECHAINPATH")
            key = _os.environ.get("ZEEBE_BROKER_NETWORK_SECURITY_PRIVATEKEYPATH")
            if not cert or not key:
                raise SystemExit(
                    "ZEEBE_BROKER_NETWORK_SECURITY_ENABLED requires "
                    "ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATECHAINPATH and "
                    "ZEEBE_BROKER_NETWORK_SECURITY_PRIVATEKEYPATH")
            tls = TlsConfig(
                cert_file=cert, key_file=key,
                ca_file=_os.environ.get(
                    "ZEEBE_BROKER_NETWORK_SECURITY_CERTIFICATEAUTHORITYPATH"),
            )
        from zeebe_tpu.utils.external_code import (
            exporters_factory_from_env,
            gateway_interceptors_from_env,
        )

        runtime = TcpClusterRuntime(
            args.node_id, (host, int(port)), peers, tls=tls,
            partition_count=args.partitions,
            replication_factor=args.replication,
            directory=args.data_dir,
            backup_store=backup_store_from_env(),
            kernel_backend=load_broker_cfg().base.kernel_backend,
            exporters_factory=exporters_factory_from_env(),
        )
        runtime.start()
        gateway = Gateway(runtime, bind=f"0.0.0.0:{args.port}",
                      oauth=_gateway_oauth(),
                      extra_interceptors=gateway_interceptors_from_env())
        gateway.start()
        print(f"[{args.node_id}] gateway on {gateway.address}, cluster bind "
              f"{args.bind}", file=sys.stderr, flush=True)
        management = None
        if args.management_port:
            from zeebe_tpu.broker.management import ManagementServer

            management = ManagementServer(
                runtime.broker, bind=("0.0.0.0", args.management_port),
            )
            management.start()
            print(f"management on :{management.port}", file=sys.stderr, flush=True)
        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        stop.wait()
        if management is not None:
            management.stop()
        gateway.stop()
        runtime.stop()
        return 0

    # ZEEBE_BROKER_* env vars bind first; explicit CLI flags override
    overrides = {}
    if "--partitions" in (argv or sys.argv):
        overrides["base.partition_count"] = args.partitions
    if "--replication" in (argv or sys.argv):
        overrides["base.replication_factor"] = args.replication
    from zeebe_tpu.backup import backup_store_from_env

    from zeebe_tpu.utils.external_code import (
        exporters_factory_from_env,
        gateway_interceptors_from_env,
    )

    cfg = load_broker_cfg(overrides=overrides)
    runtime = ClusterRuntime(
        backup_store=backup_store_from_env(),
        exporters_factory=exporters_factory_from_env(),
        kernel_backend=cfg.base.kernel_backend,
        broker_count=args.brokers,
        partition_count=(args.partitions if "base.partition_count" in overrides
                         else cfg.base.partition_count),
        replication_factor=(args.replication if "base.replication_factor" in overrides
                            else cfg.base.replication_factor),
        directory=args.data_dir,
        backpressure_algorithm=cfg.backpressure.algorithm,
        backpressure_enabled=cfg.backpressure.enabled,
        disk_min_free_bytes=(cfg.disk.min_free_bytes
                             if cfg.disk.enable_monitoring and args.data_dir else 0),
    )
    runtime.start()
    gateway = Gateway(runtime, bind=f"0.0.0.0:{args.port}",
                  oauth=_gateway_oauth(),
                  extra_interceptors=gateway_interceptors_from_env())
    gateway.start()
    print(f"gateway listening on {gateway.address} "
          f"({args.brokers} broker(s), {runtime.partition_count} partition(s))",
          file=sys.stderr)
    management = None
    if args.management_port:
        from zeebe_tpu.broker.management import ManagementServer

        management = ManagementServer(
            next(iter(runtime.brokers.values())),
            bind=("0.0.0.0", args.management_port),
            runtime=runtime,  # /cluster/status fans out over every broker
        )
        management.start()
        print(f"management on :{management.port}", file=sys.stderr)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    if management is not None:
        management.stop()
    gateway.stop()
    runtime.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
