"""Standalone broker+gateway app (reference: dist/…/StandaloneBroker.java with
embedded gateway): boots an in-process cluster runtime and serves the gRPC
client API.

Usage: python -m zeebe_tpu.standalone [--port 26500] [--partitions 3]
       [--brokers 1] [--replication 1] [--data-dir DIR]
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="zeebe-tpu-broker")
    parser.add_argument("--port", type=int, default=26500)
    parser.add_argument("--partitions", type=int, default=1)
    parser.add_argument("--brokers", type=int, default=1)
    parser.add_argument("--replication", type=int, default=1)
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--management-port", type=int, default=0,
                        help="health/metrics/admin HTTP port (0 = disabled)")
    args = parser.parse_args(argv)

    from zeebe_tpu.broker.config import load_broker_cfg
    from zeebe_tpu.gateway import ClusterRuntime, Gateway

    # ZEEBE_BROKER_* env vars bind first; explicit CLI flags override
    overrides = {}
    if "--partitions" in (argv or sys.argv):
        overrides["base.partition_count"] = args.partitions
    if "--replication" in (argv or sys.argv):
        overrides["base.replication_factor"] = args.replication
    cfg = load_broker_cfg(overrides=overrides)
    runtime = ClusterRuntime(
        broker_count=args.brokers,
        partition_count=(args.partitions if "base.partition_count" in overrides
                         else cfg.base.partition_count),
        replication_factor=(args.replication if "base.replication_factor" in overrides
                            else cfg.base.replication_factor),
        directory=args.data_dir,
        backpressure_algorithm=cfg.backpressure.algorithm,
        backpressure_enabled=cfg.backpressure.enabled,
        disk_min_free_bytes=(cfg.disk.min_free_bytes
                             if cfg.disk.enable_monitoring and args.data_dir else 0),
    )
    runtime.start()
    gateway = Gateway(runtime, bind=f"0.0.0.0:{args.port}")
    gateway.start()
    print(f"gateway listening on {gateway.address} "
          f"({args.brokers} broker(s), {runtime.partition_count} partition(s))",
          file=sys.stderr)
    management = None
    if args.management_port:
        from zeebe_tpu.broker.management import ManagementServer

        management = ManagementServer(
            next(iter(runtime.brokers.values())),
            bind=("0.0.0.0", args.management_port),
        )
        management.start()
        print(f"management on :{management.port}", file=sys.stderr)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    if management is not None:
        management.stop()
    gateway.stop()
    runtime.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
