"""Worker process: one Broker behind the multi-process gateway.

Reference: a deployed StandaloneBroker instance minus the embedded gateway —
the broker, its partitions, Raft/SWIM over TCP messaging, and a management
port. The gateway-facing protocol on top:

- ``mp-client-command-<partition>``: client command ingress. The envelope
  carries the serialized record plus the gateway request id (trace
  satellite: the id that annotates lineage roots), and — unlike the
  raw ``command-api`` topic — replies with a typed ERROR frame on
  backpressure / not-leader / paused, so the gateway can surface
  RESOURCE_EXHAUSTED vs retry instead of timing out blind.
- ``gateway-response``: processing results routed back to the ORIGIN gateway
  by the record's ``request_stream_id`` (index into the sorted member list,
  gateways included — the reference does the same with gateway stream ids
  over atomix messaging). The reply carries the command's position so the
  gateway can mint its root span with the SAME trace id
  (``partition:position``) the worker-side spans use.
- ``worker-status``: periodic (and on-role-change) status push to every
  gateway: the same per-broker row ``/cluster/status`` aggregates in-process
  (health, roles, rates, firing alerts) plus worker pid and the partitions'
  last-recovery records — a supervisor-restarted worker's PR 6 recovery
  accounting is visible on the gateway's ``/cluster/status`` without an
  extra HTTP hop.
- ``jobs-available``: long-poll/stream wakeups forwarded to the gateways.

``WorkerRuntime`` is messaging-injectable (tests drive a gateway runtime and
a worker over the deterministic loopback network in one process); ``main()``
is the real process entry (``python -m zeebe_tpu.multiproc.worker``).
"""

from __future__ import annotations

import os
import sys
import threading
import time

from zeebe_tpu.protocol import Record

CLIENT_COMMAND_TOPIC = "mp-client-command"  # + "-<partition id>"
GATEWAY_RESPONSE_TOPIC = "mp-gateway-response"
WORKER_STATUS_TOPIC = "mp-worker-status"
JOBS_AVAILABLE_TOPIC = "mp-jobs-available"

#: bound on the request-id → command-position map (responses normally pop
#: their entry; a request whose gateway timed out never will — the oldest
#: entries are evicted past this, keeping dedupe live for recent traffic)
_MAX_INFLIGHT = 65536


class WorkerRuntime:
    """One broker + the gateway-facing protocol, pump-driven."""

    def __init__(self, node_id: str, messaging, gateway_members: list[str],
                 cfg, directory=None, status_interval_ms: int = 1000,
                 coalesce_window_ms: float = 0.0,
                 **broker_kwargs) -> None:
        from zeebe_tpu.broker import Broker

        self.node_id = node_id
        self.messaging = messaging
        self.gateway_members = list(gateway_members)
        # response routing table: request_stream_id indexes this list — the
        # gateway computes the SAME sorted union, so indices agree without a
        # handshake
        self._route_members = sorted(
            set(cfg.cluster_members) | set(gateway_members))
        self.broker = Broker(
            cfg, messaging, directory=directory,
            response_sink=self._on_processing_response, **broker_kwargs)
        self.broker.jobs_listener = self._on_jobs_available
        # idempotent ingress for a LIVE worker: the gateway RESENDS an
        # unanswered envelope (e.g. its first send raced this worker's
        # restart); appending it twice would duplicate the command, so
        # remember what was appended (in flight) and replay the reply for
        # what was already answered. Keys are (gateway, request id) — two
        # gateways booted in the same millisecond derive the same request-id
        # nonce, and a bare-id collision would drop one's command or replay
        # the other's reply to it. Both maps are bounded LRU (a request
        # whose gateway timed out never gets a response and would leak its
        # in-flight entry forever — evicting the OLDEST keeps dedupe live
        # for everything recent instead of silently turning off at a cap).
        # These in-memory maps are only the FAST path now: a crash between
        # append and reply loses them, but ingress falls back to the
        # partition's replicated dedupe (pending-request window rebuilt from
        # the log at leader transitions + the REQUEST_DEDUPE column family
        # materialized on processing and replay — state/request_dedupe.py),
        # so a gateway resend to the restarted worker or the new leader
        # yields exactly one appended command (ISSUE 9).
        from collections import OrderedDict

        self._inflight_positions: OrderedDict[tuple, int] = OrderedDict()
        self._recent_replies: OrderedDict[tuple, dict] = OrderedDict()
        # tenant-aware admission in front of the per-partition backpressure
        # limiters (ISSUE 11): the worker's own gate — a multi-gateway
        # deployment cannot rely on any single gateway's buckets. Sheds are
        # typed `resource-exhausted` frames (the gateway maps them to
        # RESOURCE_EXHAUSTED) and the shed ladder's feedback signal is the
        # observed append→reply latency, read back through the broker's
        # time-series store when the metrics plane is on (signal latency =
        # one sampler tick) or the controller's own window otherwise.
        from zeebe_tpu.gateway.admission import AdmissionCfg, AdmissionController

        self.admission = AdmissionController(
            AdmissionCfg.from_env(), node_id=node_id,
            clock_millis=lambda: float(self.broker.clock_millis()),
            flight=self.broker.flight_recorder,
            max_inflight_fn=self._admission_window,
            p99_source=self._store_p99)
        self._inflight_tenants: OrderedDict[tuple, tuple[str, int]] = \
            OrderedDict()
        # ingress batch-coalescing window (ISSUE 12): with window > 0,
        # admitted client commands queue per partition and append as ONE
        # raft batch when the window elapses (or the batch cap fills) —
        # one fsync + one replication round instead of N. 0 keeps the
        # legacy append-per-frame byte path exactly. The static value
        # comes from ZEEBE_BROKER_PROCESSING_COALESCEWINDOWMS; at runtime
        # the ingress-coalescing controller's actuator owns this knob.
        self.coalesce_window_ms = float(coalesce_window_ms)
        self.coalesce_max_batch = 128
        self._ingress_pending: dict[int, list[dict]] = {}
        self._ingress_first_ms: dict[int, float] = {}
        self._queued_ingress_keys: set[tuple] = set()
        if self.broker.control is not None:
            # the coalescing knob lives at THIS ingress seam, so the worker
            # (not the bare broker) wires its loop; the admission shed
            # ladder registers as a read-only aggregated loop so `cli top`
            # CONTROL shows every closed loop in one place
            self.broker.control.add_coalescing_controller(
                lambda: self.coalesce_window_ms,
                self._set_coalesce_window,
                static_ms=self.coalesce_window_ms)
            self.broker.control.register_loop(
                "admission-shed-ladder", self._admission_loop_snapshot)
        # chaos seam (ISSUE 9): crash THIS process between a successful
        # append and its reply after N ingress appends — one-shot per data
        # dir (a marker file disarms it after the restart), letting the
        # consistency harness pin the crash-between-append-and-reply →
        # resend → dedupe sequence deterministically
        self._crash_after_appends: int | None = None
        self._crash_marker = None
        crash_spec = os.environ.get("ZEEBE_CHAOS_CRASH_AFTER_APPENDS")
        if crash_spec and directory is not None:
            from pathlib import Path

            try:
                count = int(crash_spec)
            except ValueError:
                count = 0
            marker = Path(directory) / "chaos-crash-after-append.done"
            if count > 0 and not marker.exists():
                self._crash_after_appends = count
                self._crash_marker = marker
        self._status_interval_ms = status_interval_ms
        self._last_status_ms = 0
        self._last_roles: dict[str, str] = {}
        for pid in range(1, cfg.partition_count + 1):
            messaging.subscribe(
                f"{CLIENT_COMMAND_TOPIC}-{pid}",
                lambda s, p, pid=pid: self._on_client_command(pid, s, p))

    # -- admission plumbing ----------------------------------------------------

    def _admission_window(self) -> int:
        """The weighted-fair share's window: the sum of the LEADER
        partitions' current adaptive backpressure limits — admission sits
        exactly in front of the limiters, so its window is theirs."""
        total = 0
        for partition in self.broker.partitions.values():
            if partition.is_leader and partition.limiter is not None:
                total += partition.limiter.limit
        return total

    def _store_p99(self) -> float | None:
        """Shed signal from the Gorilla plane: the sampler distills the
        controller's own ack-latency histogram into a retained ``:p99``
        series; a stale sample (idle broker, sampler off) yields None so
        the controller falls back to its in-process window."""
        store = getattr(self.broker, "timeseries", None)
        if store is None:
            return None
        now_ms = self.broker.clock_millis()
        values = [entry["value"]
                  for entry in store.latest("zeebe_admission_ack_latency_ms:p99")
                  if self.node_id in entry["labels"]
                  and now_ms - entry["t"] <= 15_000]
        return max(values) if values else None

    def _release_admission(self, dedupe_key: tuple,
                           observe: bool = True) -> None:
        entry = self._inflight_tenants.pop(dedupe_key, None)
        if entry is not None:
            tenant, t0 = entry
            latency = float(self.broker.clock_millis() - t0) if observe \
                else None
            self.admission.release(tenant, latency_ms=latency)

    # -- command ingress -------------------------------------------------------

    def _reply_error(self, gateway: str, request_id: int, kind: str,
                     message: str) -> None:
        self.messaging.send(gateway, GATEWAY_RESPONSE_TOPIC, {
            "requestId": request_id,
            "error": {"type": kind, "message": message},
        })

    def _on_client_command(self, partition_id: int, sender: str,
                           payload: dict) -> None:
        from zeebe_tpu.broker.partition import BackpressureExceeded

        record = Record.from_bytes(payload["record"])
        request_id = payload.get("requestId", record.request_id)
        dedupe_key = (sender, request_id)
        if dedupe_key in self._inflight_positions:
            return  # duplicate resend: already appended, reply is coming
        if dedupe_key in self._queued_ingress_keys:
            return  # duplicate resend: queued in the coalescing window
        replay = self._recent_replies.get(dedupe_key)
        if replay is not None:
            self.messaging.send(sender, GATEWAY_RESPONSE_TOPIC, replay)
            return  # duplicate resend of an already-answered request
        partition = self.broker.partitions.get(partition_id)
        if partition is None or not partition.is_leader:
            # the worker did NOT append: the gateway may safely re-route
            self._reply_error(sender, request_id, "not-leader",
                              f"{self.node_id} does not lead partition "
                              f"{partition_id}")
            return
        if not partition.ready_for_ingress:
            # leader mid-recovery (replay barrier / startup replay): its
            # replicated dedupe window is not complete yet, so appending now
            # could duplicate a command this very log already carries. We
            # did NOT append — the gateway retries until recovery finishes.
            self._reply_error(sender, request_id, "unavailable",
                              f"partition {partition_id} leader is "
                              f"recovering")
            return
        # replicated dedupe (ISSUE 9): the in-memory maps above die with the
        # process; this consult survives crashes because the table is
        # materialized from the replicated log on processing AND replay —
        # the resend after a crash-between-append-and-reply lands here
        hit = partition.lookup_request(record.request_stream_id, request_id)
        if hit is not None:
            kind, entry = hit
            if kind == "replied":
                reply = {
                    "requestId": request_id,
                    "record": entry["f"],
                    "commandPosition": entry["c"],
                    "dedupe": "replayed",
                }
                self._recent_replies[dedupe_key] = reply
                while len(self._recent_replies) > 4096:
                    self._recent_replies.popitem(last=False)
                self.messaging.send(sender, GATEWAY_RESPONSE_TOPIC, reply)
                return
            # appended (or processed-awaiting, e.g. await-result): do NOT
            # append again; processing answers it through the normal reply
            # path. Backfill the in-flight map so that reply carries the
            # original command position.
            self._inflight_positions[dedupe_key] = entry["c"]
            while len(self._inflight_positions) > _MAX_INFLIGHT:
                self._inflight_positions.popitem(last=False)
            return
        # tenant admission (ISSUE 11) — AFTER the dedupe consults (a resend
        # of an already-appended request must reach its stored answer, not
        # a shed) and BEFORE the partition limiter, so one hot tenant
        # exhausts its own share instead of the whole in-flight window
        shed_reason, tenant, _priority = self.admission.try_admit(record)
        if shed_reason is not None:
            self._reply_error(
                sender, request_id, "resource-exhausted",
                f"admission shed ({shed_reason}): tenant {tenant!r} on "
                f"partition {partition_id} (shed level "
                f"{self.admission.shed_level})")
            return
        entry = {"sender": sender, "requestId": request_id,
                 "key": dedupe_key, "record": record, "tenant": tenant,
                 "enqMs": self.broker.clock_millis()}
        if self.coalesce_window_ms > 0:
            # batch-coalescing window (ISSUE 12): queue the ADMITTED
            # command; the pump flushes the partition's queue as one raft
            # batch when the window elapses or the batch cap fills
            queue = self._ingress_pending.setdefault(partition_id, [])
            if not queue:
                self._ingress_first_ms[partition_id] = float(entry["enqMs"])
            queue.append(entry)
            self._queued_ingress_keys.add(dedupe_key)
            if len(queue) >= self.coalesce_max_batch:
                self._flush_ingress_partition(partition_id)
            return
        try:
            position = partition.client_write(record)
        except BackpressureExceeded as exc:
            self.admission.release(tenant)
            self._reply_error(sender, request_id, "backpressure", str(exc))
            return
        except OSError as exc:
            # storage fault under the append (ISSUE 14): nothing was acked
            # — we did NOT durably append, so the gateway may retry; the
            # journal/raft layers own the repair
            self.admission.release(tenant)
            self._reply_error(sender, request_id, "unavailable",
                              f"storage fault on partition {partition_id}: "
                              f"{type(exc).__name__}")
            return
        if position is None:
            self.admission.release(tenant)
            self._reply_error(sender, request_id, "unavailable",
                              f"partition {partition_id} paused or disk-paused")
            return
        self._note_appended(entry, partition_id, position, partition)

    def _note_appended(self, entry: dict, partition_id: int, position: int,
                       partition) -> None:
        """Post-append bookkeeping shared by the direct and coalesced
        ingress paths: chaos seam, dedupe/in-flight maps, admission t0,
        and the cross-process ingress span."""
        from zeebe_tpu.observability.tracer import get_tracer

        self._maybe_chaos_crash(partition)
        dedupe_key = entry["key"]
        self._inflight_positions[dedupe_key] = position
        while len(self._inflight_positions) > _MAX_INFLIGHT:
            self._inflight_positions.popitem(last=False)
        # latency t0 is the ENQUEUE time: the coalescing window's own
        # delay must count against the shed ladder's ack-latency signal
        self._inflight_tenants[dedupe_key] = (entry["tenant"],
                                              entry["enqMs"])
        while len(self._inflight_tenants) > _MAX_INFLIGHT:
            # evicted entries (gateway gave up; no reply will come) still
            # release their in-flight slot — a leak here would slowly
            # starve the tenant's fair share
            stale_key = next(iter(self._inflight_tenants))
            self._release_admission(stale_key, observe=False)
        tracer = get_tracer()
        if tracer.enabled:
            # cross-process Dapper discipline: the trace id is DERIVED
            # (partition:position), identical on both sides of the process
            # boundary; this span records where the command crossed it
            trace_id = f"{partition_id}:{position}"
            if tracer.sampled(trace_id):
                tracer.emit(trace_id, "gateway.ingress", 0.0, partition_id,
                            attrs={"requestId": entry["requestId"],
                                   "gateway": entry["sender"],
                                   "worker": self.node_id,
                                   "workerPid": os.getpid()})
                # coalesce-window wait: enqueue→append, ms-clock resolution
                # (the window itself is ms-scale). The direct path appends
                # within the same millisecond and emits nothing — the span
                # set records the wait only where a wait existed.
                wait_ms = self.broker.clock_millis() - entry["enqMs"]
                if wait_ms > 0:
                    tracer.emit(trace_id, "gateway.coalesce_wait",
                                wait_ms / 1000.0, partition_id,
                                parent="gateway.ingress",
                                attrs={"windowMs": self.coalesce_window_ms})

    def _flush_due_ingress(self) -> int:
        """Flush every partition queue whose coalescing window elapsed (a
        shrunken window — the controller narrowing it — flushes on the
        next pump round)."""
        now = float(self.broker.clock_millis())
        flushed = 0
        for pid in list(self._ingress_pending):
            if (now - self._ingress_first_ms.get(pid, now)
                    >= self.coalesce_window_ms):
                flushed += self._flush_ingress_partition(pid)
        return flushed

    def _flush_ingress_partition(self, partition_id: int) -> int:
        """Append one partition's queued commands as ONE raft batch, then
        run the per-record bookkeeping / typed error replies."""
        entries = self._ingress_pending.pop(partition_id, [])
        self._ingress_first_ms.pop(partition_id, None)
        if not entries:
            return 0
        for entry in entries:
            self._queued_ingress_keys.discard(entry["key"])
        partition = self.broker.partitions.get(partition_id)
        if partition is None or not partition.is_leader:
            # leadership moved inside the window: nothing was appended, so
            # the gateway may safely re-route the same request ids
            for entry in entries:
                self.admission.release(entry["tenant"])
                self._reply_error(entry["sender"], entry["requestId"],
                                  "not-leader",
                                  f"{self.node_id} no longer leads "
                                  f"partition {partition_id}")
            return 0
        if not partition.ready_for_ingress:
            for entry in entries:
                self.admission.release(entry["tenant"])
                self._reply_error(entry["sender"], entry["requestId"],
                                  "unavailable",
                                  f"partition {partition_id} leader is "
                                  f"recovering")
            return 0
        try:
            results = partition.client_write_batch(
                [entry["record"] for entry in entries])
        except OSError as exc:
            # storage fault under the batched append (ISSUE 14): nothing
            # was acked; typed unavailable, gateway retries
            for entry in entries:
                self.admission.release(entry["tenant"])
                self._reply_error(entry["sender"], entry["requestId"],
                                  "unavailable",
                                  f"storage fault on partition "
                                  f"{partition_id}: {type(exc).__name__}")
            return 0
        for entry, (status, position) in zip(entries, results):
            if status == "ok":
                self._note_appended(entry, partition_id, position, partition)
            elif status == "backpressure":
                self.admission.release(entry["tenant"])
                self._reply_error(
                    entry["sender"], entry["requestId"], "backpressure",
                    f"partition {partition_id} has reached its in-flight "
                    f"command limit")
            else:
                self.admission.release(entry["tenant"])
                self._reply_error(
                    entry["sender"], entry["requestId"], "unavailable",
                    f"partition {partition_id} paused or disk-paused")
        return len(entries)

    def _maybe_chaos_crash(self, partition) -> None:
        """Armed by ``ZEEBE_CHAOS_CRASH_AFTER_APPENDS=N``: hard-exit between
        the Nth successful append and its reply. The raft journal is flushed
        first so the appended command SURVIVES the crash (the scenario under
        test is dedupe-on-resend, not a legitimately-lost volatile append),
        and the marker file keeps the restarted process from re-arming."""
        if self._crash_after_appends is None:
            return
        self._crash_after_appends -= 1
        if self._crash_after_appends > 0:
            return
        self._crash_after_appends = None
        try:
            self._crash_marker.parent.mkdir(parents=True, exist_ok=True)
            self._crash_marker.touch()
            partition.raft.journal.flush()
        finally:
            print(f"[{self.node_id}] chaos: crashing between append and reply",
                  file=sys.stderr, flush=True)
            os._exit(86)

    def _on_processing_response(self, response) -> None:
        origin = response.request_stream_id
        if not 0 <= origin < len(self._route_members):
            return
        target = self._route_members[origin]
        if target == self.node_id:
            return  # workers never originate client requests
        dedupe_key = (target, response.request_id)
        # the append→reply latency IS the shed ladder's feedback signal
        self._release_admission(dedupe_key)
        from zeebe_tpu.observability.tracer import get_tracer

        tracer = get_tracer()
        t_reply = time.perf_counter() if tracer.enabled else 0.0
        payload = {
            "requestId": response.request_id,
            "record": response.record.to_bytes(),
            "commandPosition": self._inflight_positions.pop(dedupe_key, -1),
        }
        self._recent_replies[dedupe_key] = payload
        while len(self._recent_replies) > 4096:
            self._recent_replies.popitem(last=False)
        self.messaging.send(target, GATEWAY_RESPONSE_TOPIC, payload)
        if tracer.enabled:
            # reply-release seam: serialize + enqueue to the gateway, on the
            # ROOT trace so the critical-path sweep can close the tail edge
            pid = response.record.partition_id
            position = payload["commandPosition"]
            if position >= 0:
                root = tracer.resolve_root(pid, position, position)
                trace_id = f"{pid}:{root}"
                if tracer.sampled(trace_id):
                    tracer.emit(trace_id, "processor.reply_release",
                                time.perf_counter() - t_reply, pid,
                                parent="processor.ack",
                                attrs={"position": position,
                                       "gateway": target})

    # -- jobs available --------------------------------------------------------

    def _on_jobs_available(self, partition_id: int, job_types: set) -> None:
        payload = {"partitionId": partition_id, "types": sorted(job_types)}
        for gateway in self.gateway_members:
            self.messaging.send(gateway, JOBS_AVAILABLE_TOPIC, payload)

    # -- status push -----------------------------------------------------------

    def _roles(self) -> dict[str, str]:
        return {str(pid): ("leader" if p.is_leader else "follower")
                for pid, p in self.broker.partitions.items()}

    def send_status(self) -> None:
        from zeebe_tpu.broker.management import broker_status

        # broker_status already attaches the control block (knob/bounds
        # evidence) when the plane is on — it rides the push as-is
        status = broker_status(self.broker)
        status["workerPid"] = os.getpid()
        if self.admission.cfg.enabled:
            # per-worker admission evidence rides the status row the same
            # way recovery accounting does — /cluster/status and `cli top`
            # see every worker's tenant rates/sheds without an extra hop
            status["admission"] = self.admission.snapshot()
        recoveries = {
            str(pid): p.last_recovery
            for pid, p in self.broker.partitions.items()
            if getattr(p, "last_recovery", None) is not None
        }
        if recoveries:
            # PR 6 recovery accounting crosses the process boundary with the
            # status row: /cluster/status answers "what did the restart cost"
            status["recoveries"] = recoveries
        for gateway in self.gateway_members:
            self.messaging.send(gateway, WORKER_STATUS_TOPIC,
                                {"status": status})

    def maybe_send_status(self) -> None:
        now = self.broker.clock_millis()
        roles = self._roles()
        if (roles != self._last_roles
                or now - self._last_status_ms >= self._status_interval_ms):
            self._last_roles = roles
            self._last_status_ms = now
            self.send_status()

    # -- pump ------------------------------------------------------------------

    def pump(self) -> int:
        moved = 0
        poll = getattr(self.messaging, "poll", None)
        if poll is not None:
            moved += poll()
        if self._ingress_pending:
            # coalesced ingress: due windows append as one batch per
            # partition BEFORE the broker pump so the batch processes in
            # this very round
            moved += self._flush_due_ingress()
        moved += self.broker.pump()
        # shed-ladder feedback loop (throttled internally to its tick)
        self.admission.tick(float(self.broker.clock_millis()))
        self.maybe_send_status()
        return moved

    def _set_coalesce_window(self, value: float) -> None:
        """The ingress-coalescing actuator's registered write seam — the
        knob lives on this runtime, so the assignment does too; nothing
        else may write it after construction."""
        # (suppressed: this method IS the write callback handed to the
        # registered Actuator — the one sanctioned mutation site)
        self.coalesce_window_ms = float(value)  # zlint: disable=control-actuation-discipline

    def _admission_loop_snapshot(self) -> dict:
        return {
            "knob": "admission.shedLevel",
            "description": "DAGOR shed ladder driven by observed ack p99 "
                           "(PR 11)",
            "value": self.admission.shed_level,
            "adjustments": self.admission.level_changes,
            "observedP99Ms": round(self.admission.last_p99_ms, 1),
            "draining": self.admission.draining,
        }

    def close(self) -> None:
        if self.broker.control is not None:
            # the control audit trail must survive an orderly shutdown:
            # the arm's flight dump (with the control context block) is
            # the evidence the autotune gate collects offline
            self.broker.flight_recorder.dump("control-shutdown", force=True)
        self._dump_spans()
        self.broker.close()

    def _dump_spans(self) -> None:
        """Persist this process's span ring as ``spans-<node>-<pid>.jsonl``
        under the data dir: the offline critical-path assembler merges these
        per-process dumps by derived trace id (no in-band propagation)."""
        from zeebe_tpu.observability.tracer import get_tracer

        tracer = get_tracer()
        if not tracer.enabled or not len(tracer.collector):
            return
        path = (self.broker.directory
                / f"spans-{self.node_id}-{os.getpid()}.jsonl")
        try:
            tracer.collector.to_jsonl(path)
        except OSError:
            pass  # a full disk must not turn shutdown fatal


class _TestLeak:
    """Deliberate resource leak for the fleet-day recall arm (ISSUE 20):
    ``ZEEBE_AUDIT_TESTLEAK=fd:20`` leaks ~20 file descriptors per second,
    ``ring:50`` pushes ~50 junk events/s into the flight recorder's node
    ring. The online auditor MUST return a leak verdict against a worker
    running with this armed — proving the detector's recall, not just its
    quietness on a clean tree. Never enable outside a test harness."""

    def __init__(self, kind: str, per_sec: float) -> None:
        self.kind = kind
        self.per_sec = per_sec
        self._held: list = []   # leaked fds stay referenced until exit
        self._last = time.monotonic()

    @staticmethod
    def from_env() -> "_TestLeak | None":
        spec = os.environ.get("ZEEBE_AUDIT_TESTLEAK", "")
        if not spec:
            return None
        kind, _, rate = spec.partition(":")
        try:
            per_sec = float(rate) if rate else 10.0
        except ValueError:
            per_sec = 10.0
        if kind not in ("fd", "ring"):
            return None
        return _TestLeak(kind, per_sec)

    def tick(self, runtime) -> None:
        now = time.monotonic()
        count = int((now - self._last) * self.per_sec)
        if count <= 0:
            return
        self._last = now
        if self.kind == "fd":
            for _ in range(min(count, 64)):
                try:
                    self._held.append(open(os.devnull, "rb"))  # noqa: SIM115
                except OSError:
                    return  # fd table exhausted: stop leaking, stay alive
        else:
            flight = getattr(runtime.broker, "flight_recorder", None)
            if flight is not None:
                for i in range(min(count, 256)):
                    flight.record(0, "test_leak", seq=len(self._held) + i)
                self._held.extend(range(min(count, 256)))


def main(argv: list[str] | None = None) -> int:
    """Process entry: ``python -m zeebe_tpu.multiproc.worker ...`` (normally
    spawned by :class:`zeebe_tpu.multiproc.supervisor.WorkerSupervisor`)."""
    import argparse
    import signal

    from zeebe_tpu.utils.zlogging import configure_logging

    configure_logging()
    parser = argparse.ArgumentParser(prog="zeebe-tpu-worker")
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--bind", required=True, help="host:port for TCP "
                        "cluster messaging")
    parser.add_argument("--contact", required=True,
                        help="comma-separated member=host:port for EVERY "
                             "member (workers AND gateways)")
    parser.add_argument("--gateway", required=True,
                        help="comma-separated gateway member ids (subset of "
                             "--contact)")
    parser.add_argument("--partitions", type=int, default=1)
    parser.add_argument("--replication", type=int, default=1)
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--management-port", type=int, default=0)
    args = parser.parse_args(argv)

    # startup device probe (killable, SIGKILL on wedge): a wedged TPU tunnel
    # must degrade this worker to host devices, never hang its boot
    from zeebe_tpu.utils.backend_probe import pin_cpu_if_unreachable

    diag = pin_cpu_if_unreachable()
    if diag.get("outcome") != "env-pinned-cpu":
        print(f"[{args.node_id}] device probe: {diag}", file=sys.stderr,
              flush=True)

    from zeebe_tpu.utils.xla_cache import enable_persistent_cache

    enable_persistent_cache()

    from zeebe_tpu.backup import backup_store_from_env
    from zeebe_tpu.broker.config import load_broker_cfg
    from zeebe_tpu.cluster.messaging import TcpMessagingService
    from zeebe_tpu.standalone import _parse_contacts
    from zeebe_tpu.utils.external_code import exporters_factory_from_env

    contacts = _parse_contacts(args.contact)
    gateways = [g.strip() for g in args.gateway.split(",") if g.strip()]
    broker_members = sorted(m for m in contacts if m not in gateways)
    host, port = args.bind.rsplit(":", 1)
    peers = {m: a for m, a in contacts.items() if m != args.node_id}
    messaging = TcpMessagingService(args.node_id, (host, int(port)), peers)
    messaging.start()
    # TCP-layer chaos (ISSUE 9): ZEEBE_CHAOS_TCP wraps this worker's whole
    # messaging plane — gateway↔worker AND worker↔worker (raft/SWIM) frames
    # ride through the seeded fault injector
    from zeebe_tpu.testing.chaos_tcp import ChaosTcpMessagingService, maybe_wrap_chaos

    messaging = maybe_wrap_chaos(messaging)
    if isinstance(messaging, ChaosTcpMessagingService) and args.data_dir:
        # observed-fault evidence for the consistency report, one snapshot
        # file per process life (a SIGKILL loses ≤1 dump interval)
        messaging.counts_file = os.path.join(
            args.data_dir, f"chaos-counts-{os.getpid()}.json")
    # disk-layer chaos (ISSUE 14): ZEEBE_CHAOS_DISK installs the seeded
    # fault controller into the storage_io seam BEFORE any journal opens;
    # its tick (at-rest bit-rot + counts evidence) rides the pump loop
    from zeebe_tpu.testing.chaos_disk import maybe_install_from_env as \
        _maybe_disk_chaos

    disk_chaos = _maybe_disk_chaos(member_id=args.node_id,
                                   data_dir=args.data_dir)
    # device-layer chaos (ISSUE 15): ZEEBE_CHAOS_DEVICE installs the seeded
    # fault controller into the kernel backend's dispatch seam; its tick
    # (disarm check + counts evidence) rides the pump loop
    from zeebe_tpu.testing.chaos_device import maybe_install_from_env as \
        _maybe_device_chaos

    device_chaos = _maybe_device_chaos(member_id=args.node_id,
                                       data_dir=args.data_dir)

    ext = load_broker_cfg(overrides={
        "base.node_id": args.node_id,
        "base.partition_count": args.partitions,
        "base.replication_factor": args.replication,
        "base.cluster_members": broker_members,
    })
    runtime = WorkerRuntime(
        args.node_id, messaging, gateways, ext.base,
        directory=args.data_dir,
        coalesce_window_ms=ext.processing.coalesce_window_ms,
        exporters_factory=exporters_factory_from_env(),
        backup_store=backup_store_from_env(),
        backpressure_algorithm=ext.backpressure.algorithm,
        backpressure_enabled=ext.backpressure.enabled,
        disk_min_free_bytes=(ext.disk.min_free_bytes
                             if ext.disk.enable_monitoring and args.data_dir
                             else 0),
    )
    management = None
    if args.management_port:
        from zeebe_tpu.broker.management import ManagementServer

        management = ManagementServer(
            runtime.broker, bind=("0.0.0.0", args.management_port))
        management.start()

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    test_leak = _TestLeak.from_env()
    print(f"[{args.node_id}] worker up: partitions<={args.partitions} "
          f"bind {args.bind} pid {os.getpid()}", file=sys.stderr, flush=True)
    while not stop.is_set():
        if disk_chaos is not None:
            disk_chaos.tick()
        if device_chaos is not None:
            device_chaos.tick()
        if test_leak is not None:
            test_leak.tick(runtime)
        if runtime.pump() == 0:
            time.sleep(0.001)
    if management is not None:
        management.stop()
    runtime.close()
    messaging.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
