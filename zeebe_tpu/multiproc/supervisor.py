"""Worker supervisor: spawn, monitor, restart per-core broker processes.

Reference shape: a process manager in front of N StandaloneBroker instances
(systemd / the k8s statefulset the reference deploys as), reduced to what
the single-host scale-out needs:

- spawn each worker as a child process (stderr teed to ``<data-dir>/worker.log``
  when the spec has a data dir, so a crashed worker leaves evidence);
- monitor liveness; a worker that EXITS while the supervisor is running is
  restarted with exponential backoff (crash loops are bounded, a healthy
  restart resets the backoff) — the restarted worker recovers its partitions
  through the PR 6 snapshot+replay path over its data dir;
- stop with SIGTERM, escalate to SIGKILL after a grace period (a wedged
  device runtime must not be able to hold shutdown hostage — the same
  discipline as the killable device probe).

``zeebe_worker_restarts_total{worker}`` counts restarts on the metrics
plane; :meth:`WorkerSupervisor.status` feeds the gateway's
``/cluster/status`` ``workers`` section.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

logger = logging.getLogger("zeebe_tpu.multiproc.supervisor")


@dataclasses.dataclass
class WorkerSpec:
    """One worker process: its identity and the exact command to run it.

    ``cmd`` is explicit (not derived) so tests can supervise stub processes
    and operators can see the full spawn line in ``status()``."""

    node_id: str
    cmd: list[str]
    data_dir: str | None = None
    management_port: int = 0
    # per-worker environment overlay on the supervisor env (chaos knobs:
    # e.g. one worker armed with ZEEBE_CHAOS_CRASH_AFTER_APPENDS)
    extra_env: dict | None = None


def worker_cmd(node_id: str, bind: str, contact: str, gateways: str,
               partitions: int, replication: int,
               data_dir: str | None = None,
               management_port: int = 0) -> list[str]:
    """The canonical ``python -m zeebe_tpu.multiproc.worker`` spawn line."""
    cmd = [sys.executable, "-m", "zeebe_tpu.multiproc.worker",
           "--node-id", node_id, "--bind", bind, "--contact", contact,
           "--gateway", gateways,
           "--partitions", str(partitions),
           "--replication", str(replication)]
    if data_dir:
        cmd += ["--data-dir", str(data_dir)]
    if management_port:
        cmd += ["--management-port", str(management_port)]
    return cmd


class WorkerSupervisor:
    """Spawn/monitor/restart a set of :class:`WorkerSpec` processes."""

    def __init__(self, specs: list[WorkerSpec], env: dict | None = None,
                 restart_backoff_s: float = 0.5, max_backoff_s: float = 10.0,
                 stable_after_s: float = 30.0,
                 grace_period_s: float = 5.0) -> None:
        from zeebe_tpu.utils.metrics import REGISTRY

        self.specs = {spec.node_id: spec for spec in specs}
        if env is None:
            env = dict(os.environ)
            # workers must import zeebe_tpu exactly as this process does
            pkg_parent = str(Path(__file__).resolve().parent.parent.parent)
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in (pkg_parent, env.get("PYTHONPATH")) if p)
        self._env = env
        self._restart_backoff_s = restart_backoff_s
        self._max_backoff_s = max_backoff_s
        self._stable_after_s = stable_after_s
        self._grace_period_s = grace_period_s
        self._procs: dict[str, subprocess.Popen] = {}
        self._logs: dict[str, object] = {}
        self._backoff: dict[str, float] = {}
        self._restart_at: dict[str, float] = {}
        self._spawned_at: dict[str, float] = {}
        self.restarts: dict[str, int] = {s: 0 for s in self.specs}
        # observer seam: called (node_id, restart count) AFTER a successful
        # respawn — the gateway runtime records it in its flight recorder
        self.on_restart = None
        self._running = False
        self._monitor_thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._m_restarts = REGISTRY.counter(
            "worker_restarts_total",
            "worker processes restarted by the supervisor after an "
            "unexpected exit", ("worker",))

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._running = True
        for node_id in self.specs:
            self._spawn(node_id)
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="worker-supervisor")
        self._monitor_thread.start()

    def _spawn(self, node_id: str) -> None:
        spec = self.specs[node_id]
        stderr = subprocess.DEVNULL
        if spec.data_dir:
            Path(spec.data_dir).mkdir(parents=True, exist_ok=True)
            old_log = self._logs.pop(node_id, None)
            if old_log is not None:
                try:  # a restart must not leak the previous spawn's fd
                    old_log.close()
                except OSError:  # pragma: no cover
                    pass
            log = open(Path(spec.data_dir) / "worker.log", "ab")
            self._logs[node_id] = log
            stderr = log
        env = self._env
        if spec.extra_env:
            env = {**env, **spec.extra_env}
        proc = subprocess.Popen(
            spec.cmd, env=env,
            stdout=stderr, stderr=stderr,
            start_new_session=True,  # SIGKILL escalation targets the whole
            # session: a worker's own children must not survive it
        )
        with self._lock:
            self._procs[node_id] = proc
            self._spawned_at[node_id] = time.monotonic()
        logger.info("spawned worker %s pid=%s", node_id, proc.pid)

    def _monitor(self) -> None:
        while self._running:
            now = time.monotonic()
            for node_id in list(self.specs):
                try:
                    self._monitor_one(node_id, now)
                except Exception:  # noqa: BLE001 — a failed respawn (fork
                    # EAGAIN under memory pressure, log-file open error) must
                    # not kill the monitor thread and silently end
                    # supervision for EVERY worker; retry next tick
                    logger.exception("supervising %s failed; retrying",
                                     node_id)
            time.sleep(0.05)

    def _monitor_one(self, node_id: str, now: float) -> None:
        proc = self._procs.get(node_id)
        if proc is None or proc.poll() is None:
            # alive long enough → the crash loop (if any) is over
            if (proc is not None and node_id in self._backoff
                    and now - self._spawned_at.get(node_id, now)
                    >= self._stable_after_s):
                self._backoff.pop(node_id, None)
            return
        if not self._running:
            return
        due = self._restart_at.get(node_id)
        if due is None:
            backoff = self._backoff.get(node_id, self._restart_backoff_s)
            self._backoff[node_id] = min(backoff * 2, self._max_backoff_s)
            self._restart_at[node_id] = now + backoff
            logger.warning("worker %s exited rc=%s; restarting in %.1fs",
                           node_id, proc.returncode, backoff)
            return
        if now >= due:
            self._restart_at.pop(node_id, None)
            # count AFTER the spawn succeeds: a failed respawn (fork EAGAIN,
            # log-open error) is retried by the monitor and must not count
            # the same crash twice on the restarts dashboard
            self._spawn(node_id)
            self.restarts[node_id] += 1
            self._m_restarts.labels(node_id).inc()
            if self.on_restart is not None:
                try:
                    self.on_restart(node_id, self.restarts[node_id])
                except Exception:  # noqa: BLE001 — observation must never
                    logger.exception("on_restart observer failed")  # stop supervision

    def stop(self) -> None:
        self._running = False
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5)
        procs = list(self._procs.items())
        for _node_id, proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    proc.terminate()
        deadline = time.monotonic() + self._grace_period_s
        for node_id, proc in procs:
            remaining = deadline - time.monotonic()
            try:
                proc.wait(timeout=max(remaining, 0.05))
            except subprocess.TimeoutExpired:
                logger.warning("worker %s ignored SIGTERM; killing", node_id)
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    proc.kill()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    logger.error("worker %s unkillable", node_id)
        for log in self._logs.values():
            try:
                log.close()
            except OSError:  # pragma: no cover
                pass
        self._logs.clear()

    # -- introspection ---------------------------------------------------------

    def kill_worker(self, node_id: str) -> None:
        """SIGKILL one worker (chaos/tests): the monitor restarts it."""
        proc = self._procs.get(node_id)
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                proc.kill()

    def alive(self) -> dict[str, bool]:
        return {n: (p is not None and p.poll() is None)
                for n, p in self._procs.items()}

    def pid_of(self, node_id: str) -> int | None:
        proc = self._procs.get(node_id)
        if proc is None or proc.poll() is not None:
            return None
        return proc.pid

    def status(self) -> dict:
        """Per-worker supervision row for ``/cluster/status``."""
        out = {}
        for node_id, spec in self.specs.items():
            proc = self._procs.get(node_id)
            out[node_id] = {
                "pid": proc.pid if proc is not None else None,
                "alive": proc is not None and proc.poll() is None,
                "returncode": proc.returncode if proc is not None else None,
                "restarts": self.restarts.get(node_id, 0),
                "managementPort": spec.management_port,
            }
        return out
