"""Multi-process mesh scale-out: per-core broker worker processes behind one
gateway (ISSUE 7; ROADMAP item 1).

The in-process ``ClusterRuntime`` runs every partition's stream processor in
ONE interpreter — the GIL is effectively the cluster scheduler, and
``mesh_serving`` p8 measured *below* p1 because eight partitions' Python
serialized on one core. This package makes partition throughput additive by
moving brokers into per-core **worker processes**:

- :mod:`zeebe_tpu.multiproc.worker` — the worker process: one
  :class:`~zeebe_tpu.broker.Broker` (hosting one or more partitions, its own
  data dir, metrics registry, and optional management port) over TCP cluster
  messaging, plus the gateway-facing protocol (client commands in, responses
  / status / jobs-available out).
- :mod:`zeebe_tpu.multiproc.supervisor` — spawns, monitors, and restarts the
  workers (SIGTERM then SIGKILL on stop; crashed workers restart with
  backoff and recover through the PR 6 snapshot+replay path).
- :mod:`zeebe_tpu.multiproc.runtime` — the gateway-side
  :class:`MultiProcClusterRuntime`: the same surface the gRPC gateway and
  the management server already consume (``submit``, ``topology``,
  ``cluster_status``, jobs-available), so topology, command routing, and
  ``/cluster/status`` aggregation are unchanged from the client's point of
  view.

Trace discipline (PR 3, Dapper): the trace id stays derivable everywhere —
``partition:command position`` — and the gateway request id rides the
command envelope across the process boundary, so ``cli trace`` reconstructs
lineage spanning processes from the worker's journal alone.
"""

from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
from zeebe_tpu.multiproc.supervisor import WorkerSpec, WorkerSupervisor
from zeebe_tpu.multiproc.worker import WorkerRuntime

__all__ = [
    "MultiProcClusterRuntime",
    "WorkerRuntime",
    "WorkerSpec",
    "WorkerSupervisor",
]
