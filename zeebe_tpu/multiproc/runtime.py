"""Gateway-side runtime for the multi-process cluster: route client commands
to per-core worker processes, aggregate topology and /cluster/status.

Implements the same surface as the in-process ``ClusterRuntime`` and the
one-broker ``TcpClusterRuntime`` (``submit``, partition selection,
``topology``, ``cluster_status``, jobs-available), so the gRPC gateway, the
management server, and ``cli top`` work unchanged — the client cannot tell
whether partitions live in this interpreter or in worker processes.

Routing: the gateway joins the TCP cluster as a messaging member (it hosts
no partitions and takes no part in Raft/SWIM). Worker leadership is learned
from the workers' ``worker-status`` pushes; commands go to the leader over
``mp-client-command-<partition>`` with the gateway request id on the
envelope, and responses return over ``mp-gateway-response`` addressed by the
record's ``request_stream_id`` (the gateway's index in the sorted member
list — both sides derive it, no handshake). A typed error frame
(``not-leader`` / ``backpressure`` / ``unavailable``) resolves the request
immediately instead of letting it time out: ``not-leader`` means the worker
did NOT append, so the gateway may safely re-route the SAME request id after
the next status refresh.

Tracing (Dapper discipline, PR 3): the response carries the command's
position, so the gateway mints its root ``gateway.request`` span with the
derived trace id ``partition:position`` — the same id the worker-side
ingress/processing/export spans key on. One trace, two processes, zero
extra wire fields beyond the request id the record already carries.
"""

from __future__ import annotations

import os
import threading
import time

from zeebe_tpu.gateway.admission import AdmissionCfg, AdmissionController
from zeebe_tpu.gateway.broker_client import (
    DeadlineExceededError,
    GatewayRuntimeBase,
    NoLeaderError,
    ResourceExhaustedError,
)
from zeebe_tpu.multiproc.worker import (
    CLIENT_COMMAND_TOPIC,
    GATEWAY_RESPONSE_TOPIC,
    JOBS_AVAILABLE_TOPIC,
    WORKER_STATUS_TOPIC,
)
from zeebe_tpu.protocol import Record

#: a worker silent for this long is considered stale for leader routing
STALE_STATUS_MS = 15_000

#: overall per-request deadline default (``ZEEBE_GATEWAY_REQUEST_TIMEOUT_MS``)
DEFAULT_REQUEST_TIMEOUT_MS = 30_000


def request_timeout_s() -> float:
    """The bounded-resend ceiling: no request outlives this, however long
    the caller's own timeout is — a dead partition surfaces a typed
    DEADLINE_EXCEEDED instead of an unbounded retry loop."""
    try:
        ms = int(os.environ.get("ZEEBE_GATEWAY_REQUEST_TIMEOUT_MS", ""))
    except ValueError:
        ms = DEFAULT_REQUEST_TIMEOUT_MS
    return max(ms, 1) / 1000.0


from zeebe_tpu.utils.metrics import REGISTRY as _REG  # noqa: E402

_M_REQUEST_TIMEOUTS = _REG.counter(
    "gateway_request_timeouts_total",
    "client requests abandoned at the overall gateway deadline "
    "(DEADLINE_EXCEEDED)", ("partition",))


class MultiProcClusterRuntime(GatewayRuntimeBase):
    """The gateway's view of a supervised multi-process worker cluster."""

    def __init__(self, node_id: str, workers: dict[str, tuple[str, int]],
                 partition_count: int, replication_factor: int = 1,
                 bind: tuple[str, int] | None = None,
                 supervisor=None, messaging=None,
                 gateway_members: list[str] | None = None,
                 admission: AdmissionController | None = None) -> None:
        self.node_id = node_id
        self.partition_count = partition_count
        self.replication_factor = replication_factor
        self.worker_members = sorted(workers)
        # stream-id derivation must MATCH the workers' _route_members
        # (sorted union of broker members and EVERY gateway): with multiple
        # gateways, pass the same gateway list the workers got via
        # --gateway, or responses route to the wrong gateway
        self._members = sorted(
            set(workers) | set(gateway_members or ()) | {node_id})
        self._stream_id = self._members.index(node_id)
        self.supervisor = supervisor
        if messaging is None:
            from zeebe_tpu.cluster.messaging import TcpMessagingService

            if bind is None:
                raise ValueError("bind is required without injected messaging")
            messaging = TcpMessagingService(node_id, bind, dict(workers))
        self.messaging = messaging
        self._owns_messaging = hasattr(messaging, "start")
        self._init_requests()
        self._init_jobstreams()
        # error frames ride the same response table as records; submit()
        # inspects the type
        self._worker_status: dict[str, dict] = {}
        self._status_seen_ms: dict[str, float] = {}
        # cluster-routing observability (ISSUE 9): the gateway's own flight
        # recorder (node-level ring; no data dir — served live, dumped
        # never) records worker restarts and routing-table epoch changes
        from zeebe_tpu.observability.flight_recorder import FlightRecorder

        self.flight = FlightRecorder(node_id, data_dir=None)
        # tenant-aware admission + cooperative shedding (ISSUE 11): every
        # client command passes the controller before it is routed; sheds
        # are typed RESOURCE_EXHAUSTED and land in this flight recorder
        self.admission = admission if admission is not None else \
            AdmissionController(AdmissionCfg.from_env(), node_id=node_id,
                                flight=self.flight)
        self.routing_epoch = 0
        self._last_leaders: dict[int, str | None] = {}
        if supervisor is not None:
            supervisor.on_restart = self._on_worker_restart
        messaging.subscribe(GATEWAY_RESPONSE_TOPIC, self._on_worker_response)
        messaging.subscribe(WORKER_STATUS_TOPIC, self._on_worker_status)
        messaging.subscribe(JOBS_AVAILABLE_TOPIC, self._on_remote_jobs_available)
        self._running = False
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        if self._owns_messaging:
            start = getattr(self.messaging, "start", None)
            if start is not None and getattr(self.messaging, "_thread", None) is None:
                start()
        if self.supervisor is not None:
            self.supervisor.start()
        self._running = True
        poll = getattr(self.messaging, "poll", None)
        if poll is not None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=f"mp-gateway-{self.node_id}")
            self._thread.start()
        self.job_streams.start()

    def _run(self) -> None:
        poll = self.messaging.poll
        while self._running:
            # the shed ladder's feedback loop rides the poll thread
            # (throttled internally to its tick interval)
            self.admission.tick()
            if poll() == 0:
                time.sleep(0.001)

    def stop(self) -> None:
        # robust against a partially-started runtime (boot-failure teardown
        # path): whatever else breaks, the supervisor MUST be stopped — it
        # is the only thing that can tear down the detached workers
        try:
            self.job_streams.stop()
        except Exception:  # noqa: BLE001
            pass
        self._dump_spans()
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            if self.supervisor is not None:
                self.supervisor.stop()
        finally:
            stop = getattr(self.messaging, "stop", None)
            if stop is not None:
                stop()

    def _dump_spans(self) -> None:
        """Persist this gateway's span ring as ``spans-<node>-<pid>.jsonl``
        under ``ZEEBE_TRACE_DUMP_DIR`` (the gateway owns no data dir — the
        harness that wants merged cluster traces points every process at a
        shared dump dir). The offline assembler joins these per-process
        dumps by derived trace id."""
        import os

        from zeebe_tpu.observability.tracer import get_tracer

        dump_dir = os.environ.get("ZEEBE_TRACE_DUMP_DIR")
        tracer = get_tracer()
        if not dump_dir or not tracer.enabled or not len(tracer.collector):
            return
        from pathlib import Path

        path = (Path(dump_dir)
                / f"spans-{self.node_id}-{os.getpid()}.jsonl")
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            tracer.collector.to_jsonl(path)
        except OSError:
            pass  # best-effort evidence; shutdown must not fail on a dump

    def ready(self) -> bool:
        """Readiness: every partition has a live (non-stale) leader AND the
        admission controller is not draining (sustained shedding of new work
        degrades /ready so an LB can rotate this gateway out while
        completions keep draining)."""
        if self.admission.draining:
            return False
        return all(self._leader_of(p) is not None
                   for p in range(1, self.partition_count + 1))

    def await_leaders(self, timeout_s: float = 60.0) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if all(self._leader_of(p) is not None
                   for p in range(1, self.partition_count + 1)):
                return
            time.sleep(0.05)
        raise RuntimeError("partition leaders not elected in time")

    # -- worker status ---------------------------------------------------------

    def _on_worker_status(self, sender: str, payload: dict) -> None:
        status = payload.get("status")
        if isinstance(status, dict):
            self._worker_status[sender] = status
            self._status_seen_ms[sender] = time.time() * 1000.0
            self._observe_routing_table()

    def _on_worker_restart(self, node_id: str, restarts: int) -> None:
        self.flight.record(0, "worker_restart", worker=node_id,
                           restarts=restarts)

    def _observe_routing_table(self) -> None:
        """Bump the routing epoch when the leader map changes — every
        re-route decision is attributable to a concrete epoch in the
        flight recorder."""
        leaders = {p: self._leader_of(p)
                   for p in range(1, self.partition_count + 1)}
        if leaders != self._last_leaders:
            self._last_leaders = leaders
            self.routing_epoch += 1
            self.flight.record(0, "routing_epoch", epoch=self.routing_epoch,
                               leaders={str(p): m
                                        for p, m in leaders.items()})

    def _on_remote_jobs_available(self, sender: str, payload: dict) -> None:
        self._on_jobs_available(payload["partitionId"], set(payload["types"]))

    def _leader_of(self, partition_id: int) -> str | None:
        now_ms = time.time() * 1000.0
        key = str(partition_id)
        for member in self.worker_members:
            status = self._worker_status.get(member)
            if status is None:
                continue
            if now_ms - self._status_seen_ms.get(member, 0.0) > STALE_STATUS_MS:
                continue  # silent worker: likely dead, don't route to it
            if status.get("partitions", {}).get(key, {}).get("role") == "leader":
                return member
        return None

    # -- topology / status -----------------------------------------------------

    def topology(self) -> dict:
        brokers = []
        for member in self.worker_members:
            status = self._worker_status.get(member)
            partitions = []
            if status is not None:
                partitions = [
                    {"partitionId": int(pid), "role": info.get("role", "?")}
                    for pid, info in sorted(
                        status.get("partitions", {}).items(),
                        key=lambda kv: int(kv[0]))
                ]
            brokers.append({"member": member, "nodeId": member,
                            "partitions": partitions})
        return {
            "clusterSize": len(self.worker_members),
            "partitionsCount": self.partition_count,
            "replicationFactor": self.replication_factor,
            "brokers": brokers,
        }

    def cluster_status(self) -> dict:
        """The /cluster/status aggregation, fed by worker status pushes
        instead of in-process fan-out — same shape as
        ``broker.management.cluster_status`` plus a ``workers`` supervision
        section (pids, restarts, liveness)."""
        order = ["HEALTHY", "DEGRADED", "UNHEALTHY", "DEAD"]
        rows = []
        worst = "HEALTHY"
        now_ms = time.time() * 1000.0
        for member in self.worker_members:
            status = self._worker_status.get(member)
            if status is None:
                rows.append({"nodeId": member, "health": "DEAD",
                             "partitions": {}, "stale": True})
                worst = "DEAD"
                continue
            row = dict(status)
            age = now_ms - self._status_seen_ms.get(member, 0.0)
            if age > STALE_STATUS_MS:
                row["stale"] = True
                worst = "DEAD"
            health = row.get("health", "HEALTHY")
            if health in order and order.index(health) > order.index(worst):
                worst = health
            rows.append(row)
        partition_ids = {
            pid for row in rows for pid in row.get("partitions", {})
        }
        out = {
            "clusterSize": len(rows),
            "partitionsCount": max(len(partition_ids), self.partition_count),
            "health": worst,
            "alertsFiring": sum(r.get("alertsFiring", 0) for r in rows),
            "appendPerSec": round(sum(
                r.get("rates", {}).get("appendPerSec", 0.0) for r in rows), 1),
            "processedPerSec": round(sum(
                r.get("rates", {}).get("processedPerSec", 0.0)
                for r in rows), 1),
            "topology": {"members": {
                r.get("nodeId", "?"): {"partitions": r.get("partitions", {})}
                for r in rows
            }},
            "brokers": rows,
        }
        out["routingEpoch"] = self.routing_epoch
        # admission + shed counters ride /cluster/status (ISSUE 11): the
        # gateway's own gate plus whatever the workers pushed in their rows
        out["admission"] = self.admission.snapshot()
        if self.supervisor is not None:
            out["workers"] = self.supervisor.status()
        return out

    def has_activatable_jobs(self, partition_id: int, job_type: str,
                             tenant_ids: list[str] | None = None) -> bool:
        # no local state to peek: let the long-poll write a real activation
        # (an empty JOB_BATCH comes back quickly) — same as the TCP runtime's
        # remote-leader case
        return True

    # -- request path ----------------------------------------------------------

    def _on_worker_response(self, sender: str, payload: dict) -> None:
        request_id = payload.get("requestId")
        event = self._pending.get(request_id)
        if event is None:
            return
        error = payload.get("error")
        if error is not None:
            self._responses[request_id] = {**error, "from": sender}
        else:
            self._responses[request_id] = {
                "record": Record.from_bytes(payload["record"]),
                "commandPosition": payload.get("commandPosition", -1),
                # "replayed": the worker answered from the replicated dedupe
                # table instead of processing (a resend of an answered
                # request) — surfaced to the consistency checker
                "dedupe": payload.get("dedupe"),
            }
        event.set()

    def submit(self, partition_id: int, record: Record,
               timeout_s: float = 10.0, meta: dict | None = None) -> Record:
        """Route a command to the partition leader and await the reply.

        Bounded (ISSUE 9): the effective deadline is
        ``min(timeout_s, ZEEBE_GATEWAY_REQUEST_TIMEOUT_MS)``; expiry raises
        a typed :class:`DeadlineExceededError` and increments
        ``gateway_request_timeouts_total`` instead of retrying forever
        against a dead partition. ``meta`` (optional dict) is filled with
        routing evidence — resends, re-routes, the answering worker, the
        command position, and whether the reply was a dedupe replay — for
        the consistency harness's history."""
        from zeebe_tpu.observability.tracer import get_tracer

        if not 1 <= partition_id <= self.partition_count:
            raise NoLeaderError(f"unknown partition {partition_id}")
        # admission-gate entry: the root span covers from HERE so the
        # critical-path sweep can see the admission wait as a queue edge
        t_enter = time.perf_counter()
        # tenant admission (ISSUE 11): typed, fast shed — no routing, no
        # worker round trip, no queue. The caller sees RESOURCE_EXHAUSTED
        # with the reason; the flight recorder carries the evidence.
        shed_reason, tenant, _priority = self.admission.try_admit(record)
        if shed_reason is not None:
            if meta is not None:
                meta.update(tenant=tenant, shed=shed_reason)
            raise ResourceExhaustedError(
                f"admission shed ({shed_reason}): tenant {tenant!r} on "
                f"partition {partition_id} (shed level "
                f"{self.admission.shed_level})")
        if meta is not None:
            meta.update(tenant=tenant)
        t_admitted = time.perf_counter()
        # feed the shed ladder only latencies that measure the CLUSTER:
        # engine replies and deadline expiries. Typed fast errors
        # (backpressure, not-leader) would read as "fast" and mask overload.
        observe_latency = False
        tracer = get_tracer()
        traced = tracer.enabled
        request_id = None
        try:
            request_id, event = self._register_request()
            rec = record.replace(request_id=request_id,
                                 request_stream_id=self._stream_id)
            payload = {"record": rec.to_bytes(), "requestId": request_id}
        except BaseException:
            # nothing was sent: the admitted in-flight slot must not leak —
            # an unserializable record value (to_bytes raising) would
            # otherwise inflate this tenant's count until the fair-share
            # gate sheds everyone forever
            if request_id is not None:
                self._pending.pop(request_id, None)
            self.admission.release(tenant)
            raise
        effective_timeout = min(timeout_s, request_timeout_s())
        deadline = time.time() + effective_timeout
        sent_to: str | None = None
        resend_slice = 1.0
        sends = 0
        reroutes = 0
        # a member that answered not-leader/unavailable is not re-routed to
        # until a NEWER status push from it arrives — the stale table that
        # mis-routed us would otherwise bounce the same envelope (and
        # produce duplicate typed frames) every retry tick
        refused_member: str | None = None
        refused_seen_ms = 0.0
        if meta is not None:
            meta.update(requestId=request_id, resends=0, reroutes=0)

        def _fill_meta(**kw) -> None:
            if meta is not None:
                meta.update(resends=max(sends - 1, 0), reroutes=reroutes,
                            worker=sent_to, **kw)

        try:
            while time.time() < deadline:
                leader = self._leader_of(partition_id)
                if (leader is not None and leader == refused_member
                        and self._status_seen_ms.get(leader, 0.0)
                        <= refused_seen_ms):
                    leader = None  # its refusal postdates our routing info
                if leader is None and sent_to is None:
                    time.sleep(0.02)
                    continue
                if sent_to is None:
                    sent_to = leader
                if not event.is_set():
                    # a restored wakeup (late reply raced a not-leader frame)
                    # means a response is already waiting — consume it below
                    # instead of sending a redundant envelope
                    sends += 1
                    self.messaging.send(
                        sent_to, f"{CLIENT_COMMAND_TOPIC}-{partition_id}",
                        payload)
                # bounded wait per send, then RESEND with backoff. A resend
                # normally targets the SAME worker (its dedupe map makes it
                # idempotent); when the routing table names a DIFFERENT
                # leader — the first worker died or lost leadership — the
                # resend re-routes there. Re-routing the same request id
                # without a typed "I did not append" frame used to risk a
                # duplicate append; the replicated dedupe table (ISSUE 9)
                # travels with the partition's log, so the new leader
                # recognizes the first member's append and answers instead
                # of appending again.
                if not event.wait(
                        min(max(deadline - time.time(), 0.001), resend_slice)):
                    if time.time() >= deadline:
                        break  # deadline exceeded below
                    resend_slice = min(resend_slice * 2, 8.0)
                    current = self._leader_of(partition_id)
                    if current is not None and current != sent_to:
                        sent_to = current
                        reroutes += 1
                        self.flight.record(0, "request_reroute",
                                           partition=partition_id,
                                           requestId=request_id,
                                           to=current,
                                           epoch=self.routing_epoch)
                    continue
                response = self._responses.pop(request_id, None)
                if response is None:  # pragma: no cover — resolver raced
                    break  # deadline path below
                if "record" in response:
                    observe_latency = True
                    result: Record = response["record"]
                    _fill_meta(
                        commandPosition=response.get("commandPosition", -1),
                        dedupe=response.get("dedupe"))
                    if traced:
                        self._emit_root_span(
                            tracer, partition_id, record, result,
                            response.get("commandPosition", -1),
                            request_id, sent_to,
                            time.perf_counter() - t_enter,
                            t_admitted - t_enter)
                    return result
                # typed error frame
                kind = response.get("type")
                if kind in ("backpressure", "resource-exhausted"):
                    # resource-exhausted: the WORKER's admission controller
                    # shed it (tenant quota / fair share / shed ladder) —
                    # same typed surface as partition backpressure
                    _fill_meta(error=kind)
                    raise ResourceExhaustedError(
                        response.get("message", kind))
                if kind in ("not-leader", "unavailable"):
                    # the worker did NOT append this request: safe to
                    # re-route the same request id once fresher status
                    # arrives
                    refused_member = response.get("from", sent_to)
                    if refused_member is not None:
                        refused_seen_ms = self._status_seen_ms.get(
                            refused_member, 0.0)
                    event.clear()
                    if request_id in self._responses:
                        # a reply from an earlier resend landed between the
                        # pop above and the clear — restore the wakeup and
                        # keep sent_to so the next iteration consumes it
                        # instead of resending
                        event.set()
                    else:
                        if sent_to is not None:
                            reroutes += 1
                        sent_to = None
                        time.sleep(0.02)
                    continue
                _fill_meta(error=kind)
                raise NoLeaderError(
                    response.get("message", f"worker error {kind!r}"))
            _M_REQUEST_TIMEOUTS.labels(str(partition_id)).inc()
            # a deadline IS an overload observation: feed it to the ladder
            observe_latency = True
            _fill_meta(error="deadline")
            raise DeadlineExceededError(
                f"partition {partition_id} request {request_id} exceeded the "
                f"{effective_timeout:.1f}s gateway deadline "
                f"(last worker {sent_to}, {sends} send(s), "
                f"{reroutes} re-route(s))")
        finally:
            self._pending.pop(request_id, None)
            self._responses.pop(request_id, None)
            self.admission.release(
                tenant,
                latency_ms=((time.perf_counter() - t_admitted) * 1000.0
                            if observe_latency else None))

    def _emit_root_span(self, tracer, partition_id: int, record: Record,
                        response: Record, position: int, request_id: int,
                        worker: str | None, latency: float,
                        admit_wait: float = 0.0) -> None:
        tracer.observe_ack("gateway", latency)
        if position < 0:
            return  # worker predates the position-carrying envelope
        trace_id = f"{partition_id}:{position}"
        if not tracer.sampled(trace_id):
            return
        attrs = {"position": position, "requestId": request_id,
                 "valueType": record.value_type.name,
                 "intent": record.intent.name,
                 "worker": worker or "?"}
        if response.is_rejection:
            attrs["rejection"] = response.rejection_type.name
        from zeebe_tpu.observability.span import now_us

        root_start_us = now_us() - int(latency * 1e6)
        tracer.emit(trace_id, "gateway.request", latency, partition_id,
                    attrs=attrs, start_us=root_start_us)
        if admit_wait > 0:
            # admission-gate wait pinned to the FRONT of the root window —
            # a back-dated-from-now emit would charge it to the reply edge
            tracer.emit(trace_id, "gateway.admission", admit_wait,
                        partition_id, parent="gateway.request",
                        attrs={"requestId": request_id},
                        start_us=root_start_us)
