"""Prometheus-style metrics registry (no external client dependency).

Reference: the reference uses Prometheus simpleclient throughout — 111 metric
names under namespace ``zeebe`` (SURVEY §5.5): stream_processor_*, sequencer_*,
log_appender_*, journal_*, snapshot_*, raft_*/election_latency_in_ms,
backpressure_*, exporter_*, gateway_*, process_instance_execution_time,
actor_*. Scraped via the management server's /metrics in the standard text
exposition format.
"""

from __future__ import annotations

import bisect
import os
import threading
from typing import Callable, Iterable


def _escape_label_value(value: str) -> str:
    """Prometheus text-exposition escaping for label VALUES: backslash,
    double-quote, and line-feed must be escaped or a single adversarial
    label (an exporter id with a quote, an element id with a newline)
    corrupts the whole scrape. Backslash first — escaping is not
    commutative."""
    return (value.replace("\\", "\\\\")
                 .replace('"', '\\"')
                 .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format: backslash and
    line-feed only (quotes are legal in HELP text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: tuple[str, ...]) -> None:
        self.name = name
        self.help = help_text
        self.label_names = label_names
        self._children: dict[tuple, "_Child"] = {}
        self._lock = threading.Lock()
        # cached default child: label-less Metric.inc()/observe()/set() calls
        # would otherwise pay the labels() lock + dict lookup per call — too
        # hot for append/processing loops (journal/journal.py documents the
        # same cost for its cached children)
        self._default_child: "_Child" | None = None

    def labels(self, *values: str) -> "_Child":
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {values}"
            )
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child_cls()(self, key)
                self._children[key] = child
            return child

    def _default(self) -> "_Child":
        child = self._default_child
        if child is None:
            child = self.labels(
                *([] if not self.label_names else [""] * len(self.label_names)))
            self._default_child = child
        return child

    def _children_snapshot(self) -> list["_Child"]:
        """Children list captured under the lock: ``collect()`` runs on the
        management scrape thread while hot paths call ``labels()`` — iterating
        the live dict can raise ``RuntimeError: dictionary changed size
        during iteration`` mid-scrape."""
        with self._lock:
            return list(self._children.values())


class _Child:
    def __init__(self, parent: _Metric, label_values: tuple) -> None:
        self.parent = parent
        self.label_values = label_values

    def _label_str(self) -> str:
        if not self.parent.label_names:
            return ""
        pairs = ",".join(
            f'{n}="{_escape_label_value(v)}"'
            for n, v in zip(self.parent.label_names, self.label_values)
        )
        return "{" + pairs + "}"


class Counter(_Metric):
    type_name = "counter"

    class Child(_Child):
        def __init__(self, parent, label_values):
            super().__init__(parent, label_values)
            self.value = 0.0

        def inc(self, amount: float = 1.0) -> None:
            self.value += amount

    def _child_cls(self):
        return Counter.Child

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def collect(self) -> Iterable[str]:
        for child in self._children_snapshot():
            yield f"{self.name}{child._label_str()} {child.value}"


class Gauge(_Metric):
    type_name = "gauge"

    class Child(_Child):
        def __init__(self, parent, label_values):
            super().__init__(parent, label_values)
            self.value = 0.0

        def set(self, value: float) -> None:
            self.value = value

        def inc(self, amount: float = 1.0) -> None:
            self.value += amount

        def dec(self, amount: float = 1.0) -> None:
            self.value -= amount

    def _child_cls(self):
        return Gauge.Child

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def collect(self) -> Iterable[str]:
        for child in self._children_snapshot():
            yield f"{self.name}{child._label_str()} {child.value}"


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                    2.5, 5.0, 10.0)


class Histogram(_Metric):
    type_name = "histogram"

    def __init__(self, name, help_text, label_names, buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets))

    class Child(_Child):
        def __init__(self, parent, label_values):
            super().__init__(parent, label_values)
            self.bucket_counts = [0] * (len(parent.buckets) + 1)
            self.sum = 0.0
            self.count = 0

        def observe(self, value: float) -> None:
            idx = bisect.bisect_left(self.parent.buckets, value)
            self.bucket_counts[idx] += 1
            self.sum += value
            self.count += 1

    def _child_cls(self):
        return Histogram.Child

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def collect(self) -> Iterable[str]:
        for child in self._children_snapshot():
            labels = child._label_str()
            base = labels[1:-1] if labels else ""
            cumulative = 0
            for bucket, count in zip(self.buckets, child.bucket_counts):
                cumulative += count
                le = f'le="{bucket}"'
                inner = f"{base},{le}" if base else le
                yield f"{self.name}_bucket{{{inner}}} {cumulative}"
            cumulative += child.bucket_counts[-1]
            le = 'le="+Inf"'
            inner = f"{base},{le}" if base else le
            yield f"{self.name}_bucket{{{inner}}} {cumulative}"
            yield f"{self.name}_sum{labels} {child.sum}"
            yield f"{self.name}_count{labels} {child.count}"


def estimate_quantile(buckets: tuple, bucket_counts: list, q: float) -> float:
    """Quantile estimate from cumulative histogram buckets, Prometheus
    ``histogram_quantile`` style: find the bucket the q-th observation lands
    in and interpolate linearly inside it. The +Inf bucket clamps to the
    highest finite bound (there is no upper edge to interpolate toward)."""
    total = sum(bucket_counts)
    if total == 0:
        return 0.0
    rank = q * total
    cumulative = 0.0
    for i, count in enumerate(bucket_counts[:-1]):
        prev_cumulative = cumulative
        cumulative += count
        if cumulative >= rank and count:
            lower = buckets[i - 1] if i > 0 else 0.0
            upper = buckets[i]
            return lower + (upper - lower) * (rank - prev_cumulative) / count
    return float(buckets[-1]) if buckets else 0.0


class MetricsRegistry:
    def __init__(self, namespace: str = "zeebe") -> None:
        self.namespace = namespace
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        # hooks run at scrape/snapshot time to refresh pull-style values
        # (process CPU/RSS/GC) that nothing in the hot path updates
        self._collect_hooks: list[Callable[[], None]] = []

    def _register(self, cls, name: str, help_text: str, labels: tuple,
                  raw: bool = False, **kw) -> _Metric:
        full = name if raw else f"{self.namespace}_{name}"
        with self._lock:
            metric = self._metrics.get(full)
            if metric is None:
                metric = cls(full, help_text, tuple(labels), **kw)
                self._metrics[full] = metric
            return metric

    def counter(self, name: str, help_text: str = "",
                labels: tuple[str, ...] = (), raw: bool = False) -> Counter:
        return self._register(Counter, name, help_text, labels, raw=raw)

    def gauge(self, name: str, help_text: str = "",
              labels: tuple[str, ...] = (), raw: bool = False) -> Gauge:
        return self._register(Gauge, name, help_text, labels, raw=raw)

    def histogram(self, name: str, help_text: str = "",
                  labels: tuple[str, ...] = (), buckets=_DEFAULT_BUCKETS,
                  raw: bool = False) -> Histogram:
        return self._register(Histogram, name, help_text, labels, raw=raw,
                              buckets=buckets)

    def add_collect_hook(self, hook: Callable[[], None]) -> None:
        """Register a pre-scrape refresh hook (idempotent by identity)."""
        with self._lock:
            if hook not in self._collect_hooks:
                self._collect_hooks.append(hook)

    def _run_collect_hooks(self) -> None:
        with self._lock:
            hooks = list(self._collect_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — a failing refresh hook must
                pass           # never take the scrape (or the sampler) down

    def _metrics_snapshot(self) -> list[_Metric]:
        # registration happens on hot paths (labels()/first use); both the
        # scrape and the time-series sampler iterate a frozen list
        with self._lock:
            return list(self._metrics.values())

    def expose(self) -> str:
        """Prometheus text exposition format."""
        self._run_collect_hooks()
        lines = []
        for metric in self._metrics_snapshot():
            lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
            lines.append(f"# TYPE {metric.name} {metric.type_name}")
            lines.extend(metric.collect())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> list[tuple]:
        """Structured point-in-time view for the time-series sampler, one
        tuple per child series — cheaper to consume than re-parsing the text
        exposition, and taken under the same locks as ``expose``:

        - counter/gauge: ``(name, type, label_str, value)``
        - histogram:     ``(name, 'histogram', label_str,
                            (count, sum, bucket_counts_copy, buckets))``
        """
        self._run_collect_hooks()
        out: list[tuple] = []
        for metric in self._metrics_snapshot():
            kind = metric.type_name
            for child in metric._children_snapshot():
                if kind == "histogram":
                    out.append((metric.name, kind, child._label_str(),
                                (child.count, child.sum,
                                 list(child.bucket_counts), metric.buckets)))
                else:
                    out.append((metric.name, kind, child._label_str(),
                                child.value))
        return out

    def describe(self) -> list[dict]:
        """Name/type/labels/HELP of every registered metric family, sorted —
        the ``metrics-doc`` generator's source of truth."""
        return sorted(
            ({"name": m.name, "type": m.type_name,
              "labels": list(m.label_names), "help": m.help}
             for m in self._metrics_snapshot()),
            key=lambda d: d["name"])


# process-global default registry (the reference's CollectorRegistry.default)
REGISTRY = MetricsRegistry()


# -- process self-metrics ------------------------------------------------------

_PAGE_SIZE = 4096


def _read_rss_bytes() -> float:
    """Resident set size. /proc is authoritative on Linux; the ru_maxrss
    fallback (peak, in KiB) keeps the gauge meaningful elsewhere."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return float(int(f.read().split()[1]) * _PAGE_SIZE)
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
        except Exception:  # noqa: BLE001 — platform without getrusage
            return 0.0


def install_process_metrics(registry: MetricsRegistry | None = None) -> None:
    """Register the standard Prometheus process/Python self-metrics
    (``process_cpu_seconds_total``, ``process_resident_memory_bytes``,
    ``python_gc_*``) as pull-style gauges refreshed by a collect hook, so
    ``/metrics`` and the time-series store can correlate engine stalls with
    host pressure (a flush-latency alert next to a climbing RSS curve reads
    very differently from one next to a flat line). Idempotent; names follow
    the prometheus_client conventions, un-namespaced."""
    import gc
    import resource

    reg = registry or REGISTRY
    # a fresh refresh-closure per call would defeat add_collect_hook's
    # identity dedupe, stacking a redundant rusage/statm/gc pass onto every
    # scrape and sampler tick
    if getattr(reg, "_process_metrics_installed", False):
        return
    reg._process_metrics_installed = True
    cpu = reg.counter(
        "process_cpu_seconds_total",
        "Total user and system CPU time spent in seconds.", raw=True)
    rss = reg.gauge(
        "process_resident_memory_bytes",
        "Resident memory size in bytes.", raw=True)
    gc_collections = reg.counter(
        "python_gc_collections_total",
        "Number of times this generation was collected",
        ("generation",), raw=True)
    gc_collected = reg.counter(
        "python_gc_objects_collected_total",
        "Objects collected during gc", ("generation",), raw=True)
    gc_uncollectable = reg.gauge(
        "python_gc_objects_uncollectable_total",
        "Uncollectable objects found during GC", ("generation",), raw=True)
    # zeebe-namespaced process gauges (ISSUE 20): the fleet auditor's
    # leak-trend detectors read these off the sampler tick, so they ride
    # the normal zeebe_ namespace and land in the time-series store
    proc_rss = reg.gauge(
        "process_rss_bytes",
        "resident set size of this process (bytes), from /proc/self with "
        "an ru_maxrss fallback")
    proc_fds = reg.gauge(
        "process_fd_count",
        "open file descriptors of this process (0 where /proc/self/fd is "
        "unavailable)")
    proc_threads = reg.gauge(
        "process_thread_count",
        "live threads in this process")

    def refresh() -> None:
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # counters are cumulative by contract: assign, don't inc — rusage is
        # already the monotonic total
        cpu._default().value = ru.ru_utime + ru.ru_stime
        rss_bytes = _read_rss_bytes()
        rss.set(rss_bytes)
        proc_rss.set(rss_bytes)
        proc_fds.set(float(read_fd_count()))
        proc_threads.set(float(read_thread_count()))
        for gen, stats in enumerate(gc.get_stats()):
            g = str(gen)
            gc_collections.labels(g).value = float(stats.get("collections", 0))
            gc_collected.labels(g).value = float(stats.get("collected", 0))
            gc_uncollectable.labels(g).set(
                float(stats.get("uncollectable", 0)))

    reg.add_collect_hook(refresh)
    refresh()


def read_fd_count() -> int:
    """Open file descriptors of this process — ``/proc/self/fd`` on Linux,
    gracefully 0 elsewhere (the trend detector treats a constant 0 as a
    flat line, never a leak)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def read_thread_count() -> int:
    """Live threads in this process. ``threading.active_count`` only sees
    threads started through :mod:`threading`, so prefer the kernel's count
    from ``/proc/self/status`` when available."""
    try:
        with open("/proc/self/status", "rb") as fh:
            for line in fh:
                if line.startswith(b"Threads:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return threading.active_count()
