"""Prometheus-style metrics registry (no external client dependency).

Reference: the reference uses Prometheus simpleclient throughout — 111 metric
names under namespace ``zeebe`` (SURVEY §5.5): stream_processor_*, sequencer_*,
log_appender_*, journal_*, snapshot_*, raft_*/election_latency_in_ms,
backpressure_*, exporter_*, gateway_*, process_instance_execution_time,
actor_*. Scraped via the management server's /metrics in the standard text
exposition format.
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterable


def _escape_label_value(value: str) -> str:
    """Prometheus text-exposition escaping for label VALUES: backslash,
    double-quote, and line-feed must be escaped or a single adversarial
    label (an exporter id with a quote, an element id with a newline)
    corrupts the whole scrape. Backslash first — escaping is not
    commutative."""
    return (value.replace("\\", "\\\\")
                 .replace('"', '\\"')
                 .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition format: backslash and
    line-feed only (quotes are legal in HELP text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: tuple[str, ...]) -> None:
        self.name = name
        self.help = help_text
        self.label_names = label_names
        self._children: dict[tuple, "_Child"] = {}
        self._lock = threading.Lock()
        # cached default child: label-less Metric.inc()/observe()/set() calls
        # would otherwise pay the labels() lock + dict lookup per call — too
        # hot for append/processing loops (journal/journal.py documents the
        # same cost for its cached children)
        self._default_child: "_Child" | None = None

    def labels(self, *values: str) -> "_Child":
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {values}"
            )
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child_cls()(self, key)
                self._children[key] = child
            return child

    def _default(self) -> "_Child":
        child = self._default_child
        if child is None:
            child = self.labels(
                *([] if not self.label_names else [""] * len(self.label_names)))
            self._default_child = child
        return child


class _Child:
    def __init__(self, parent: _Metric, label_values: tuple) -> None:
        self.parent = parent
        self.label_values = label_values

    def _label_str(self) -> str:
        if not self.parent.label_names:
            return ""
        pairs = ",".join(
            f'{n}="{_escape_label_value(v)}"'
            for n, v in zip(self.parent.label_names, self.label_values)
        )
        return "{" + pairs + "}"


class Counter(_Metric):
    type_name = "counter"

    class Child(_Child):
        def __init__(self, parent, label_values):
            super().__init__(parent, label_values)
            self.value = 0.0

        def inc(self, amount: float = 1.0) -> None:
            self.value += amount

    def _child_cls(self):
        return Counter.Child

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def collect(self) -> Iterable[str]:
        for child in self._children.values():
            yield f"{self.name}{child._label_str()} {child.value}"


class Gauge(_Metric):
    type_name = "gauge"

    class Child(_Child):
        def __init__(self, parent, label_values):
            super().__init__(parent, label_values)
            self.value = 0.0

        def set(self, value: float) -> None:
            self.value = value

        def inc(self, amount: float = 1.0) -> None:
            self.value += amount

        def dec(self, amount: float = 1.0) -> None:
            self.value -= amount

    def _child_cls(self):
        return Gauge.Child

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def collect(self) -> Iterable[str]:
        for child in self._children.values():
            yield f"{self.name}{child._label_str()} {child.value}"


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                    2.5, 5.0, 10.0)


class Histogram(_Metric):
    type_name = "histogram"

    def __init__(self, name, help_text, label_names, buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets))

    class Child(_Child):
        def __init__(self, parent, label_values):
            super().__init__(parent, label_values)
            self.bucket_counts = [0] * (len(parent.buckets) + 1)
            self.sum = 0.0
            self.count = 0

        def observe(self, value: float) -> None:
            idx = bisect.bisect_left(self.parent.buckets, value)
            self.bucket_counts[idx] += 1
            self.sum += value
            self.count += 1

    def _child_cls(self):
        return Histogram.Child

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def collect(self) -> Iterable[str]:
        for child in self._children.values():
            labels = child._label_str()
            base = labels[1:-1] if labels else ""
            cumulative = 0
            for bucket, count in zip(self.buckets, child.bucket_counts):
                cumulative += count
                le = f'le="{bucket}"'
                inner = f"{base},{le}" if base else le
                yield f"{self.name}_bucket{{{inner}}} {cumulative}"
            cumulative += child.bucket_counts[-1]
            le = 'le="+Inf"'
            inner = f"{base},{le}" if base else le
            yield f"{self.name}_bucket{{{inner}}} {cumulative}"
            yield f"{self.name}_sum{labels} {child.sum}"
            yield f"{self.name}_count{labels} {child.count}"


class MetricsRegistry:
    def __init__(self, namespace: str = "zeebe") -> None:
        self.namespace = namespace
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, cls, name: str, help_text: str, labels: tuple, **kw) -> _Metric:
        full = f"{self.namespace}_{name}"
        with self._lock:
            metric = self._metrics.get(full)
            if metric is None:
                metric = cls(full, help_text, tuple(labels), **kw)
                self._metrics[full] = metric
            return metric

    def counter(self, name: str, help_text: str = "",
                labels: tuple[str, ...] = ()) -> Counter:
        return self._register(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: tuple[str, ...] = ()) -> Gauge:
        return self._register(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: tuple[str, ...] = (), buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help_text, labels, buckets=buckets)

    def expose(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        for metric in self._metrics.values():
            lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
            lines.append(f"# TYPE {metric.name} {metric.type_name}")
            lines.extend(metric.collect())
        return "\n".join(lines) + "\n"


# process-global default registry (the reference's CollectorRegistry.default)
REGISTRY = MetricsRegistry()
