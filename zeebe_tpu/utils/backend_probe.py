"""Killable probe of the default jax backend, shared by every entry point.

On this host the TPU tunnel can hang *forever* at first device use
(``jax.devices()`` never returns), so no driver may initialize the default
backend in-process before knowing it answers. The probe runs the device query
in a subprocess with a timeout — the one place the hazard is handled, so
``bench.py`` and ``__graft_entry__.py`` cannot drift apart on timeout or
interpretation (they did in round 2: the dryrun had no probe at all and
recorded rc=124).
"""

from __future__ import annotations

import os
import subprocess
import sys

#: one shared timeout so all drivers agree on whether the backend is up
PROBE_TIMEOUT_SECS = 240


def probe_default_backend(
    cwd: str | None = None, timeout: int = PROBE_TIMEOUT_SECS
) -> tuple[str, int] | None:
    """(platform, device_count) of the default jax backend, or None.

    None means the backend did not come up inside ``timeout`` (wedged tunnel)
    or the probe subprocess failed — callers must pin the CPU platform before
    their first in-process backend use. A ``("cpu", n)`` result may reflect
    ``JAX_PLATFORMS=cpu`` / ``--xla_force_host_platform_device_count`` in the
    inherited env; callers that need *real* chips must check the platform,
    not just the count.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # caller already pinned cpu; don't burn the timeout on a subprocess
        # (the TPU plugin on this host ignores the env var and would hang —
        # only jax.config.update('jax_platforms', 'cpu') truly pins it)
        flags = os.environ.get("XLA_FLAGS", "")
        count = 1
        for flag in flags.split():
            if flag.startswith("--xla_force_host_platform_device_count="):
                try:
                    count = int(flag.split("=", 1)[1])
                except ValueError:
                    pass
        return "cpu", count
    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout, capture_output=True, text=True,
            cwd=cwd, env=dict(os.environ),
        )
        if proc.returncode != 0:
            return None
        platform, count = proc.stdout.split()[-2:]
        return platform, int(count)
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return None


def real_device_count(cwd: str | None = None,
                      timeout: int = PROBE_TIMEOUT_SECS) -> int:
    """Number of real (non-CPU) devices, or 0 if none/unreachable."""
    res = probe_default_backend(cwd, timeout)
    if res is None or res[0] == "cpu":
        return 0
    return res[1]
