"""Killable probe of the default jax backend, shared by every entry point.

On this host the TPU tunnel can hang *forever* at first device use
(``jax.devices()`` never returns), so no driver may initialize the default
backend in-process before knowing it answers. The probe runs the device query
in a subprocess with a timeout — the one place the hazard is handled, so
``bench.py`` and ``__graft_entry__.py`` cannot drift apart on timeout or
interpretation (they did in round 2: the dryrun had no probe at all and
recorded rc=124).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

#: one shared timeout so all drivers agree on whether the backend is up
PROBE_TIMEOUT_SECS = 240


def probe_with_diagnostics(
    cwd: str | None = None, timeout: int = PROBE_TIMEOUT_SECS
) -> tuple[tuple[str, int] | None, dict]:
    """((platform, device_count) | None, diagnostics) of the default backend.

    THE probe implementation — every other entry point delegates here.
    None means the backend did not come up inside ``timeout`` (wedged
    tunnel) or the probe subprocess failed — callers must pin the CPU
    platform before their first in-process backend use. The diagnostics
    dict carries the failure evidence (rc, stderr tail, elapsed) so bench
    runs can record WHY the tunnel was unreachable, not just that it was.

    A ``("cpu", n)`` result may reflect ``JAX_PLATFORMS=cpu`` /
    ``--xla_force_host_platform_device_count`` in the inherited env — that
    case short-circuits without a subprocess (the TPU plugin on this host
    ignores the env var and would hang; only
    ``jax.config.update('jax_platforms', 'cpu')`` truly pins it). Callers
    that need *real* chips must check the platform, not just the count.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        count = 1
        for flag in flags.split():
            if flag.startswith("--xla_force_host_platform_device_count="):
                try:
                    count = int(flag.split("=", 1)[1])
                except ValueError:
                    pass
        return ("cpu", count), {"outcome": "env-pinned-cpu"}
    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout, capture_output=True, text=True,
            cwd=cwd, env=dict(os.environ),
        )
    except subprocess.TimeoutExpired as exc:
        stderr = exc.stderr or b""
        if isinstance(stderr, bytes):
            stderr = stderr.decode("utf-8", "replace")
        return None, {
            "outcome": "timeout",
            "timeout_s": timeout,
            "elapsed_s": round(time.monotonic() - t0, 1),
            "stderr_tail": stderr[-800:],
        }
    diag = {
        "outcome": "ok" if proc.returncode == 0 else "nonzero-exit",
        "rc": proc.returncode,
        "elapsed_s": round(time.monotonic() - t0, 1),
        "stderr_tail": (proc.stderr or "")[-800:],
    }
    if proc.returncode != 0:
        return None, diag
    try:
        platform, count = proc.stdout.split()[-2:]
        return (platform, int(count)), diag
    except (ValueError, IndexError):
        diag["outcome"] = "unparseable-stdout"
        diag["stdout_tail"] = (proc.stdout or "")[-200:]
        return None, diag


def probe_default_backend(
    cwd: str | None = None, timeout: int = PROBE_TIMEOUT_SECS
) -> tuple[str, int] | None:
    """(platform, device_count) of the default jax backend, or None."""
    return probe_with_diagnostics(cwd, timeout)[0]


def probe_with_retries(
    attempts: int = 3,
    backoff_s: float = 20.0,
    timeout: int = PROBE_TIMEOUT_SECS,
    log: list | None = None,
    cwd: str | None = None,
) -> tuple[str, int] | None:
    """Bounded-retry probe with backoff for the flaky tunnel (VERDICT r4
    item 1). Each attempt's diagnostics are appended to ``log``. Returns the
    first successful (platform, device_count), else None after ``attempts``."""
    for i in range(attempts):
        res, diag = probe_with_diagnostics(cwd, timeout)
        diag["attempt"] = i + 1
        if log is not None:
            log.append(diag)
        if res is not None:
            return res
        if i + 1 < attempts:
            time.sleep(backoff_s)
    return None


def real_device_count(cwd: str | None = None,
                      timeout: int = PROBE_TIMEOUT_SECS) -> int:
    """Number of real (non-CPU) devices, or 0 if none/unreachable."""
    res = probe_default_backend(cwd, timeout)
    if res is None or res[0] == "cpu":
        return 0
    return res[1]
