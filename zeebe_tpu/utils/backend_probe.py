"""Killable probe of the default jax backend, shared by every entry point.

On this host the TPU tunnel can hang *forever* at first device use
(``jax.devices()`` never returns), so no driver may initialize the default
backend in-process before knowing it answers. The probe runs the device query
in a subprocess with a hard deadline — SIGKILL on wedge, never a blocking
``wait()`` on an unanswering child — so the one place the hazard is handled
cannot itself hang. ``bench.py``, ``__graft_entry__.py``, broker startup, and
mesh construction all delegate here (they drifted apart in round 2: the
dryrun had no probe at all and recorded rc=124).

A wedged probe is a *verdict*, not a hang: the diagnostics record
``outcome: "probe-killed"`` with the deadline and the kill evidence, callers
pin the CPU platform and keep serving on host devices, and the
``zeebe_device_probe_total{outcome}`` counter makes the degradation visible
on the metrics plane.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

#: one shared timeout so all drivers agree on whether the backend is up.
#: 90s covers a cold TPU runtime handshake with slack; the historical 240s
#: default meant three retries burned 12+ minutes before the fallback —
#: BENCH.json recorded exactly that (three 240s hangs in probe_attempts).
#: Override per-host with ZEEBE_PROBE_TIMEOUT_S.
PROBE_TIMEOUT_SECS = 90


def probe_timeout_secs() -> int:
    """The effective probe deadline: ``ZEEBE_PROBE_TIMEOUT_S`` when set and
    parseable, else :data:`PROBE_TIMEOUT_SECS`."""
    raw = os.environ.get("ZEEBE_PROBE_TIMEOUT_S")
    if raw:
        try:
            value = int(float(raw))
            if value > 0:
                return value
        except ValueError:
            pass
    return PROBE_TIMEOUT_SECS


_PROBE_CODE = "import jax; d = jax.devices(); print(d[0].platform, len(d))"

#: per-process probe memo keyed by the child command: broker startup, worker
#: boot, and mesh construction ALL consult the probe, and each subprocess
#: pays a jax import + device-runtime handshake (up to the full deadline on
#: a wedged host) — one verdict per process is the intended granularity.
#: ``probe_with_retries`` bypasses cache READS so retries really re-probe.
_PROBE_CACHE: dict[tuple, tuple] = {}


def _probe_metric():
    """``zeebe_device_probe_total{outcome}`` — lazily resolved so importing
    this module never pulls the metrics registry into probe *subprocesses*
    (they re-import the package) for nothing."""
    from zeebe_tpu.utils.metrics import REGISTRY

    return REGISTRY.counter(
        "device_probe_total",
        "killable default-backend probes by outcome (ok / probe-killed / "
        "nonzero-exit / unparseable-stdout / env-pinned-cpu)",
        ("outcome",))


def _run_killable(cmd: list[str], timeout: int, cwd: str | None) -> tuple:
    """Run ``cmd`` with a HARD deadline: SIGKILL the child the moment the
    deadline passes (``subprocess.run``'s TimeoutExpired path first closes
    pipes and *waits*, which a truly wedged device runtime can outlive).
    Returns (rc | None, stdout, stderr, killed)."""
    proc = subprocess.Popen(
        cmd, cwd=cwd, env=dict(os.environ), text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True,  # kill the child's whole session: the TPU
        # runtime forks helpers that would otherwise inherit the wedge
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        return proc.returncode, stdout, stderr, False
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            proc.kill()
        try:
            stdout, stderr = proc.communicate(timeout=5)
        except subprocess.TimeoutExpired:  # pragma: no cover — kernel-stuck
            stdout, stderr = "", ""
        return None, stdout, stderr, True


def probe_with_diagnostics(
    cwd: str | None = None, timeout: int | None = None,
    probe_cmd: list[str] | None = None, use_cache: bool = True,
) -> tuple[tuple[str, int] | None, dict]:
    """((platform, device_count) | None, diagnostics) of the default backend.

    THE probe implementation — every other entry point delegates here.
    None means the backend did not come up inside the deadline (wedged
    tunnel — the child is SIGKILLed, outcome ``probe-killed``) or the probe
    subprocess failed — callers must pin the CPU platform before their first
    in-process backend use. The diagnostics dict carries the failure
    evidence (rc, stderr tail, elapsed, killed) so bench runs can record WHY
    the tunnel was unreachable, not just that it was.

    ``probe_cmd`` injects the child command (tests simulate a wedged tunnel
    with a subprocess that never answers and assert it is killed at the
    deadline); default is the one-line jax device query.

    A ``("cpu", n)`` result may reflect ``JAX_PLATFORMS=cpu`` /
    ``--xla_force_host_platform_device_count`` in the inherited env — that
    case short-circuits without a subprocess (the TPU plugin on this host
    ignores the env var and would hang; only
    ``jax.config.update('jax_platforms', 'cpu')`` truly pins it). Callers
    that need *real* chips must check the platform, not just the count.
    """
    if timeout is None:
        timeout = probe_timeout_secs()
    if probe_cmd is None and os.environ.get("ZEEBE_PROBE_CMD"):
        # test/chaos seam: simulate a wedged tunnel from OUTSIDE the process
        # (e.g. a subprocess that never answers) without touching call sites
        import shlex

        probe_cmd = shlex.split(os.environ["ZEEBE_PROBE_CMD"])
    if (probe_cmd is None
            and os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        count = 1
        for flag in flags.split():
            if flag.startswith("--xla_force_host_platform_device_count="):
                try:
                    count = int(flag.split("=", 1)[1])
                except ValueError:
                    pass
        _probe_metric().labels("env-pinned-cpu").inc()
        return ("cpu", count), {"outcome": "env-pinned-cpu"}
    cmd = probe_cmd or [sys.executable, "-c", _PROBE_CODE]
    cache_key = tuple(cmd)
    if use_cache and cache_key in _PROBE_CACHE:
        cached_res, cached_diag = _PROBE_CACHE[cache_key]
        return cached_res, dict(cached_diag, cached=True)
    t0 = time.monotonic()
    rc, stdout, stderr, killed = _run_killable(cmd, timeout, cwd)
    elapsed = round(time.monotonic() - t0, 1)
    if killed:
        _probe_metric().labels("probe-killed").inc()
        diag = {
            # the clean verdict the MULTICHIP record needs: the child was
            # killed AT its deadline, the caller keeps running on host devices
            "outcome": "probe-killed",
            "timeout_s": timeout,
            "elapsed_s": elapsed,
            "killed": True,
            "stderr_tail": (stderr or "")[-800:],
        }
        _PROBE_CACHE[cache_key] = (None, dict(diag))
        return None, diag
    diag = {
        "outcome": "ok" if rc == 0 else "nonzero-exit",
        "rc": rc,
        "elapsed_s": elapsed,
        "stderr_tail": (stderr or "")[-800:],
    }
    if rc != 0:
        _probe_metric().labels("nonzero-exit").inc()
        _PROBE_CACHE[cache_key] = (None, dict(diag))
        return None, diag
    try:
        platform, count = (stdout or "").split()[-2:]
        result = (platform, int(count))
    except (ValueError, IndexError):
        diag["outcome"] = "unparseable-stdout"
        diag["stdout_tail"] = (stdout or "")[-200:]
        _probe_metric().labels("unparseable-stdout").inc()
        _PROBE_CACHE[cache_key] = (None, dict(diag))
        return None, diag
    _probe_metric().labels("ok").inc()
    _PROBE_CACHE[cache_key] = (result, dict(diag))
    return result, diag


def probe_default_backend(
    cwd: str | None = None, timeout: int | None = None
) -> tuple[str, int] | None:
    """(platform, device_count) of the default jax backend, or None."""
    return probe_with_diagnostics(cwd, timeout)[0]


def probe_with_retries(
    attempts: int = 3,
    backoff_s: float = 20.0,
    timeout: int | None = None,
    log: list | None = None,
    cwd: str | None = None,
) -> tuple[str, int] | None:
    """Bounded-retry probe with backoff for the flaky tunnel (VERDICT r4
    item 1). Each attempt's diagnostics are appended to ``log``. Returns the
    first successful (platform, device_count), else None after ``attempts``."""
    for i in range(attempts):
        # bypass cache READS: a retry that returned the memoized failure
        # would never actually re-probe the flaky tunnel
        res, diag = probe_with_diagnostics(cwd, timeout, use_cache=False)
        diag["attempt"] = i + 1
        if log is not None:
            log.append(diag)
        if res is not None:
            return res
        if i + 1 < attempts:
            time.sleep(backoff_s)
    return None


def real_device_count(cwd: str | None = None,
                      timeout: int | None = None) -> int:
    """Number of real (non-CPU) devices, or 0 if none/unreachable."""
    res = probe_default_backend(cwd, timeout)
    if res is None or res[0] == "cpu":
        return 0
    return res[1]


def pin_cpu_if_unreachable(timeout: int | None = None,
                           cwd: str | None = None,
                           probe_cmd: list[str] | None = None) -> dict:
    """Startup guard for broker/worker processes: probe the default backend
    in a killable subprocess and PIN the CPU platform in-process when nothing
    real answers — the broker then serves on host devices instead of hanging
    at its first device touch. Returns the probe diagnostics (callers log
    them / feed the flight recorder). Idempotent: an already-pinned platform
    short-circuits through the env-pinned fast path."""
    res, diag = probe_with_diagnostics(cwd=cwd, timeout=timeout,
                                       probe_cmd=probe_cmd)
    if res is None or res[0] == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        diag["pinned"] = "cpu"
    return diag
