"""The storage IO seam: ONE indirection between the storage subsystems and
the filesystem (ISSUE 14).

Every durable-storage writer — the segmented journal, the snapshot store,
the cold tier, the backup store — routes its ``open``/``write``/``fsync``/
``replace`` calls through this module instead of calling the OS directly.
With no controller installed (production) every helper is a passthrough:
one module-global ``is None`` check per call. With a
:class:`~zeebe_tpu.testing.chaos_disk.DiskChaosController` installed
(``ZEEBE_CHAOS_DISK``), writes and fsyncs consult the seeded fault plan
first — EIO/ENOSPC, torn short-writes, fsync stalls, fsync failures land
exactly at the syscall boundary they would come from on real hardware.

The zlint rule ``storage-io-discipline`` machine-enforces the seam: direct
``open``/``os.open``/``os.fsync``/``os.replace``/``write_bytes`` calls
inside the storage modules are findings, so new storage code cannot
silently bypass fault injection (and with it, everything the torture gate
proves).
"""

from __future__ import annotations

import errno
import os
from pathlib import Path

#: the installed DiskChaosController (testing/chaos_disk.py) or None.
#: Installed once at process start (worker entry / test fixture) — not
#: mutated on the IO path, so unsynchronized reads are safe.
_controller = None


def install_controller(controller) -> None:
    """Install (or clear, with None) the process-wide disk-fault
    controller. Testing-only seam; production never calls it."""
    global _controller
    _controller = controller


def controller():
    return _controller


def _raise_write_fault(verdict: str, path) -> None:
    if verdict == "eio":
        raise OSError(errno.EIO, f"chaos write EIO on {path}")
    raise OSError(errno.ENOSPC, f"chaos write ENOSPC on {path}")


class _ChaosFile:
    """File-object proxy applying write faults; everything else delegates.
    Only constructed when a controller is installed AND the path is a
    storage path — the common case never pays the wrapper."""

    __slots__ = ("_f", "_path")

    def __init__(self, f, path) -> None:
        self._f = f
        self._path = path

    def write(self, data):
        c = _controller
        if c is None:  # controller uninstalled after this handle opened
            return self._f.write(data)
        verdict, prefix = c.write_fault(self._path, len(data))
        if verdict == "ok":
            return self._f.write(data)
        if verdict == "torn":
            # the classic short-write: a prefix reaches the file, then the
            # error surfaces — the caller's retry must overwrite the tear
            self._f.write(bytes(data[:prefix]))
            raise OSError(errno.EIO,
                          f"chaos torn write ({prefix}/{len(data)} bytes) "
                          f"on {self._path}")
        _raise_write_fault(verdict, self._path)

    def __getattr__(self, name):
        return getattr(self._f, name)

    # context-manager support must live on the proxy itself (dunder lookup
    # bypasses __getattr__)
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False

    def __iter__(self):
        return iter(self._f)


def open_file(path, mode: str = "rb"):
    """``open()`` for storage files. Write-capable handles come back
    fault-wrapped when disk chaos is armed."""
    f = open(path, mode)
    if _controller is not None and any(c in mode for c in "wa+x"):
        return _ChaosFile(f, path)
    return f


def os_open(path, flags: int, mode: int = 0o644) -> int:
    return os.open(path, flags, mode)


def fsync(fd: int, path=None) -> None:
    """``os.fsync`` with the chaos seam in front: a chaos fsync failure
    raises BEFORE the real fsync — after it, the page cache state of the
    simulated device is undefined, which is exactly the fsyncgate contract
    the journal's failed-flush handling is built against."""
    if _controller is not None:
        _controller.fsync_fault(path)
    os.fsync(fd)


def pwrite(fd: int, data: bytes, offset: int, path=None) -> int:
    if _controller is not None:
        verdict, prefix = _controller.write_fault(path, len(data))
        if verdict == "torn":
            os.pwrite(fd, bytes(data[:prefix]), offset)
            raise OSError(errno.EIO, f"chaos torn pwrite on {path}")
        if verdict != "ok":
            _raise_write_fault(verdict, path)
    return os.pwrite(fd, data, offset)


def pread(fd: int, length: int, offset: int) -> bytes:
    return os.pread(fd, length, offset)


def replace(src, dst) -> None:
    os.replace(src, dst)


def write_bytes(path, data: bytes) -> None:
    with open_file(path, "wb") as f:
        f.write(data)


def write_text(path, text: str, encoding: str = "utf-8") -> None:
    write_bytes(path, text.encode(encoding))


def read_bytes(path) -> bytes:
    return Path(path).read_bytes()


def fsync_path(path) -> None:
    """Open-fsync-close a path (file or directory) through the seam."""
    fd = os_open(path, os.O_RDONLY)
    try:
        fsync(fd, path)
    finally:
        os.close(fd)
