"""Persistent XLA compilation-cache setup, shared by every entry point.

Kernel-backend compiles over a TPU tunnel cost tens of seconds per geometry;
caching compiled executables on disk makes broker restarts, benchmark runs,
and redeploys start warm. Harmless on CPU. The cache is an optimization
only — any failure (read-only home, old jax) leaves compilation uncached.
"""

from __future__ import annotations

import os


def _host_fingerprint() -> str:
    """Cache entries embed AOT code compiled for the build host's CPU
    features; loading them on a different machine type is slow (XLA falls
    back feature by feature) or outright unsafe (SIGILL). Partition the
    cache per host so a reused home directory never serves foreign code."""
    import hashlib
    import platform

    feats = ""
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags") and not feats:
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                elif line.startswith("model name") and not model:
                    model = line.split(":", 1)[1].strip()
                if feats and model:
                    break
    except OSError:
        pass
    # jaxlib version is part of the key: XLA's target-feature tuning (e.g.
    # prefer-no-scatter) changes across releases, and a same-flags host
    # still mis-loads entries compiled under a different tuning (observed:
    # cpu_aot_loader "machine type doesn't match" warnings on every run)
    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "")
    except Exception:  # noqa: BLE001
        jl = ""
    raw = f"{platform.machine()}|{model}|{feats}|jaxlib={jl}"
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def enable_persistent_cache() -> None:
    try:
        import jax

        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.expanduser("~/.cache/zeebe_tpu_xla"))
        cache_dir = os.path.join(cache_dir, _host_fingerprint())
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001
        pass
