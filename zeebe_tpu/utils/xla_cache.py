"""Persistent XLA compilation-cache setup, shared by every entry point.

Kernel-backend compiles over a TPU tunnel cost tens of seconds per geometry;
caching compiled executables on disk makes broker restarts, benchmark runs,
and redeploys start warm. Harmless on CPU. The cache is an optimization
only — any failure (read-only home, old jax) leaves compilation uncached.
"""

from __future__ import annotations

import os


def enable_persistent_cache() -> None:
    try:
        import jax

        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.expanduser("~/.cache/zeebe_tpu_xla"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001
        pass
