"""Shared utilities."""

from zeebe_tpu.utils.time_util import InvalidTimerError, parse_cycle, parse_duration_millis

__all__ = ["InvalidTimerError", "parse_cycle", "parse_duration_millis"]
