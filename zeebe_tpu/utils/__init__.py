"""Shared utilities."""

from zeebe_tpu.utils.time_util import InvalidTimerError, parse_cycle, parse_duration_millis

__all__ = ["InvalidTimerError", "parse_cycle", "parse_duration_millis",
           "evict_oldest_half"]


def evict_oldest_half(cache: dict, limit: int) -> None:
    """Drop the oldest-inserted half of ``cache`` when it reached ``limit``
    (dicts iterate in insertion order) — the shared cheap-LRU idiom of the
    hot-path caches (key codec, record-frame cache, decoded-batch cache):
    one sweep every limit/2 insertions beats per-hit LRU bookkeeping."""
    if len(cache) >= limit:
        for key in list(cache)[: limit // 2]:
            cache.pop(key, None)
