"""Link-aware kernel dispatch routing.

The automaton kernel is ONE XLA program; *where* a group of commands runs is
a deployment decision dominated by the host↔accelerator link, not by the
program. On a properly attached accelerator (PCIe/ICI) a transfer costs
microseconds and any serving-sized group amortizes it; over a network tunnel
(development attach, e.g. a remote TPU) every transfer pays a latency floor
of tens to hundreds of milliseconds *regardless of size*, so the same group
finishes orders of magnitude sooner on the host XLA backend (the identical
program, compiled for CPU).

Rather than hard-coding either assumption, the router MEASURES the link once
(a tiny put+get round trip against the accelerator) and predicts each
backend's per-group cost: accelerator = transfers × measured link floor
(+ negligible compute), host = EMA of observed group wall times per shape
bucket. Each group routes to the cheaper backend, so a broker deployed next
to its accelerator uses it and a broker behind a slow tunnel degrades
gracefully — with the measurement exposed for observability instead of a
silent assumption. (The reference pins engine work to CPU threads and has no
analogue of accelerator placement; this router is the TPU-native design's
answer to heterogeneous attach topologies.)
"""

from __future__ import annotations

import threading
import time
from typing import Any

__all__ = ["BackendRouter", "shared_router"]


class BackendRouter:
    """Chooses the execution device for one kernel group.

    ``choose(bucket)`` returns the device to run on (or None = process
    default, when routing is disabled because the default backend already IS
    the host). ``record(bucket, device, seconds)`` feeds observed group wall
    times back so the host-cost model tracks reality.
    """

    #: transfers per group on the accelerator path: the group arrays upload
    #: (elem/phase/inst/def_of/var_slots/join_counts/done) plus the typical
    #: two chunk fetches of the packed event tensor
    UPLOADS_PER_GROUP = 7
    FETCHES_PER_GROUP = 2
    #: below this predicted link cost the accelerator is effectively local
    #: and wins by default (host EMA not yet seated)
    LOCAL_LINK_S = 2e-3
    _EMA_ALPHA = 0.3

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._measured = False
        self._accel = None
        self._host = None
        self.enabled = False
        self.link_put_s: float | None = None
        self.link_get_s: float | None = None
        # host-vs-device routing threshold (ISSUE 12): the accelerator must
        # beat the host prediction by at least this margin to win a group —
        # raising it biases groups host-ward (the kernel-routing controller
        # raises it during XLA recompile storms and decays it back to 0).
        # Runtime mutation belongs to that controller's actuator.
        self.route_threshold_s = 0.0
        self._host_ema: dict[Any, float] = {}
        self._accel_ema: dict[Any, float] = {}
        self.host_groups = 0
        self.accel_groups = 0

    # -- link measurement ---------------------------------------------------

    def _measure(self) -> None:
        """Measure the accelerator link in a KILLABLE SUBPROCESS. The tunnel
        hazard utils/backend_probe.py documents — first device use hanging
        forever — applies to the measurement itself: an in-process
        device_put against a wedged tunnel would block every partition
        sharing this router. A timed-out or failed probe leaves routing
        disabled (groups run on the process default device, the pre-router
        behavior)."""
        import jax

        self._measured = True
        try:
            # devices() is safe iff the default backend is already up —
            # every caller reaches the router from inside a kernel group,
            # after the entry point's own backend probe-and-pin
            accel = jax.devices()[0]
            host = jax.devices("cpu")[0]
        except Exception:  # noqa: BLE001 — no backend: routing stays off
            return
        self._accel = accel
        self._host = host
        if accel.platform == "cpu":
            return  # default backend already the host: nothing to route
        measured = _measure_link_subprocess()
        if measured is None:
            return
        self.link_put_s, self.link_get_s = measured
        self.enabled = True

    def link_cost_s(self) -> float | None:
        """Predicted accelerator link cost for one group (None = unmeasured)."""
        if self.link_put_s is None or self.link_get_s is None:
            return None
        return (self.UPLOADS_PER_GROUP * self.link_put_s
                + self.FETCHES_PER_GROUP * self.link_get_s)

    # -- routing --------------------------------------------------------------

    def accel_device(self):
        """The measured accelerator (None when routing is disabled — the
        process default backend already is the host). Quarantine canaries
        pin their dispatch here instead of asking :meth:`choose`: while
        QUARANTINED the kernel-routing controller holds
        ``route_threshold_s`` host-ward, and a canary the router quietly
        re-routes to the host would byte-match the host oracle by
        construction — re-proving the host, not the suspect device."""
        with self._lock:
            if not self._measured:
                self._measure()
            return self._accel if self.enabled else None

    def choose(self, bucket: Any):
        """Device for this group (None = process default device)."""
        with self._lock:
            if not self._measured:
                self._measure()
            if not self.enabled:
                return None
            link = self.link_cost_s()
            host_ema = self._host_ema.get(bucket)
            accel_total = (link + self._accel_ema.get(bucket, 0.0)
                           + self.route_threshold_s)
            if host_ema is None:
                # un-seated host model: only an effectively-local accelerator
                # skips the host trial run
                return self._accel if accel_total < self.LOCAL_LINK_S else self._host
            return self._accel if accel_total < host_ema else self._host

    def record(self, bucket: Any, device, seconds: float,
               first_run: bool = False) -> None:
        """``first_run``: first execution of this (program, shape) on this
        device — the observation includes XLA compilation, which is paid once
        and must not poison the steady-state cost model."""
        with self._lock:
            if device is self._accel:
                self.accel_groups += 1
                ema = self._accel_ema
                # observed accel time includes the link; keep the compute
                # residue so repeat predictions track real runs
                link = self.link_cost_s() or 0.0
                seconds = max(0.0, seconds - link)
            else:
                self.host_groups += 1
                ema = self._host_ema
            if first_run:
                return
            prev = ema.get(bucket)
            ema[bucket] = (seconds if prev is None
                           else prev + self._EMA_ALPHA * (seconds - prev))

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "link_put_ms": None if self.link_put_s is None else round(1e3 * self.link_put_s, 2),
            "link_get_ms": None if self.link_get_s is None else round(1e3 * self.link_get_s, 2),
            "route_threshold_ms": round(1e3 * self.route_threshold_s, 2),
            "host_groups": self.host_groups,
            "accel_groups": self.accel_groups,
        }


def _measure_link_subprocess(timeout: int = 120) -> tuple[float, float] | None:
    """(put_s, get_s) link floor measured in a killable subprocess, or None
    (wedged/failed probe). min-of-2 trials each way, tiny (8KB) payload — the
    floor, not the bandwidth, is what dominates serving-sized groups."""
    import subprocess
    import sys

    code = (
        "import time, numpy as np, jax\n"
        "d = jax.devices()[0]\n"
        "probe = np.zeros(2048, np.int32)\n"
        "puts, gets = [], []\n"
        "for _ in range(2):\n"
        "    t0 = time.perf_counter(); x = jax.device_put(probe, d); "
        "jax.block_until_ready(x); puts.append(time.perf_counter() - t0)\n"
        "    t0 = time.perf_counter(); jax.device_get(x); "
        "gets.append(time.perf_counter() - t0)\n"
        "print(min(puts), min(gets))\n"
    )
    try:
        import os

        proc = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout,
            capture_output=True, text=True, env=dict(os.environ),
        )
        if proc.returncode != 0:
            return None
        put_s, get_s = (float(v) for v in proc.stdout.split()[-2:])
        return put_s, get_s
    except Exception:  # noqa: BLE001 — timeout/parse: routing stays off
        return None


_shared: BackendRouter | None = None
_shared_lock = threading.Lock()


def shared_router() -> BackendRouter:
    """Process-wide router: the link measurement is paid once, shared by
    every partition's kernel backend."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = BackendRouter()
        return _shared
