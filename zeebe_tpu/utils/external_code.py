"""Dynamic loading of externally-shipped exporters and gateway interceptors.

Reference: util/src/main/java/io/camunda/zeebe/util/jar/ExternalJarRepository
.java:1 (exporter JARs loaded from configured paths at broker boot, each in
an isolated classloader) and gateway/src/main/java/io/camunda/zeebe/gateway/
interceptors/impl/InterceptorRepository.java:1 (gateway interceptor
artifacts). The tpu-native equivalent ships Python artifacts: a class is
named by ``CLASSNAME`` (dotted path, importable) and optionally located by
``PATH`` (a .py file or a directory added to the search path) — operators
drop a file next to the deployment instead of rebuilding the image.

Environment shapes (mirroring the reference's config tree):

    ZEEBE_BROKER_EXPORTERS_<ID>_CLASSNAME = mymod.MyExporter | MyExporter
    ZEEBE_BROKER_EXPORTERS_<ID>_PATH      = /opt/exporters/myexp.py   (opt)
    ZEEBE_BROKER_EXPORTERS_<ID>_ARGS_<K>  = value                      (opt)

    ZEEBE_GATEWAY_INTERCEPTORS_<ID>_CLASSNAME / _PATH                  (opt)
"""

from __future__ import annotations

import hashlib
import importlib
import importlib.util
import os
import sys
from typing import Any, Callable


def load_external_class(class_name: str, path: str | None = None) -> type:
    """Resolve ``class_name`` (``module.sub.Class`` or bare ``Class`` when
    ``path`` names the defining .py file) from an external artifact.

    ``path``: a .py file (loaded under a content-addressed module name, so
    two artifacts defining the same module name cannot collide — the
    classloader-isolation property of the reference's ExternalJarRepository)
    or a directory appended to ``sys.path``.
    """
    module = None
    if path:
        path = os.path.abspath(path)
        if os.path.isdir(path):
            if path not in sys.path:
                sys.path.append(path)
        else:
            mod_name = "_zb_ext_" + hashlib.sha256(path.encode()).hexdigest()[:12]
            module = sys.modules.get(mod_name)
            if module is None:
                spec = importlib.util.spec_from_file_location(mod_name, path)
                if spec is None or spec.loader is None:
                    raise ImportError(f"cannot load external artifact {path!r}")
                module = importlib.util.module_from_spec(spec)
                sys.modules[mod_name] = module
                try:
                    spec.loader.exec_module(module)
                except BaseException:
                    sys.modules.pop(mod_name, None)
                    raise
    if "." in class_name and module is None:
        mod_path, _, attr = class_name.rpartition(".")
        module = importlib.import_module(mod_path)
        class_name = attr
    if module is None:
        raise ImportError(
            f"external class {class_name!r} needs a dotted module path or an "
            "artifact PATH"
        )
    obj: Any = module
    for part in class_name.split("."):
        obj = getattr(obj, part)
    if not isinstance(obj, type):
        raise TypeError(f"{class_name!r} in {getattr(module, '__name__', path)!r} "
                        "is not a class")
    return obj


def _scan_env(env: dict[str, str], prefix: str) -> dict[str, dict[str, Any]]:
    """{id: {"classname":…, "path":…, "args": {k: v}}} from PREFIX_<ID>_*.

    The field suffix is matched from the RIGHT so ids may contain
    underscores (ZEEBE_BROKER_EXPORTERS_AUDIT_LOG_CLASSNAME → id
    ``audit_log``)."""
    out: dict[str, dict[str, Any]] = {}
    for var, value in env.items():
        if not var.startswith(prefix):
            continue
        rest = var[len(prefix):]
        # ARGS first: an ARG key may itself end in CLASSNAME/PATH
        # (…_S3_ARGS_INDEX_PATH is s3's arg, not a phantom exporter's path)
        if "_ARGS_" in rest:
            ext_id, _, arg = rest.partition("_ARGS_")
            field = "args"
        elif rest.endswith("_CLASSNAME"):
            ext_id, field, arg = rest[:-len("_CLASSNAME")], "classname", None
        elif rest.endswith("_PATH"):
            ext_id, field, arg = rest[:-len("_PATH")], "path", None
        else:
            continue
        if not ext_id:
            continue
        entry = out.setdefault(ext_id.lower(), {"args": {}})
        if field == "args":
            entry["args"][arg.lower()] = value
        else:
            entry[field] = value
    return {eid: e for eid, e in out.items() if e.get("classname")}


def exporters_factory_from_env(
    env: dict[str, str] | None = None,
) -> Callable[[], dict[str, tuple[Any, dict]]] | None:
    """A per-partition exporter factory from ``ZEEBE_BROKER_EXPORTERS_*``,
    or None when nothing is configured. Classes resolve at CALL time (boot),
    once per partition instantiation — each partition gets fresh instances,
    with the configured ARGS as the exporter's configuration dict."""
    env = dict(os.environ if env is None else env)
    specs = _scan_env(env, "ZEEBE_BROKER_EXPORTERS_")
    if not specs:
        return None

    def factory() -> dict[str, tuple[Any, dict]]:
        out: dict[str, tuple[Any, dict]] = {}
        for ext_id, spec in sorted(specs.items()):
            cls = load_external_class(spec["classname"], spec.get("path"))
            out[ext_id] = (cls(), spec["args"])
        return out

    return factory


def gateway_interceptors_from_env(
    env: dict[str, str] | None = None,
) -> tuple:
    """Instantiated gRPC server interceptors from
    ``ZEEBE_GATEWAY_INTERCEPTORS_*`` (reference: InterceptorRepository →
    interceptor chain ahead of every handler), ordered by id."""
    env = dict(os.environ if env is None else env)
    specs = _scan_env(env, "ZEEBE_GATEWAY_INTERCEPTORS_")
    return tuple(
        load_external_class(spec["classname"], spec.get("path"))()
        for _eid, spec in sorted(specs.items())
    )
