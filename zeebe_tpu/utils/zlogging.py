"""Structured logging: per-subsystem logger hierarchy + Stackdriver JSON layout.

Reference: dist/src/main/config/log4j2.xml — a Console (pattern) appender and
a Stackdriver (JSON) appender selected by ``ZEEBE_LOG_APPENDER``, level bound
to ``ZEEBE_LOG_LEVEL``, service name/version from
``ZEEBE_LOG_STACKDRIVER_SERVICENAME`` / ``_SERVICEVERSION``; per-subsystem
``Loggers`` classes (broker/src/main/java/io/camunda/zeebe/broker/Loggers.java,
engine/…, gateway/…); the JSON entry shape follows
util/src/main/java/io/camunda/zeebe/util/logging/stackdriver/StackdriverLogEntry.java
(severity, message, logging.googleapis.com/sourceLocation, serviceContext,
context, timestampSeconds/Nanos, exception).
"""

from __future__ import annotations

import io
import json
import logging
import os
import traceback

_SEVERITY = {
    logging.DEBUG: "DEBUG",
    logging.INFO: "INFO",
    logging.WARNING: "WARNING",
    logging.ERROR: "ERROR",
    logging.CRITICAL: "CRITICAL",
}

_ERROR_REPORT_TYPE = (
    "type.googleapis.com/google.devtools.clouderrorreporting.v1beta1.ReportedErrorEvent"
)


class StackdriverFormatter(logging.Formatter):
    """One JSON object per line, Google Cloud Logging special fields
    (reference: StackdriverLogEntryBuilder)."""

    def __init__(self, service_name: str = "", service_version: str = "") -> None:
        super().__init__()
        self.service_name = service_name
        self.service_version = service_version

    def format(self, record: logging.LogRecord) -> str:
        entry: dict = {
            "severity": _SEVERITY.get(record.levelno, "DEFAULT"),
            "message": record.getMessage(),
            "logging.googleapis.com/sourceLocation": {
                "file": record.pathname,
                "line": record.lineno,
                "function": record.funcName,
            },
            "context": {
                "threadName": record.threadName,
                "loggerName": record.name,
            },
            "timestampSeconds": int(record.created),
            "timestampNanos": int((record.created % 1) * 1e9),
        }
        if self.service_name or self.service_version:
            entry["serviceContext"] = {
                "service": self.service_name,
                "version": self.service_version,
            }
        if record.exc_info:
            buf = io.StringIO()
            traceback.print_exception(*record.exc_info, file=buf)
            entry["exception"] = buf.getvalue()
            if record.levelno >= logging.ERROR:
                # error-reporting ingestion marker (reference: @type on
                # ERROR+ entries carrying an exception)
                entry["@type"] = _ERROR_REPORT_TYPE
        return json.dumps(entry, separators=(",", ":"), default=str)


_CONSOLE_PATTERN = (
    "%(asctime)s.%(msecs)03d [%(threadName)s] %(levelname)-5s %(name)s - %(message)s"
)

# explicit name → level map (reference log4j2 accepts TRACE; and resolving
# arbitrary env strings via getattr(logging, …) could hit unrelated module
# attributes like raiseExceptions). Unknown names fall back to INFO.
_LEVELS = {
    "TRACE": logging.DEBUG,  # python logging has no TRACE tier
    "DEBUG": logging.DEBUG,
    "INFO": logging.INFO,
    "WARN": logging.WARNING,
    "WARNING": logging.WARNING,
    "ERROR": logging.ERROR,
    "FATAL": logging.CRITICAL,
    "CRITICAL": logging.CRITICAL,
}


def configure_logging(appender: str | None = None, level: str | None = None,
                      service_name: str | None = None,
                      service_version: str | None = None,
                      stream=None) -> logging.Handler:
    """Install the selected appender on the ``zeebe_tpu`` logger hierarchy
    (reference: log4j2.xml root appender ref ``${env:ZEEBE_LOG_APPENDER:-
    Console}``). Returns the installed handler."""
    appender = (appender or os.environ.get("ZEEBE_LOG_APPENDER", "console")).lower()
    level_name = (level or os.environ.get("ZEEBE_LOG_LEVEL", "info")).upper()
    if appender == "stackdriver":
        formatter: logging.Formatter = StackdriverFormatter(
            service_name=service_name
            or os.environ.get("ZEEBE_LOG_STACKDRIVER_SERVICENAME", ""),
            service_version=service_version
            or os.environ.get("ZEEBE_LOG_STACKDRIVER_SERVICEVERSION", ""),
        )
    else:
        formatter = logging.Formatter(_CONSOLE_PATTERN, datefmt="%Y-%m-%d %H:%M:%S")
    handler = logging.StreamHandler(stream)
    handler.setFormatter(formatter)
    root = logging.getLogger("zeebe_tpu")
    for old in list(root.handlers):
        root.removeHandler(old)
    root.addHandler(handler)
    root.setLevel(_LEVELS.get(level_name.upper(), logging.INFO))
    root.propagate = False
    return handler


class Loggers:
    """Per-subsystem loggers (reference: the per-module Loggers classes —
    io.camunda.zeebe.broker.*, engine processing, gateway, raft, journal)."""

    SYSTEM = logging.getLogger("zeebe_tpu.broker.system")
    CLUSTERING = logging.getLogger("zeebe_tpu.broker.clustering")
    TRANSPORT = logging.getLogger("zeebe_tpu.broker.transport")
    LOGSTREAMS = logging.getLogger("zeebe_tpu.logstreams")
    JOURNAL = logging.getLogger("zeebe_tpu.journal")
    RAFT = logging.getLogger("zeebe_tpu.raft")
    SNAPSHOT = logging.getLogger("zeebe_tpu.snapshot")
    STREAM_PROCESSING = logging.getLogger("zeebe_tpu.stream")
    PROCESS_PROCESSOR = logging.getLogger("zeebe_tpu.engine.processing")
    GATEWAY = logging.getLogger("zeebe_tpu.gateway")
    JOB_STREAM = logging.getLogger("zeebe_tpu.gateway.jobstream")
    EXPORTERS = logging.getLogger("zeebe_tpu.broker.exporter")
    KERNEL = logging.getLogger("zeebe_tpu.kernel_backend")
    TOPOLOGY = logging.getLogger("zeebe_tpu.topology")
    BACKUP = logging.getLogger("zeebe_tpu.backup")

    @staticmethod
    def exporter_logger(exporter_id: str) -> logging.Logger:
        """Per-exporter child logger (reference: Loggers.getExporterLogger)."""
        return logging.getLogger(f"zeebe_tpu.broker.exporter.{exporter_id}")
