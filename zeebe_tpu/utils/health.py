"""Component health aggregation.

Reference: scheduler/src/main/java/io/camunda/zeebe/scheduler/health/
CriticalComponentsHealthMonitor.java:26 — named components report
HEALTHY/UNHEALTHY/DEAD; the monitor aggregates to the worst status; partition
health feeds broker health (BrokerHealthCheckService) and the startup/ready/
liveness probes on the management server.
"""

from __future__ import annotations

import enum
from typing import Callable

from zeebe_tpu.utils.zlogging import Loggers


class HealthStatus(enum.IntEnum):
    # ordered by severity so aggregation is max()
    HEALTHY = 0
    # a component is limping but self-healing (e.g. an exporter in retry
    # backoff): the node keeps serving, probes stay green, operators see it
    DEGRADED = 1
    UNHEALTHY = 2
    DEAD = 3


class HealthReport:
    def __init__(self, component: str, status: HealthStatus,
                 message: str = "") -> None:
        self.component = component
        self.status = status
        self.message = message

    def to_dict(self) -> dict:
        return {"component": self.component, "status": self.status.name,
                "message": self.message}


class CriticalComponentsHealthMonitor:
    """Aggregates component healths; listeners fire on any status change."""

    def __init__(self, name: str = "broker") -> None:
        self.name = name
        self._components: dict[str, HealthReport] = {}
        self._listeners: list[Callable[[HealthReport], None]] = []

    def register(self, component: str) -> None:
        self._components.setdefault(
            component, HealthReport(component, HealthStatus.HEALTHY)
        )

    def add_listener(self, listener: Callable[[HealthReport], None]) -> None:
        self._listeners.append(listener)

    def deregister(self, component: str) -> None:
        """Forget a component (e.g. a partition replica moved off this node);
        its last report must not pin the aggregate health forever."""
        self._components.pop(component, None)

    def deregister_matching(self, prefix: str) -> None:
        """Forget every component under a prefix (a stopped partition takes
        its exporter sub-components with it)."""
        for component in [c for c in self._components if c.startswith(prefix)]:
            self._components.pop(component, None)

    def report(self, component: str, status: HealthStatus, message: str = "") -> None:
        # the component map is updated BEFORE listeners fire: a listener that
        # throws (or reads back status()) must observe the new report, never
        # a half-applied monitor
        previous = self._components.get(component)
        report = HealthReport(component, status, message)
        self._components[component] = report
        if previous is None or previous.status != status:
            for listener in self._listeners:
                try:
                    listener(report)
                except Exception:  # noqa: BLE001 — one bad listener must not
                    # starve the rest (probes, metrics) of the status change
                    Loggers.SYSTEM.exception(
                        "health listener failed for %s -> %s",
                        component, status.name)

    def status(self) -> HealthStatus:
        if not self._components:
            return HealthStatus.HEALTHY
        return max(r.status for r in self._components.values())

    def is_healthy(self) -> bool:
        # DEGRADED keeps serving: probes must not evict a node whose only
        # problem is a backing-off exporter
        return self.status() <= HealthStatus.DEGRADED

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "status": self.status().name,
            "components": [r.to_dict() for r in self._components.values()],
        }
