"""Component health aggregation.

Reference: scheduler/src/main/java/io/camunda/zeebe/scheduler/health/
CriticalComponentsHealthMonitor.java:26 — named components report
HEALTHY/UNHEALTHY/DEAD; the monitor aggregates to the worst status; partition
health feeds broker health (BrokerHealthCheckService) and the startup/ready/
liveness probes on the management server.
"""

from __future__ import annotations

import enum
from typing import Callable


class HealthStatus(enum.IntEnum):
    # ordered by severity so aggregation is max()
    HEALTHY = 0
    UNHEALTHY = 1
    DEAD = 2


class HealthReport:
    def __init__(self, component: str, status: HealthStatus,
                 message: str = "") -> None:
        self.component = component
        self.status = status
        self.message = message

    def to_dict(self) -> dict:
        return {"component": self.component, "status": self.status.name,
                "message": self.message}


class CriticalComponentsHealthMonitor:
    """Aggregates component healths; listeners fire on any status change."""

    def __init__(self, name: str = "broker") -> None:
        self.name = name
        self._components: dict[str, HealthReport] = {}
        self._listeners: list[Callable[[HealthReport], None]] = []

    def register(self, component: str) -> None:
        self._components.setdefault(
            component, HealthReport(component, HealthStatus.HEALTHY)
        )

    def add_listener(self, listener: Callable[[HealthReport], None]) -> None:
        self._listeners.append(listener)

    def deregister(self, component: str) -> None:
        """Forget a component (e.g. a partition replica moved off this node);
        its last report must not pin the aggregate health forever."""
        self._components.pop(component, None)

    def report(self, component: str, status: HealthStatus, message: str = "") -> None:
        previous = self._components.get(component)
        report = HealthReport(component, status, message)
        self._components[component] = report
        if previous is None or previous.status != status:
            for listener in self._listeners:
                listener(report)

    def status(self) -> HealthStatus:
        if not self._components:
            return HealthStatus.HEALTHY
        return max(r.status for r in self._components.values())

    def is_healthy(self) -> bool:
        return self.status() == HealthStatus.HEALTHY

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "status": self.status().name,
            "components": [r.to_dict() for r in self._components.values()],
        }
