"""ISO-8601 duration/cycle parsing for timer definitions.

Reference: the engine's timer transformation uses the BPMN timer definitions
(duration PT5S, cycles R3/PT10S, dates) evaluated via FEEL; this module is the
duration arithmetic behind it.
"""

from __future__ import annotations

import re

_DURATION_RE = re.compile(
    r"^P(?:(?P<days>\d+(?:\.\d+)?)D)?"
    r"(?:T(?:(?P<hours>\d+(?:\.\d+)?)H)?(?:(?P<minutes>\d+(?:\.\d+)?)M)?"
    r"(?:(?P<seconds>\d+(?:\.\d+)?)S)?)?$"
)
_CYCLE_RE = re.compile(r"^R(?P<reps>\d*)/(?P<dur>.+)$")


class InvalidTimerError(ValueError):
    pass


def parse_duration_millis(text: str) -> int:
    """'PT5S' → 5000. Supports D/H/M/S components (weeks/months are rejected,
    matching the engine's interval subset)."""
    m = _DURATION_RE.match(text.strip())
    if not m or text.strip() in ("P", "PT"):
        raise InvalidTimerError(f"invalid ISO-8601 duration: {text!r}")
    days = float(m.group("days") or 0)
    hours = float(m.group("hours") or 0)
    minutes = float(m.group("minutes") or 0)
    seconds = float(m.group("seconds") or 0)
    if days == hours == minutes == seconds == 0 and "0" not in text:
        raise InvalidTimerError(f"empty duration: {text!r}")
    return int(((days * 24 + hours) * 60 + minutes) * 60000 + seconds * 1000)


def parse_cycle(text: str) -> tuple[int, int]:
    """'R3/PT10S' → (3, 10000); 'R/PT10S' → (-1, 10000) (infinite)."""
    m = _CYCLE_RE.match(text.strip())
    if not m:
        raise InvalidTimerError(f"invalid ISO-8601 cycle: {text!r}")
    reps = int(m.group("reps")) if m.group("reps") else -1
    return reps, parse_duration_millis(m.group("dur"))
