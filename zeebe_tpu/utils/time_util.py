"""ISO-8601 duration/cycle parsing for timer definitions.

Reference: the engine's timer transformation uses the BPMN timer definitions
(duration PT5S, cycles R3/PT10S, dates) evaluated via FEEL; this module is the
duration arithmetic behind it.
"""

from __future__ import annotations

import re

_CYCLE_RE = re.compile(r"^R(?P<reps>\d*)/(?P<dur>.+)$")


class InvalidTimerError(ValueError):
    pass


def parse_duration_millis(text: str) -> int:
    """'PT5S' → 5000. Timer intervals are non-negative days-time spans
    (years/months and negative spans are rejected — the engine's interval
    subset). Delegates to the single ISO-duration parser in feel.temporal."""
    from zeebe_tpu.feel.temporal import Duration, TemporalParseError, parse_duration

    try:
        d = parse_duration(text)
    except TemporalParseError as exc:
        raise InvalidTimerError(f"invalid ISO-8601 duration: {text!r}") from exc
    if not isinstance(d, Duration) or d.millis < 0:
        raise InvalidTimerError(f"not a timer interval: {text!r}")
    return d.millis


def parse_cycle(text: str) -> tuple[int, int]:
    """'R3/PT10S' → (3, 10000); 'R/PT10S' → (-1, 10000) (infinite)."""
    m = _CYCLE_RE.match(text.strip())
    if not m:
        raise InvalidTimerError(f"invalid ISO-8601 cycle: {text!r}")
    reps = int(m.group("reps")) if m.group("reps") else -1
    return reps, parse_duration_millis(m.group("dur"))
