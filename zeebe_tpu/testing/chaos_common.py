"""Shared machinery for the seeded chaos planes (ISSUE 15 satellite).

``chaos_tcp`` (PR 9), ``chaos_disk`` (PR 14), and ``chaos_device`` (PR 15)
each wrap one liar — the network, the disk, the accelerator — behind the
same evidence discipline, and by PR 14 the mechanical halves of that
discipline had drift-copied twice:

- **per-member RNG derivation** — every plane seeds
  ``random.Random(seed ^ crc32(member_id))`` so one seed describes the
  whole fleet while distinct members never mirror each other's decisions;
- **spec field parsing** — ``key=value`` comma fields inside ``;`` sections
  (the ``format_spec``/``parse_spec`` round-trip each plane pins in tests);
- **per-life counts snapshots** — throttled atomic dumps of the applied-
  fault counters, one file per process life, so a SIGKILLed worker loses at
  most one dump interval of observations and a configured-but-never-applied
  fault class is a *gate violation*, never silent coverage;
- **JSONL evidence ledgers** — line-flushed append-only records of
  individual injections (bit-rot flips, result corruptions) the offline
  checkers join against detection/repair evidence.

This module is their one home; the zlint drift-copy rule no longer has to
look away from the chaos planes. Spec *fields* stay owned by each plane
(the fault classes genuinely differ); only the mechanics live here.
"""

from __future__ import annotations

import json
import logging
import os
import random
import time
import zlib

logger = logging.getLogger("zeebe_tpu.testing.chaos_common")

#: throttle for the per-life counts snapshots: a SIGKILL loses at most this
#: many seconds of observed-fault evidence
COUNTS_DUMP_INTERVAL_S = 2.0


def member_rng(seed: int, member_id: str) -> random.Random:
    """The per-member fault stream every chaos plane derives: one seed
    describes the fleet, ``crc32(member)`` keeps members from mirroring
    each other's decisions."""
    return random.Random(seed ^ zlib.crc32(member_id.encode("utf-8")))


def parse_spec_fields(section: str, setters: dict) -> None:
    """Apply one ``key=value,key=value`` spec section through ``setters``
    (key → one-arg callable). Unknown keys are ignored (forward compat:
    an older worker must boot under a newer harness's spec)."""
    for fld in section.split(","):
        key, _, value = fld.partition("=")
        setter = setters.get(key.strip())
        if setter is not None:
            setter(value)


class CountsSnapshot:
    """Throttled atomic per-life counts dump (``<file>.tmp`` + rename).
    The consistency/torture/device-chaos reports aggregate these as the
    OBSERVED fault evidence; ``counts_file`` is None until a harness-run
    worker entry assigns it, so production processes never write."""

    def __init__(self, member_id: str) -> None:
        self.member_id = member_id
        self.counts_file: str | None = None
        self._last_dump = 0.0

    def maybe_dump(self, counts: dict) -> None:
        if self.counts_file is None:
            return
        now = time.time()
        if now - self._last_dump < COUNTS_DUMP_INTERVAL_S:
            return
        self._last_dump = now
        try:
            payload = json.dumps({"member": self.member_id, **counts})
            tmp = f"{self.counts_file}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(payload)
            os.replace(tmp, self.counts_file)
        except OSError:  # pragma: no cover — evidence is best-effort
            pass


class JsonlLedger:
    """Line-flushed JSONL evidence ledger (bit-rot flips, injected result
    corruptions). Unlike the throttled counts snapshot this is flushed per
    entry — the ledger is the authoritative applied count for fault
    classes whose individual occurrences the offline checkers must join
    against detection evidence."""

    def __init__(self) -> None:
        self.path: str | None = None

    def append(self, entry: dict) -> None:
        if self.path is None:
            return
        try:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(entry, separators=(",", ":")) + "\n")
                f.flush()
        except OSError:  # pragma: no cover — evidence is best-effort
            pass


def read_jsonl_ledgers(paths) -> list[dict]:
    """Merge JSONL ledger files (harness-side), skipping torn tail lines
    of SIGKILLed workers."""
    out: list[dict] = []
    for path in paths:
        try:
            lines = path.read_text(encoding="utf-8").splitlines()
        except OSError:
            continue
        for line in lines:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail line of a killed worker
    return out


def sum_counts_files(paths) -> dict[str, int]:
    """Aggregate per-life counts snapshots (harness-side): integer fields
    sum across every process life and every member."""
    totals: dict[str, int] = {}
    for path in paths:
        try:
            counts = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        for key, value in counts.items():
            if isinstance(value, int):
                totals[key] = totals.get(key, 0) + value
    return totals
