"""Jepsen-shaped consistency checker for the multi-process TCP cluster.

ISSUE 9's gate: under injected TCP faults (testing/chaos_tcp.py), worker
kill storms, and a deterministic crash-between-append-and-reply, the
gateway's acked-command semantics must be **exactly-once**:

- **no acked command lost** — every request the gateway acked appears in
  the partition's committed log AND in the export stream;
- **no duplicate application** — a request id appears on at most ONE
  command position per partition (export-stream evidence, positions
  CRC-deduped so at-least-once re-exports must be byte-identical);
- **rejections are terminal** — one request's logged replies never mix
  rejections with results;
- **gateway-observed positions are monotone per partition** — the driver
  submits sequentially per partition, so first-ack command positions must
  strictly increase in completion order.

The harness (:func:`run_consistency`) boots a REAL supervised worker
cluster over TCP (the PR 7 stack end to end: typed error frames,
same-worker resends, re-routes, reconnect retry, leader fencing), records
every client submit/ack/reject with its routing evidence
(``MultiProcClusterRuntime.submit(meta=...)``), every exported record
(:class:`JsonlExporter` running inside the worker processes), executes a
seeded schedule of ``kill_worker`` storms and link-partition windows, then
reads the workers' journals offline and checks the history. One worker is
armed with ``ZEEBE_CHAOS_CRASH_AFTER_APPENDS`` so the
crash-between-append-and-reply → resend → dedupe sequence happens by
construction, and a post-drive probe (:func:`_dedupe_replay_probe`) kills a
leader and resends an already-answered envelope to prove the replicated
dedupe table replays the stored reply across a process death.

``bench.py --consistency [--quick]`` runs this and writes
``CONSISTENCY[_quick].json``; the CI ``consistency-smoke`` job gates on it.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import sys
import threading
import time
import zlib
from pathlib import Path
from typing import Any

logger = logging.getLogger("zeebe_tpu.testing.consistency")


# ---------------------------------------------------------------------------
# export-stream evidence (runs INSIDE the worker processes)


from zeebe_tpu.exporters.api import Exporter as _ExporterBase  # noqa: E402


class JsonlExporter(_ExporterBase):
    """Append-only JSONL export stream: one line per exported record with
    position, request identity, and a CRC over the re-encoded frame. Each
    container lifetime writes its own file (a supervisor-restarted worker's
    exporter re-exports from its recovered cursor — at-least-once), so the
    checker can prove re-exported positions byte-identical via the CRC.
    Loaded into workers through ``ZEEBE_BROKER_EXPORTERS_*``."""

    def configure(self, context) -> None:
        super().configure(context)
        self._dir = Path(context.configuration["dir"])

    def open(self, controller) -> None:
        self._controller = controller
        self._dir.mkdir(parents=True, exist_ok=True)
        name = f"export-{os.getpid()}-{time.monotonic_ns()}.jsonl"
        self._f = open(self._dir / name, "a", encoding="utf-8")

    def export(self, record) -> None:
        rec = record.record
        frame = rec.encode()[0]
        self._f.write(json.dumps({
            "pt": rec.partition_id,
            "p": record.position,
            "src": record.source_position,
            "rt": int(rec.record_type),
            "vt": int(rec.value_type),
            "it": int(rec.intent),
            "sid": rec.request_stream_id,
            "rid": rec.request_id,
            "crc": zlib.crc32(frame) & 0xFFFFFFFF,
        }, separators=(",", ":")) + "\n")
        # flush per record: a SIGKILLed worker must not lose acked export
        # evidence from its userspace buffer (rates here are checker-scale)
        self._f.flush()
        self._controller.update_last_exported_position(record.position)

    def close(self) -> None:
        try:
            self._f.close()
        except (OSError, AttributeError):
            pass


# ---------------------------------------------------------------------------
# history + checker (pure functions — unit-testable without a cluster)


@dataclasses.dataclass
class ClientOp:
    """One client request as the gateway observed it."""

    index: int
    partition: int
    kind: str                      # "deploy" | "create" | "create-missing"
    outcome: str = "pending"       # ack | rejected | backpressure | deadline
                                   # | no-leader | error
    request_id: int = -1
    position: int = -1
    worker: str | None = None
    resends: int = 0
    reroutes: int = 0
    dedupe: str | None = None      # "replayed" when answered from the table
    rejection: str | None = None
    submit_ms: float = 0.0
    done_ms: float = 0.0

    def row(self) -> dict:
        return dataclasses.asdict(self)


def submit_client_op(runtime, partition: int, kind: str, record, *,
                     history: list, history_lock, op_seq: list, clock_ms,
                     timeout_s: float) -> ClientOp:
    """One sequential-driver client request against the multi-process
    runtime, recorded with its routing evidence — the shared submit half
    of the consistency / torture / device-chaos harness drivers."""
    with history_lock:
        op_seq[0] += 1
        op = ClientOp(index=op_seq[0], partition=partition, kind=kind,
                      submit_ms=clock_ms())
    meta: dict = {}
    try:
        result = runtime.submit(partition, record, timeout_s=timeout_s,
                                meta=meta)
        op.outcome = "rejected" if result.is_rejection else "ack"
        if result.is_rejection:
            op.rejection = result.rejection_type.name
    except Exception as exc:  # noqa: BLE001 — typed below
        from zeebe_tpu.gateway.broker_client import (
            DeadlineExceededError,
            NoLeaderError,
            ResourceExhaustedError,
        )

        op.outcome = (
            "backpressure" if isinstance(exc, ResourceExhaustedError)
            else "deadline" if isinstance(exc, DeadlineExceededError)
            else "no-leader" if isinstance(exc, NoLeaderError)
            else "error")
        if op.outcome == "error":
            op.rejection = repr(exc)[:200]
    op.done_ms = clock_ms()
    op.request_id = meta.get("requestId", -1)
    op.position = meta.get("commandPosition", -1)
    op.worker = meta.get("worker")
    op.resends = meta.get("resends", 0)
    op.reroutes = meta.get("reroutes", 0)
    op.dedupe = meta.get("dedupe")
    with history_lock:
        history.append(op)
    return op


def check_consistency(history: list[ClientOp],
                      logs: dict[int, list[dict]],
                      exports: dict[int, dict[int, dict]] | None = None,
                      ) -> list[str]:
    """The invariant suite over a finished run.

    ``logs``: per partition, the authoritative committed log as dicts with
    keys ``p`` (position), ``rt`` (record type int), ``rid``, ``sid``,
    ``rej`` (is_rejection). ``exports``: per partition, position → export
    line (already CRC-verified across duplicates by the caller).
    """
    from zeebe_tpu.protocol import RecordType

    violations: list[str] = []
    command_rt = int(RecordType.COMMAND)
    rejection_rt = int(RecordType.COMMAND_REJECTION)

    by_partition_cmds: dict[int, dict[int, list[int]]] = {}
    for partition, records in logs.items():
        cmd_positions: dict[int, list[int]] = {}
        reply_kinds: dict[int, set[str]] = {}
        for rec in records:
            rid = rec.get("rid", -1)
            if rid < 0:
                continue
            if rec["rt"] == command_rt:
                cmd_positions.setdefault(rid, []).append(rec["p"])
            else:
                kind = "rejection" if rec["rt"] == rejection_rt else "result"
                reply_kinds.setdefault(rid, set()).add(kind)
        by_partition_cmds[partition] = cmd_positions
        # no duplicate application: a request id owns at most one command
        for rid, positions in cmd_positions.items():
            if len(positions) > 1:
                violations.append(
                    f"partition {partition}: request {rid} appended "
                    f"{len(positions)} times at positions {positions} "
                    f"(duplicate application)")
        # rejections are terminal: one request's replies never mix kinds
        for rid, kinds in reply_kinds.items():
            if len(kinds) > 1:
                violations.append(
                    f"partition {partition}: request {rid} has both a "
                    f"rejection and a result reply (rejection not terminal)")

    last_ack_position: dict[int, int] = {}
    acked = [op for op in sorted(history, key=lambda o: o.done_ms)
             if op.outcome == "ack"]
    for op in acked:
        cmds = by_partition_cmds.get(op.partition, {})
        positions = cmds.get(op.request_id, [])
        # no acked command lost (log evidence)
        if not positions:
            violations.append(
                f"partition {op.partition}: acked request {op.request_id} "
                f"(op #{op.index}) has no command in the log (acked loss)")
            continue
        if op.position >= 0 and positions != [op.position]:
            violations.append(
                f"partition {op.partition}: acked request {op.request_id} "
                f"acked position {op.position} but the log has it at "
                f"{positions}")
        # no acked command lost (export-stream evidence)
        if exports is not None:
            exported = exports.get(op.partition, {})
            if positions[0] not in exported:
                violations.append(
                    f"partition {op.partition}: acked request "
                    f"{op.request_id} at {positions[0]} never exported "
                    f"(acked loss on the export stream)")
        # monotone per partition: sequential driver ⇒ strictly increasing
        # first-ack positions in completion order
        prev = last_ack_position.get(op.partition)
        if prev is not None and positions[0] <= prev:
            violations.append(
                f"partition {op.partition}: acked position {positions[0]} "
                f"(op #{op.index}) not after previous ack {prev} "
                f"(gateway-observed positions regressed)")
        last_ack_position[op.partition] = positions[0]

    return violations


# ---------------------------------------------------------------------------
# offline evidence collection


def read_partition_log(stream_dir: Path, partition_id: int) -> list[dict]:
    """Decode one replica's materialized stream journal (the committed
    prefix) into checker rows. Opens read-write AFTER teardown — the
    journal's own open() truncates any crash-torn suffix exactly like a
    real recovery would."""
    from zeebe_tpu.journal import SegmentedJournal
    from zeebe_tpu.logstreams import LogStream

    journal = SegmentedJournal(stream_dir)
    try:
        stream = LogStream(journal, partition_id)
        out = []
        for logged in stream.new_reader(1):
            rec = logged.record
            out.append({
                "p": logged.position,
                "src": logged.source_position,
                "rt": int(rec.record_type),
                "vt": int(rec.value_type),
                "it": int(rec.intent),
                "rid": rec.request_id,
                "sid": rec.request_stream_id,
                "rej": rec.is_rejection,
                "crc": zlib.crc32(rec.encode()[0]) & 0xFFFFFFFF,
            })
        return out
    finally:
        journal.close()


def collect_logs(data_dir: Path, workers: list[str],
                 partitions: int) -> tuple[dict[int, list[dict]], list[str]]:
    """Per partition: every replica's committed log, cross-checked — the
    overlapping prefixes of two replicas must agree record-for-record
    (same frame CRC at the same position) — and the longest replica's log
    as the authoritative one."""
    logs: dict[int, list[dict]] = {}
    violations: list[str] = []
    for pid in range(1, partitions + 1):
        replicas: list[tuple[str, list[dict]]] = []
        for worker in workers:
            stream_dir = data_dir / worker / f"partition-{pid}" / "stream"
            if stream_dir.exists():
                try:
                    replicas.append((worker, read_partition_log(stream_dir, pid)))
                except Exception as exc:  # noqa: BLE001 — a torn replica is
                    violations.append(    # evidence, not a crash
                        f"partition {pid}: replica {worker} unreadable: {exc}")
        if not replicas:
            logs[pid] = []
            continue
        by_position: dict[int, tuple[str, dict]] = {}
        for worker, records in replicas:
            for rec in records:
                seen = by_position.get(rec["p"])
                if seen is None:
                    by_position[rec["p"]] = (worker, rec)
                elif seen[1]["crc"] != rec["crc"]:
                    violations.append(
                        f"partition {pid}: position {rec['p']} diverges "
                        f"between replicas {seen[0]} and {worker} "
                        f"(committed-log split-brain)")
        replicas.sort(key=lambda wr: len(wr[1]), reverse=True)
        logs[pid] = replicas[0][1]
    return logs, violations


def collect_exports(export_dir: Path) -> tuple[dict[int, dict[int, dict]],
                                               list[str], int]:
    """Merge every container lifetime's JSONL stream. Re-exported positions
    (at-least-once across restarts) must be byte-identical — divergent CRCs
    are violations. Returns (per-partition position→line, violations,
    re-exported line count)."""
    exports: dict[int, dict[int, dict]] = {}
    violations: list[str] = []
    re_exports = 0
    for path in sorted(export_dir.glob("export-*.jsonl")):
        try:
            lines = path.read_text(encoding="utf-8").splitlines()
        except OSError:
            continue
        for raw in lines:
            if not raw.strip():
                continue
            try:
                line = json.loads(raw)
            except ValueError:
                continue  # torn tail line of a killed worker
            part = exports.setdefault(line["pt"], {})
            seen = part.get(line["p"])
            if seen is None:
                part[line["p"]] = line
            else:
                re_exports += 1
                if seen["crc"] != line["crc"]:
                    violations.append(
                        f"partition {line['pt']}: position {line['p']} "
                        f"re-exported with different bytes "
                        f"(crc {seen['crc']} vs {line['crc']})")
    return exports, violations, re_exports


# ---------------------------------------------------------------------------
# the harness


@dataclasses.dataclass
class ConsistencyConfig:
    seed: int = 0
    workers: int = 3
    partitions: int = 2
    # RF = worker count: killing one leader leaves a quorum, so kills cause
    # real leader TRANSFERS (RF=2 would just stall the partition until the
    # supervisor restart — no transfer to check dedupe inheritance against)
    replication: int = 3
    drive_seconds: float = 25.0
    think_ms: float = 15.0          # driver pause between submits
    request_timeout_s: float = 20.0
    kills: int = 3                  # seeded kill_worker storm size
    link_windows: int = 2           # scheduled TCP link partitions
    link_window_ms: int = 1500
    drop_p: float = 0.01
    duplicate_p: float = 0.02
    delay_p: float = 0.03
    reorder_p: float = 0.02
    crash_after_appends: int = 3    # arms ONE worker (one-shot)
    reject_every: int = 25          # every Nth request targets a missing
                                    # process id → terminal NOT_FOUND
    kernel_backend: bool = False    # quick/CI: skip per-worker XLA warmup


def run_consistency(cfg: ConsistencyConfig, directory: str | Path) -> dict:
    """Run the full gate; returns the report dict (violations inside)."""
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
    from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
    from zeebe_tpu.multiproc.supervisor import (
        WorkerSpec,
        WorkerSupervisor,
        worker_cmd,
    )
    from zeebe_tpu.protocol import ValueType
    from zeebe_tpu.protocol.intent import (
        DeploymentIntent,
        ProcessInstanceCreationIntent,
    )
    from zeebe_tpu.protocol.record import command
    from zeebe_tpu.standalone import _free_ports
    from zeebe_tpu.testing.chaos import FaultPlan
    from zeebe_tpu.testing.chaos_tcp import LinkWindow, format_spec

    directory = Path(directory)
    export_dir = directory / "exports"
    export_dir.mkdir(parents=True, exist_ok=True)
    rng = random.Random(cfg.seed)
    started = time.monotonic()
    epoch_ms = time.time() * 1000.0

    worker_names = [f"worker-{i}" for i in range(cfg.workers)]
    ports = _free_ports(cfg.workers + 1)
    contacts = {n: ("127.0.0.1", p) for n, p in zip(worker_names, ports)}
    contacts["gateway-0"] = ("127.0.0.1", ports[-1])
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))

    # seeded fault scenario: probabilistic TCP faults ride the boot spec;
    # link-partition WINDOWS are scheduled only once the fleet is actually
    # up — the controller writes the dynamically-reloaded windows file at
    # drive start, so the windows land mid-drive regardless of boot time
    # (a hard-coded boot estimate either expired before the first request
    # on a slow runner or overshot the drive on a fast one)
    plan = FaultPlan(seed=cfg.seed, drop_p=cfg.drop_p,
                     duplicate_p=cfg.duplicate_p, delay_p=cfg.delay_p,
                     reorder_p=cfg.reorder_p, max_delay_ticks=3)
    chaos_spec = format_spec(plan, [], tick_ms=50)
    windows_file = directory / "chaos-windows.txt"
    windows: list[LinkWindow] = []

    def schedule_link_windows() -> None:
        """Called at drive start: windows between seeded worker pairs,
        spread over the first ~70% of the drive, relative to the shared
        epoch NOW (boot already paid)."""
        now_rel = time.time() * 1000.0 - epoch_ms
        for i in range(cfg.link_windows):
            a, b = rng.sample(worker_names, 2)
            start = now_rel + rng.uniform(0.1, 0.7) * cfg.drive_seconds * 1000.0
            windows.append(LinkWindow(a, b, int(start),
                                      int(start + cfg.link_window_ms)))
        windows_file.write_text("".join(
            f"{w.a}|{w.b}@{w.start_ms}-{w.end_ms}\n" for w in windows),
            encoding="utf-8")

    repo = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["JAX_PLATFORMS"] = "cpu"
    if not cfg.kernel_backend:
        env["ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND"] = "false"
    env["ZEEBE_CHAOS_TCP"] = chaos_spec
    env["ZEEBE_CHAOS_EPOCH_MS"] = str(epoch_ms)
    env["ZEEBE_CHAOS_TCP_WINDOWSFILE"] = str(windows_file)
    env["ZEEBE_BROKER_EXPORTERS_CONSIST_CLASSNAME"] = \
        "zeebe_tpu.testing.consistency.JsonlExporter"
    env["ZEEBE_BROKER_EXPORTERS_CONSIST_ARGS_DIR"] = str(export_dir)

    # arm EVERY worker (each one-shot per data dir): whichever member wins
    # the elections serves ingress, so the crash-between-append-and-reply
    # fires by construction regardless of where leadership lands
    armed = cfg.crash_after_appends > 0
    specs = []
    for name in worker_names:
        data_dir = str(directory / name)
        extra = None
        if armed:
            extra = {"ZEEBE_CHAOS_CRASH_AFTER_APPENDS":
                     str(cfg.crash_after_appends)}
        specs.append(WorkerSpec(
            node_id=name,
            cmd=worker_cmd(name, f"127.0.0.1:{contacts[name][1]}",
                           contact_str, "gateway-0", cfg.partitions,
                           cfg.replication, data_dir=data_dir),
            data_dir=data_dir, extra_env=extra))
    supervisor = WorkerSupervisor(specs, env=env, restart_backoff_s=0.2)
    runtime = MultiProcClusterRuntime(
        "gateway-0",
        {m: a for m, a in contacts.items() if m != "gateway-0"},
        partition_count=cfg.partitions, replication_factor=cfg.replication,
        bind=contacts["gateway-0"], supervisor=supervisor)

    history: list[ClientOp] = []
    history_lock = threading.Lock()
    op_seq = [0]
    events: list[dict] = []
    report: dict[str, Any] = {"seed": cfg.seed}

    def clock_ms() -> float:
        return time.time() * 1000.0 - epoch_ms

    def submit_op(partition: int, kind: str, record) -> ClientOp:
        return submit_client_op(
            runtime, partition, kind, record, history=history,
            history_lock=history_lock, op_seq=op_seq, clock_ms=clock_ms,
            timeout_s=cfg.request_timeout_s)

    model = (Bpmn.create_executable_process("consist")
             .start_event("s").end_event("e").done())
    deploy = command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
        "resources": [{"resourceName": "consist.bpmn",
                       "resource": to_bpmn_xml(model)}]})

    def create_cmd(process_id: str = "consist"):
        return command(ValueType.PROCESS_INSTANCE_CREATION,
                       ProcessInstanceCreationIntent.CREATE,
                       {"bpmnProcessId": process_id, "version": -1,
                        "variables": {}})

    stop_driving = threading.Event()

    def drive(partition: int) -> None:
        n = 0
        while not stop_driving.is_set():
            n += 1
            if cfg.reject_every and n % cfg.reject_every == 0:
                # a command that terminally rejects (NOT_FOUND): the checker
                # proves the rejection stays terminal under resends
                submit_op(partition, "create-missing",
                          create_cmd("no-such-process"))
            else:
                submit_op(partition, "create", create_cmd())
            time.sleep(cfg.think_ms / 1000.0)

    def chaos_schedule() -> list[tuple[float, str, str]]:
        """(at_s since drive start, action, target) — the kill storm."""
        out = []
        for i in range(cfg.kills):
            at = rng.uniform(0.15, 0.8) * cfg.drive_seconds
            target = worker_names[rng.randrange(len(worker_names))]
            out.append((at, "kill", target))
        return sorted(out)

    try:
        runtime.start()
        boot_deadline = time.monotonic() + 180.0
        while True:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                if time.monotonic() >= boot_deadline:
                    raise
        # deploy on partition 1; the deployment distributes to the rest —
        # wait until every partition serves creates before chaos starts
        deploy_op = submit_op(1, "deploy", deploy)
        if deploy_op.outcome != "ack":
            raise RuntimeError(f"deploy failed: {deploy_op.row()}")
        for pid in range(1, cfg.partitions + 1):
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if submit_op(pid, "create", create_cmd()).outcome == "ack":
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError(f"partition {pid} never served a create")

        drive_started = time.monotonic()
        schedule_link_windows()
        drivers = [threading.Thread(target=drive, args=(pid,), daemon=True,
                                    name=f"driver-{pid}")
                   for pid in range(1, cfg.partitions + 1)]
        for t in drivers:
            t.start()
        for at, action, target in chaos_schedule():
            delay = drive_started + at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            logger.warning("chaos: %s %s at t=%.1fs", action, target, at)
            events.append({"atMs": clock_ms(), "action": action,
                           "target": target})
            supervisor.kill_worker(target)
        remaining = drive_started + cfg.drive_seconds - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        stop_driving.set()
        for t in drivers:
            t.join(timeout=cfg.request_timeout_s + 10)

        # post-drive probe: kill a leader and resend an ANSWERED request's
        # envelope — the replicated dedupe table must replay the stored
        # reply across the process death (the acceptance sequence, pinned)
        probe = _dedupe_replay_probe(runtime, supervisor, history, events,
                                     clock_ms)
        report["dedupeProbe"] = probe

        # quiesce: leaders back, exporters caught up to the acked frontier
        quiesce_deadline = time.monotonic() + 90.0
        while time.monotonic() < quiesce_deadline:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                continue
        _await_exports(export_dir, history, deadline_s=60.0)
        report["routingEpochs"] = runtime.routing_epoch
        report["gatewayFlight"] = runtime.flight.snapshot()
        report["workerRestarts"] = dict(supervisor.restarts)
    finally:
        try:
            runtime.stop()
        except Exception:  # noqa: BLE001 — teardown must reach evidence
            logger.exception("runtime stop failed")

    # ---- offline evidence + checks ----------------------------------------
    logs, log_violations = collect_logs(directory, worker_names,
                                        cfg.partitions)
    exports, export_violations, re_exports = collect_exports(export_dir)
    violations = log_violations + export_violations
    violations += check_consistency(history, logs, exports)

    # observed TCP-fault evidence (periodic per-process-life snapshots from
    # the workers' chaos wrappers): configured-but-never-applied chaos must
    # fail the gate, not silently report coverage
    tcp_chaos: dict[str, int] = {}
    for counts_path in directory.glob("*/chaos-counts-*.json"):
        try:
            counts = json.loads(counts_path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        for key, value in counts.items():
            if isinstance(value, int):
                tcp_chaos[key] = tcp_chaos.get(key, 0) + value
    if cfg.link_windows > 0 and not tcp_chaos.get("link_blocked"):
        violations.append(
            f"{cfg.link_windows} link-partition window(s) configured but no "
            f"worker observed a blocked frame (windows missed the run)")

    crash_markers = [name for name in worker_names
                     if (directory / name
                         / "chaos-crash-after-append.done").exists()]
    crash_fired = armed and bool(crash_markers)
    # the armed crash + every kill that interrupted an in-flight request:
    # acked despite ≥1 resend, exactly one command in the log (checked
    # above) — the crash/kill → resend → dedupe evidence
    recovered = [op.row() for op in history
                 if op.outcome == "ack" and (op.resends or op.reroutes)]
    crash_sequences = len(recovered) + (1 if report.get(
        "dedupeProbe", {}).get("verified") else 0)
    if crash_fired and not crash_sequences:
        violations.append(
            "armed crash-between-append-and-reply fired but no request "
            "survived it through a resend (dedupe sequence unverified)")
    if report.get("dedupeProbe", {}).get("verified") is False:
        violations.append(
            f"dedupe replay probe failed: {report['dedupeProbe']}")

    outcomes: dict[str, int] = {}
    for op in history:
        outcomes[op.outcome] = outcomes.get(op.outcome, 0) + 1
    report.update({
        "workers": cfg.workers,
        "partitions": cfg.partitions,
        "replication": cfg.replication,
        "requests": len(history),
        "outcomes": outcomes,
        "ackedCommands": outcomes.get("ack", 0),
        "kills": len([e for e in events if e["action"] == "kill"]),
        "linkPartitionWindows": len(windows),
        "linkWindows": [dataclasses.asdict(w) for w in windows],
        "tcpChaosObserved": tcp_chaos,
        "chaosSpec": chaos_spec,
        "events": events,
        "crashBetweenAppendAndReplyFired": crash_fired,
        "crashArmedWorkersFired": crash_markers,
        "crashSequencesVerified": crash_sequences,
        "resentAckedRequests": recovered[:50],
        "dedupeRepliesObserved": sum(1 for op in history
                                     if op.dedupe == "replayed"),
        "reExportedRecords": re_exports,
        "logRecords": {str(p): len(r) for p, r in logs.items()},
        "exportedPositions": {str(p): len(v) for p, v in exports.items()},
        "violations": violations,
        "wallSeconds": round(time.monotonic() - started, 2),
    })
    return report


def _await_exports(export_dir: Path, history: list[ClientOp],
                   deadline_s: float) -> None:
    """Block until the export stream covers every acked position (or the
    deadline passes — the checker then reports the loss as a violation)."""
    want: dict[int, int] = {}
    for op in history:
        if op.outcome == "ack" and op.position >= 0:
            want[op.partition] = max(want.get(op.partition, 0), op.position)
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        exports, _, _ = collect_exports(export_dir)
        if all(want_pos in exports.get(pid, {})
               for pid, want_pos in want.items()):
            return
        time.sleep(0.5)


def _dedupe_replay_probe(runtime, supervisor, history: list[ClientOp],
                         events: list[dict], clock_ms) -> dict:
    """Deterministic acceptance sequence: take an ACKED create, SIGKILL the
    partition's current leader (wiping its in-memory ingress maps), wait
    for service to return, then resend the original envelope. The reply
    must come back flagged ``dedupe: replayed`` with the ORIGINAL command
    position — proof the stored reply survived the process death in the
    replicated table."""
    from zeebe_tpu.multiproc.worker import CLIENT_COMMAND_TOPIC
    from zeebe_tpu.protocol import ValueType
    from zeebe_tpu.protocol.intent import ProcessInstanceCreationIntent
    from zeebe_tpu.protocol.record import command

    candidates = [op for op in history
                  if op.kind == "create" and op.outcome == "ack"
                  and op.request_id >= 0 and op.position >= 0]
    if not candidates:
        return {"verified": False, "reason": "no acked create to probe"}
    op = candidates[-1]
    leader = runtime._leader_of(op.partition)
    if leader is None:
        return {"verified": False, "reason": "no leader to kill"}
    events.append({"atMs": clock_ms(), "action": "kill-probe",
                   "target": leader})
    supervisor.kill_worker(leader)
    time.sleep(1.0)

    rec = command(ValueType.PROCESS_INSTANCE_CREATION,
                  ProcessInstanceCreationIntent.CREATE,
                  {"bpmnProcessId": "consist", "version": -1,
                   "variables": {}}).replace(
        request_id=op.request_id, request_stream_id=runtime._stream_id)
    payload = {"record": rec.to_bytes(), "requestId": op.request_id}
    # re-arm the gateway's correlation table for the finished request id and
    # resend until a (possibly different) leader answers
    event = threading.Event()
    runtime._pending[op.request_id] = event
    try:
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            target = runtime._leader_of(op.partition)
            if target is None:
                time.sleep(0.2)
                continue
            runtime.messaging.send(
                target, f"{CLIENT_COMMAND_TOPIC}-{op.partition}", payload)
            if event.wait(1.0):
                response = runtime._responses.pop(op.request_id, None)
                if response is None:
                    event.clear()
                    continue
                if "record" not in response:
                    # not-leader/unavailable while the cluster re-elects:
                    # keep probing
                    event.clear()
                    time.sleep(0.2)
                    continue
                return {
                    "verified":
                        response.get("dedupe") == "replayed"
                        and response.get("commandPosition") == op.position,
                    "requestId": op.request_id,
                    "originalPosition": op.position,
                    "replayedPosition": response.get("commandPosition"),
                    "dedupe": response.get("dedupe"),
                    "killedLeader": leader,
                    "answeredBy": target,
                }
        return {"verified": False, "reason": "probe timed out",
                "requestId": op.request_id, "killedLeader": leader}
    finally:
        runtime._pending.pop(op.request_id, None)
        runtime._responses.pop(op.request_id, None)


def main(argv: list[str] | None = None) -> int:  # pragma: no cover — manual
    import argparse
    import tempfile

    parser = argparse.ArgumentParser(prog="zeebe-tpu-consistency")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args(argv)
    cfg = ConsistencyConfig(seed=args.seed)
    if not args.quick:
        cfg.drive_seconds = 120.0
        cfg.kills = 8
        cfg.link_windows = 5
    with tempfile.TemporaryDirectory(prefix="zeebe-consistency-") as tmp:
        report = run_consistency(cfg, tmp)
    json.dump(report, sys.stdout, indent=2)
    return 1 if report["violations"] else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
