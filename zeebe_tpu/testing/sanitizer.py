"""Runtime single-writer / determinism sanitizer (``ZEEBE_SANITIZE=1``).

The static half of ISSUE 10 (zeebe_tpu/analysis) proves properties an AST
can see; this is the dynamic half for the ones it can't: *which thread*
actually touched what at runtime. The architecture's threading contract is
narrow and load-bearing:

- **single-writer:** exactly one thread — the pump thread — mutates a
  partition's state (``ZbDb`` transactions, bulk loads) and appends to its
  journal. Every other thread (management HTTP, gateway long-polls, metric
  samplers) reads through the lock-free committed accessors only.
- **lock-held / no-reentry:** the flight recorder's ring mutations happen
  under its internal lock, and never re-enter ``record`` from the same
  thread (its plain ``threading.Lock`` would deadlock).

With ``ZEEBE_SANITIZE=1`` (tests/conftest.py calls :func:`maybe_install`),
the sanitizer wraps ``ZbDb``, ``Transaction.commit``, the journal's
``append``, and the flight recorder with affinity assertions: the first
mutating thread claims an object's writer affinity, and any later mutation
from a different thread raises :class:`SanitizerViolation` — turning a
latent cross-thread race into a deterministic test failure with both
thread names in the message. Read paths (``committed_get`` /
``committed_keys_of`` / ``lookup_request``) are deliberately unwrapped:
they are the sanctioned cross-thread surface.

Handoffs that are *architecturally* legitimate (a harness builds state on
one thread and hands the whole partition to another before any concurrent
access) declare themselves with :func:`adopt_writer`.

Scope note: installation patches classes process-wide but only for THIS
process — multi-process harnesses (multiproc supervisor workers) spawn
children without the sanitizer unless their entry point also calls
:func:`maybe_install`.
"""

from __future__ import annotations

import os
import threading

_AFFINITY_ATTR = "_zs_writer"
_ENV_FLAG = "ZEEBE_SANITIZE"

_installed = False
_originals: dict[tuple[type, str], object] = {}
_tls = threading.local()


class SanitizerViolation(AssertionError):
    """A thread broke the single-writer / no-reentry contract. Raised (not
    logged): under the sanitizer a latent race IS a test failure."""


def enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "").strip() not in ("", "0", "false")


def _thread_label() -> str:
    t = threading.current_thread()
    return f"{t.name}(ident={t.ident})"


def adopt_writer(obj) -> None:
    """Explicitly (re)claim ``obj``'s writer affinity for the current
    thread — the declared-handoff escape hatch for architecturally
    legitimate ownership transfers (e.g. a harness thread handing a fully
    built partition to a worker loop). A silent cross-thread write without
    this call is exactly what the sanitizer exists to catch."""
    try:
        obj.__dict__[_AFFINITY_ATTR] = (threading.get_ident(),
                                        threading.current_thread().name)
    except AttributeError:  # __slots__ object: affinity not trackable
        pass


def _assert_writer(obj, operation: str) -> None:
    """First mutating thread claims ``obj``; later mutators must match."""
    try:
        claimed = obj.__dict__.get(_AFFINITY_ATTR)
    except AttributeError:
        return
    if claimed is None:
        adopt_writer(obj)
        return
    ident, name = claimed
    if ident != threading.get_ident():
        raise SanitizerViolation(
            f"single-writer violation: {operation} on "
            f"{type(obj).__name__}@{id(obj):#x} from thread "
            f"{_thread_label()}, but writer affinity belongs to "
            f"{name}(ident={ident}) — partition state may only be mutated "
            f"by its pump thread; cross-thread readers must use the "
            f"committed_* accessors (or declare a legitimate handoff with "
            f"testing.sanitizer.adopt_writer)")


def _wrap_mutator(cls: type, method_name: str, obj_of=None) -> None:
    """Patch ``cls.method_name`` to assert writer affinity first.
    ``obj_of`` maps the call's ``self`` to the affinity-carrying object
    (e.g. ``Transaction.commit`` claims on the transaction's db)."""
    original = getattr(cls, method_name)
    _originals[(cls, method_name)] = original

    def checked(self, *args, **kwargs):
        _assert_writer(obj_of(self) if obj_of is not None else self,
                       f"{cls.__name__}.{method_name}")
        return original(self, *args, **kwargs)

    checked.__name__ = method_name
    checked.__qualname__ = f"{cls.__name__}.{method_name}"
    checked.__doc__ = original.__doc__
    setattr(cls, method_name, checked)


def _wrap_reentrancy_guard(cls: type, method_name: str) -> None:
    """Patch ``cls.method_name`` to fail on same-thread reentry: the flight
    recorder's plain Lock would deadlock if a context provider or clock
    hook called back into it."""
    original = getattr(cls, method_name)
    _originals[(cls, method_name)] = original

    def checked(self, *args, **kwargs):
        active = getattr(_tls, "active", None)
        if active is None:
            active = _tls.active = set()
        key = (id(self), method_name)
        if key in active:
            raise SanitizerViolation(
                f"reentrant {cls.__name__}.{method_name} on thread "
                f"{_thread_label()}: a hook invoked from inside "
                f"{method_name} called back into it — this deadlocks the "
                f"recorder's non-reentrant lock")
        active.add(key)
        try:
            return original(self, *args, **kwargs)
        finally:
            active.discard(key)

    checked.__name__ = method_name
    checked.__qualname__ = f"{cls.__name__}.{method_name}"
    checked.__doc__ = original.__doc__
    setattr(cls, method_name, checked)


def install() -> None:
    """Idempotently wrap the mutation surfaces. Import-light: pulls only
    the state/journal/observability modules (no jax)."""
    global _installed
    if _installed:
        return
    from zeebe_tpu.control.actuators import Actuator
    from zeebe_tpu.journal.journal import SegmentedJournal
    from zeebe_tpu.observability.flight_recorder import FlightRecorder
    from zeebe_tpu.state.db import Transaction, ZbDb

    # ZbDb: transaction opens + bulk mutation paths claim/assert affinity.
    # Subclasses (durable/tiered stores) inherit the patched methods.
    _wrap_mutator(ZbDb, "transaction")
    _wrap_mutator(ZbDb, "bulk_apply")
    _wrap_mutator(ZbDb, "load_snapshot_bytes")
    # commit checks again at commit time: a transaction handed to another
    # thread mid-flight is the subtlest cross-thread write there is
    _wrap_mutator(Transaction, "commit", obj_of=lambda txn: txn._db)
    # require_transaction is the chokepoint for EVERY transactional
    # ColumnFamily read/write: a non-writer thread reaching it is reading
    # the mutable overlay mid-processing (committed-read discipline,
    # enforced at runtime)
    _wrap_mutator(ZbDb, "require_transaction")
    _wrap_mutator(SegmentedJournal, "append")
    # control-plane actuators (ISSUE 12): apply is the single runtime
    # write path to a controller-owned knob, and it runs on the pump
    # thread that ticks the plane — same first-writer-claims discipline as
    # ZbDb (a management thread or test harness mutating a knob through an
    # actuator from the side is exactly the race the audit trail can't see)
    _wrap_mutator(Actuator, "apply")
    _wrap_reentrancy_guard(FlightRecorder, "record")
    _wrap_reentrancy_guard(FlightRecorder, "dump")
    _installed = True


def uninstall() -> None:
    """Restore every patched method (tests that provoke violations clean
    up after themselves)."""
    global _installed
    for (cls, name), original in _originals.items():
        setattr(cls, name, original)
    _originals.clear()
    _installed = False


def installed() -> bool:
    return _installed


def maybe_install() -> None:
    if enabled():
        install()
