"""The fleet-day gate: everything at once, with the auditor watching
(ISSUE 20, ROADMAP item 4).

Every earlier gate proves one adversary at a time in ≤2 minutes.
Production is all of them at once for hours: the open-loop multi-tenant
serving workload (PR 11) with diurnal ramps, tiered state under
park/spill pressure (PR 8), ALL THREE chaos planes armed at background
rates (TCP / disk / device, via ``chaos_common``), live definition churn
(new process versions deployed mid-traffic), and rolling worker restarts
— while the per-worker **fleet auditor** (``observability/auditor.py``)
watches invariants, burn rates, and resource trends ONLINE.

Gates:

- **the PR 9 offline checker holds**: every acked request appears exactly
  once in its partition's committed log (no acked loss, no duplicate
  application), plus the export-stream gap checks;
- **SLOs hold outside declared incident windows**: each rolling restart
  declares ``[kill, kill + grace]``; acked latency p50/p99 over requests
  scheduled OUTSIDE those windows must meet the SLO, and the terminal-ack
  fraction must clear the goodput floor;
- **≥1 chaos event per plane observed** (summed per-life counts files) —
  an armed-but-silent plane is a violation;
- **every injected device corruption accounted** (ledger join, reusing
  the PR 15 checker with the death waiver for restart-killed lives);
- **zero leak verdicts on the clean fleet** — and a separate
  **leak-injection arm** (a worker deliberately leaking fds via
  ``ZEEBE_AUDIT_TESTLEAK``) where the auditor MUST return a leak verdict:
  detector recall proven in both directions, with identical knobs;
- **auditor recall 100%**: any violation class the offline checker finds
  that the online auditor did not flag fails the gate — the auditor's
  recall is measured, not assumed (on a clean run this is vacuously 100%,
  which the leak arm keeps honest).

``bench.py --fleetday [--quick]`` runs this and writes
``FLEETDAY[_quick].json``; the CI ``fleetday-smoke`` job gates on it.
Honest caveat (docs/fleetday.md): the quick gate is minutes, not hours —
it proves the composition and the auditor's recall, not day-scale drift.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import random
import sys
import threading
import time
from pathlib import Path
from typing import Any

from zeebe_tpu.observability.auditor import ClusterAuditor
from zeebe_tpu.testing.chaos import FaultPlan
from zeebe_tpu.testing.chaos_common import read_jsonl_ledgers, sum_counts_files
from zeebe_tpu.testing.chaos_device import DeviceFaultPlan
from zeebe_tpu.testing.chaos_device import format_spec as device_spec
from zeebe_tpu.testing.chaos_disk import DiskFaultPlan
from zeebe_tpu.testing.chaos_disk import format_spec as disk_spec
from zeebe_tpu.testing.chaos_tcp import format_spec as tcp_spec
from zeebe_tpu.testing.device_chaos import check_corruption_accounting
from zeebe_tpu.testing.evidence import percentile
from zeebe_tpu.testing.serving import (
    ServingOp,
    TenantSpec,
    check_serving_history,
    drain_arrival_queue,
    execute_op,
    poisson_schedule,
    tenant_rate_fn,
)

logger = logging.getLogger("zeebe_tpu.testing.fleetday")


def _default_tenants() -> list[TenantSpec]:
    return [
        # the default tenant is the kernel's traffic: non-default tenants
        # ride the sequential host path by design (kernel_backend lowers
        # default-tenant record shapes only), so without this slice the
        # device chaos plane would never see a dispatch
        TenantSpec("<default>", "well", 10.0, 10.0, quota_rate=40.0),
        TenantSpec("t-well-0", "well", 5.0, 5.0, quota_rate=20.0),
        # the diurnal tenant: calm through the first shoulder, ~3x after
        TenantSpec("t-diurnal", "well", 4.0, 12.0, quota_rate=30.0),
    ]


@dataclasses.dataclass
class FleetDayConfig:
    seed: int = 0
    workers: int = 3
    partitions: int = 2
    replication: int = 3
    client_streams: int = 96
    drive_seconds: float = 32.0
    #: diurnal shoulder: first fraction of the drive is calm, then a ramp
    calm_fraction: float = 0.35
    ramp_seconds: float = 4.0
    request_timeout_s: float = 15.0
    tenants: list[TenantSpec] = dataclasses.field(
        default_factory=_default_tenants)
    # tiered million-instance stand-in (PR 8): a parked pool spilled cold,
    # woken mid-drive by a correlation burst
    parked_instances: int = 60
    storm_publishes: int = 25
    park_after_ms: int = 500
    spill_batch: int = 64
    park_wait_s: float = 20.0
    park_fraction: float = 0.25
    #: live definition churn: serve-model redeployments spread mid-drive
    churn_deploys: int = 2
    #: rolling restarts: sequential worker kills, each declaring an
    #: incident window of ``incident_grace_s``
    rolling_restarts: int = 1
    incident_grace_s: float = 10.0
    # -- SLO gates (outside incident windows) --------------------------------
    slo_p50_ms: float = 1500.0
    slo_p99_ms: float = 6000.0
    goodput_floor: float = 0.7
    # -- chaos background rates (all three planes, low) ----------------------
    tcp_drop_p: float = 0.01
    tcp_dup_p: float = 0.01
    tcp_delay_p: float = 0.10
    tcp_reorder_p: float = 0.02
    tcp_max_delay_ticks: int = 2
    disk_fsync_stall_p: float = 0.06
    disk_stall_ms: int = 40
    device_compile_fail_p: float = 0.02
    device_dispatch_fail_p: float = 0.06
    device_chunk_fail_p: float = 0.04
    device_corrupt_p: float = 0.04
    device_flips: int = 2
    # -- auditor knobs for the gate (shrunk to fit minutes) ------------------
    audit_fast_ms: int = 10_000
    audit_slow_ms: int = 40_000
    audit_leak_ms: int = 15_000
    audit_warmup_ms: int = 8_000
    audit_min_growth: float = 0.3
    # -- the leak-injection arm ----------------------------------------------
    leak_arm_seconds: float = 30.0
    leak_spec: str = "fd:25"


FULL_FLEETDAY = FleetDayConfig(
    workers=4, partitions=3, client_streams=256,
    drive_seconds=900.0, ramp_seconds=60.0,
    parked_instances=400, storm_publishes=150,
    churn_deploys=6, rolling_restarts=4, incident_grace_s=20.0,
    audit_fast_ms=60_000, audit_slow_ms=600_000, audit_leak_ms=120_000,
    audit_warmup_ms=60_000, leak_arm_seconds=90.0,
    tenants=[
        TenantSpec("<default>", "well", 20.0, 20.0, quota_rate=60.0),
        TenantSpec("t-well-0", "well", 10.0, 10.0, quota_rate=40.0),
        TenantSpec("t-well-1", "well", 10.0, 10.0, quota_rate=40.0),
        TenantSpec("t-diurnal", "well", 8.0, 30.0, quota_rate=60.0),
    ])


# ---------------------------------------------------------------------------
# pure helpers (unit-testable without a cluster)


def incident_windows(events: list[dict], grace_ms: float
                     ) -> list[tuple[float, float]]:
    """Declared incident windows from the harness event log: each rolling
    restart opens ``[atMs, atMs + grace]`` on the drive clock."""
    return [(e["atMs"], e["atMs"] + grace_ms)
            for e in events if e.get("action") in ("kill", "restart")]


def outside_incidents(at_ms: float,
                      windows: list[tuple[float, float]]) -> bool:
    return all(not (lo <= at_ms <= hi) for lo, hi in windows)


def evaluate_fleet_slo(history: list[ServingOp],
                       windows: list[tuple[float, float]],
                       cfg: FleetDayConfig) -> tuple[dict, list[str]]:
    """SLO + goodput over the drive, EXCLUDING requests scheduled inside a
    declared incident window (a rolling restart is allowed its re-election
    tail; steady state is not). Pure — tests drive it synthetically."""
    violations: list[str] = []
    clear = [op for op in history
             if op.scheduled_ms >= 0 and outside_incidents(
                 op.scheduled_ms, windows)]
    acked = [op for op in clear if op.outcome == "ack"]
    latencies = sorted(op.latency_ms for op in acked)
    report: dict[str, Any] = {
        "requestsOutsideIncidents": len(clear),
        "ackedOutsideIncidents": len(acked),
        "incidentWindows": [[round(a, 1), round(b, 1)] for a, b in windows],
    }
    if not latencies:
        violations.append("no acked requests outside incident windows — "
                          "no SLO evidence")
        return report, violations
    p50 = percentile(latencies, 0.50)
    p99 = percentile(latencies, 0.99)
    report["p50Ms"] = round(p50, 1)
    report["p99Ms"] = round(p99, 1)
    if p50 > cfg.slo_p50_ms:
        violations.append(
            f"fleet p50 outside incidents {p50:.0f}ms > SLO "
            f"{cfg.slo_p50_ms:.0f}ms")
    if p99 > cfg.slo_p99_ms:
        violations.append(
            f"fleet p99 outside incidents {p99:.0f}ms > SLO "
            f"{cfg.slo_p99_ms:.0f}ms")
    terminal = [op for op in clear if op.outcome != "pending"]
    good = len(acked) / len(terminal) if terminal else 0.0
    report["ackFraction"] = round(good, 4)
    if good < cfg.goodput_floor:
        violations.append(
            f"goodput outside incidents {good:.0%} < floor "
            f"{cfg.goodput_floor:.0%}")
    pending = [op for op in history if op.outcome == "pending"]
    if pending:
        violations.append(
            f"{len(pending)} request(s) never reached a terminal outcome "
            f"(silent drop)")
    return report, violations


#: offline violation text -> the online monitor class that should have
#: flagged it while the cluster ran (the recall join). Specific classes
#: first: the acked-position keywords include the generic "position",
#: which must not swallow exporter/CRC findings.
_RECALL_MAP = (
    (("export", "exporter"), "exporter_sequence"),
    (("crc", "diverge", "replica"), "replica_crc"),
    (("leak",), "resource_leak"),
    (("quarantin",), "quarantine_latch"),
    (("acked loss", "duplicate application", "moved backward",
      "appended", "position"), "acked_position"),
)


#: monitors whose online flags the offline checker can corroborate — a
#: flag on a run the offline evidence calls clean is a precision failure
INVARIANT_MONITORS = frozenset(
    {"acked_position", "exporter_sequence", "replica_crc",
     "quarantine_latch"})


def _monitor_of(violation_text: str) -> str | None:
    lowered = violation_text.lower()
    for keywords, name in _RECALL_MAP:
        if any(k in lowered for k in keywords):
            return name
    return None


def offline_monitors(offline_violations: list[str]) -> set:
    """Monitor classes the offline findings map onto."""
    return {m for m in map(_monitor_of, offline_violations)
            if m is not None}


def check_auditor_recall(offline_violations: list[str],
                         flagged_monitors: set
                         ) -> tuple[list[str], dict]:
    """The recall cross-check: every offline-found violation must map to
    an online monitor class that actually flagged during the run. Offline
    findings with no monitor mapping (e.g. a pure harness failure) are
    reported but do not count against recall."""
    misses: list[str] = []
    mapped = 0
    unmapped = 0
    for text in offline_violations:
        monitor = _monitor_of(text)
        if monitor is None:
            unmapped += 1
            continue
        mapped += 1
        if monitor not in flagged_monitors:
            misses.append(
                f"auditor recall miss: offline violation maps to monitor "
                f"`{monitor}` which never flagged online — {text[:160]}")
    stats = {
        "offlineViolations": len(offline_violations),
        "mappedToMonitors": mapped,
        "unmapped": unmapped,
        "onlineFlagged": sorted(flagged_monitors),
        "misses": len(misses),
        "recallPct": (100.0 if mapped == 0
                      else round(100.0 * (mapped - len(misses)) / mapped, 1)),
    }
    return misses, stats


def _audit_env(cfg: FleetDayConfig) -> dict[str, str]:
    return {
        "ZEEBE_AUDIT_ENABLED": "1",
        "ZEEBE_AUDIT_FASTWINDOWMS": str(cfg.audit_fast_ms),
        "ZEEBE_AUDIT_SLOWWINDOWMS": str(cfg.audit_slow_ms),
        "ZEEBE_AUDIT_LEAKWINDOWMS": str(cfg.audit_leak_ms),
        "ZEEBE_AUDIT_LEAKWARMUPMS": str(cfg.audit_warmup_ms),
        "ZEEBE_AUDIT_LEAKMINGROWTH": str(cfg.audit_min_growth),
        "ZEEBE_AUDIT_SLOP99MS": str(cfg.slo_p99_ms),
    }


# ---------------------------------------------------------------------------
# the leak-injection arm (recall in the firing direction)


def run_leak_arm(cfg: FleetDayConfig, directory: Path) -> dict:
    """Boot ONE worker with ``ZEEBE_AUDIT_TESTLEAK`` armed and the SAME
    auditor knobs as the clean fleet; poll its status push until the
    online auditor returns a leak verdict. No traffic needed — the leak
    and the sampler both ride the worker's pump loop."""
    from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
    from zeebe_tpu.multiproc.supervisor import (
        WorkerSpec,
        WorkerSupervisor,
        worker_cmd,
    )
    from zeebe_tpu.standalone import _free_ports

    directory.mkdir(parents=True, exist_ok=True)
    ports = _free_ports(2)
    contacts = {"leaker-0": ("127.0.0.1", ports[0]),
                "gateway-0": ("127.0.0.1", ports[1])}
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))
    repo = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["JAX_PLATFORMS"] = "cpu"
    env["ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND"] = "false"
    env.update(_audit_env(cfg))
    env["ZEEBE_AUDIT_TESTLEAK"] = cfg.leak_spec
    spec = WorkerSpec(
        node_id="leaker-0",
        cmd=worker_cmd("leaker-0", f"127.0.0.1:{contacts['leaker-0'][1]}",
                       contact_str, "gateway-0", 1, 1,
                       data_dir=str(directory / "leaker-0")),
        data_dir=str(directory / "leaker-0"))
    supervisor = WorkerSupervisor([spec], env=env, restart_backoff_s=0.5)
    runtime = MultiProcClusterRuntime(
        "gateway-0", {"leaker-0": contacts["leaker-0"]},
        partition_count=1, replication_factor=1,
        bind=contacts["gateway-0"], supervisor=supervisor)
    result: dict[str, Any] = {"leakSpec": cfg.leak_spec, "fired": False}
    try:
        runtime.start()
        boot_deadline = time.monotonic() + 120.0
        while True:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                if time.monotonic() >= boot_deadline:
                    raise
        deadline = time.monotonic() + cfg.leak_arm_seconds + 60.0
        while time.monotonic() < deadline:
            audit = runtime._worker_status.get("leaker-0", {}).get("audit")
            if isinstance(audit, dict):
                result["lastAudit"] = {
                    "leaks": audit.get("leaks", {}),
                    "leakVerdict": audit.get("leakVerdict"),
                    "violations": audit.get("violations", 0)}
                if audit.get("leakVerdict") == "leak":
                    result["fired"] = True
                    result["firedResources"] = [
                        name for name, v in audit.get("leaks", {}).items()
                        if v.get("state") == "leak"]
                    break
            time.sleep(0.5)
    finally:
        try:
            runtime.stop()
        except Exception:  # noqa: BLE001 — the arm must reach its verdict
            logger.exception("leak arm teardown failed")
    return result


# ---------------------------------------------------------------------------
# the harness


def run_fleetday(cfg: FleetDayConfig, directory: str | Path) -> dict:
    """Run the fleet-day gate; returns the report dict."""
    from zeebe_tpu.gateway.admission import AdmissionCfg, AdmissionController
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
    from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
    from zeebe_tpu.multiproc.supervisor import (
        WorkerSpec,
        WorkerSupervisor,
        worker_cmd,
    )
    from zeebe_tpu.protocol import ValueType
    from zeebe_tpu.protocol.intent import (
        DeploymentIntent,
        MessageIntent,
        ProcessInstanceCreationIntent,
    )
    from zeebe_tpu.protocol.record import command
    from zeebe_tpu.standalone import _free_ports
    from zeebe_tpu.testing.consistency import collect_exports, collect_logs

    directory = Path(directory)
    export_dir = directory / "exports"
    export_dir.mkdir(parents=True, exist_ok=True)
    started = time.monotonic()
    report: dict[str, Any] = {"seed": cfg.seed}
    violations: list[str] = []

    worker_names = [f"worker-{i}" for i in range(cfg.workers)]
    ports = _free_ports(cfg.workers + 1)
    contacts = {n: ("127.0.0.1", p) for n, p in zip(worker_names, ports)}
    contacts["gateway-0"] = ("127.0.0.1", ports[-1])
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))

    tcp_plan = FaultPlan(
        seed=cfg.seed, drop_p=cfg.tcp_drop_p, duplicate_p=cfg.tcp_dup_p,
        delay_p=cfg.tcp_delay_p, reorder_p=cfg.tcp_reorder_p,
        max_delay_ticks=cfg.tcp_max_delay_ticks)
    disk_plan = DiskFaultPlan(
        seed=cfg.seed, fsync_stall_p=cfg.disk_fsync_stall_p,
        stall_ms=cfg.disk_stall_ms)
    device_plan = DeviceFaultPlan(
        seed=cfg.seed, compile_fail_p=cfg.device_compile_fail_p,
        dispatch_fail_p=cfg.device_dispatch_fail_p,
        chunk_fail_p=cfg.device_chunk_fail_p,
        corrupt_p=cfg.device_corrupt_p, flips=cfg.device_flips)
    disk_disarm = directory / "disk-chaos-disarm"
    device_disarm = directory / "device-chaos-disarm"

    quota_spec = ",".join(
        f"{s.name}={s.quota_rate:g}"
        + (f":{s.quota_burst:g}" if s.quota_burst else "")
        for s in cfg.tenants if s.quota_rate > 0)
    repo = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["JAX_PLATFORMS"] = "cpu"
    # the device plane needs the kernel backend LIVE (the direct dispatch
    # path is the seam); mesh dispatch pinned off as in the device gate
    env["ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND"] = "true"
    env["ZEEBE_BROKER_EXPERIMENTAL_KERNELMESHSHARDS"] = "0"
    env["ZEEBE_GATEWAY_TENANT_QUOTAS"] = quota_spec
    env["ZEEBE_BROKER_DATA_TIERING_ENABLED"] = "true"
    env["ZEEBE_BROKER_DATA_TIERING_PARKAFTERMS"] = str(cfg.park_after_ms)
    env["ZEEBE_BROKER_DATA_TIERING_SPILLBATCH"] = str(cfg.spill_batch)
    # all three chaos planes at background rates
    env["ZEEBE_CHAOS_TCP"] = tcp_spec(tcp_plan)
    env["ZEEBE_CHAOS_EPOCH_MS"] = str(time.time() * 1000.0)
    env["ZEEBE_CHAOS_DISK"] = disk_spec(disk_plan)
    env["ZEEBE_CHAOS_DISK_DISARMFILE"] = str(disk_disarm)
    env["ZEEBE_CHAOS_DEVICE"] = device_spec(device_plan)
    env["ZEEBE_CHAOS_DEVICE_DISARMFILE"] = str(device_disarm)
    # exhaustive shadow verification: every injected corruption must be
    # caught before commit (the accounting gate below joins the ledger)
    env["ZEEBE_BROKER_DEVICE_SHADOWSAMPLERATE"] = "1.0"
    # background-rate posture: the ladder should tolerate the background
    # fault trickle without quarantining mid-gate (quarantine is the device
    # gate's business; here it would just sink goodput)
    env["ZEEBE_BROKER_DEVICE_QUARANTINEFAULTS"] = "200"
    env.update(_audit_env(cfg))
    env["ZEEBE_BROKER_EXPORTERS_FLEETDAY_CLASSNAME"] = \
        "zeebe_tpu.testing.consistency.JsonlExporter"
    env["ZEEBE_BROKER_EXPORTERS_FLEETDAY_ARGS_DIR"] = str(export_dir)

    specs = [WorkerSpec(
        node_id=name,
        cmd=worker_cmd(name, f"127.0.0.1:{contacts[name][1]}", contact_str,
                       "gateway-0", cfg.partitions, cfg.replication,
                       data_dir=str(directory / name)),
        data_dir=str(directory / name)) for name in worker_names]
    supervisor = WorkerSupervisor(specs, env=env, restart_backoff_s=0.2)
    admission = AdmissionController(
        AdmissionCfg(
            quotas={s.name: (s.quota_rate, s.quota_burst)
                    for s in cfg.tenants if s.quota_rate > 0},
            weights={s.name: s.weight for s in cfg.tenants}),
        node_id="gateway-0")
    runtime = MultiProcClusterRuntime(
        "gateway-0",
        {m: a for m, a in contacts.items() if m != "gateway-0"},
        partition_count=cfg.partitions, replication_factor=cfg.replication,
        bind=contacts["gateway-0"], supervisor=supervisor,
        admission=admission)
    admission.flight = runtime.flight

    history: list[ServingOp] = []
    history_lock = threading.Lock()
    op_seq = [0]
    events: list[dict] = []
    drive_t0 = [0.0]
    cluster_audit = ClusterAuditor()
    audit_lock = threading.Lock()

    def drive_ms() -> float:
        return (time.monotonic() - drive_t0[0]) * 1000.0

    def new_op(tenant: str, kind: str, partition: int,
               scheduled_ms: float) -> ServingOp:
        with history_lock:
            op_seq[0] += 1
            op = ServingOp(index=op_seq[0], tenant=tenant, kind=kind,
                           partition=partition, scheduled_ms=scheduled_ms)
            history.append(op)
        return op

    def execute(op: ServingOp, record) -> ServingOp:
        return execute_op(runtime, op, record, cfg.request_timeout_s,
                          drive_ms)

    def create_cmd(tenant: str):
        return command(ValueType.PROCESS_INSTANCE_CREATION,
                       ProcessInstanceCreationIntent.CREATE,
                       {"bpmnProcessId": "fleet", "version": -1,
                        "variables": {}, "tenantId": tenant})

    def serve_model(version_tag: int):
        # each churn deploys a structurally DIFFERENT model under the same
        # process id — a real new version, not a dedup'd redeploy
        return (Bpmn.create_executable_process("fleet")
                .start_event("s").end_event(f"e{version_tag}").done())

    storm_model = (Bpmn.create_executable_process("fleet_wait")
                   .start_event("s")
                   .intermediate_catch_message("wait",
                                               message_name="fleet-msg",
                                               correlation_key="=ck")
                   .end_event("e").done())

    def parked_cold_total() -> int:
        return sum(
            info.get("parkedCold", 0)
            for status in runtime._worker_status.values()
            for info in status.get("partitions", {}).values()
            if info.get("role") == "leader")

    # open-loop schedule: calm shoulder then diurnal ramp, per tenant
    calm_s = cfg.calm_fraction * cfg.drive_seconds
    merged: list[tuple[float, str]] = []
    for idx, spec in enumerate(cfg.tenants):
        rng = random.Random((cfg.seed << 8) ^ (idx + 1))
        rate = tenant_rate_fn(spec, calm_s, cfg.ramp_seconds)
        peak = max(spec.rate_a, spec.rate_bc)
        merged.extend(
            (t, spec.name)
            for t in poisson_schedule(rng, cfg.drive_seconds, rate, peak))
    merged.sort()
    report["offeredArrivals"] = len(merged)

    arrivals: "queue.Queue[tuple[float, str] | None]" = queue.Queue()
    stop_streams = threading.Event()

    def submit_create(at_ms: float, tenant: str) -> None:
        op = new_op(tenant, "create",
                    runtime.partition_for_new_instance(), at_ms)
        execute(op, create_cmd(tenant))

    def client_stream() -> None:
        drain_arrival_queue(arrivals, stop_streams, submit_create)

    def scheduler() -> None:
        for at_s, tenant in merged:
            delay = drive_t0[0] + at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if stop_streams.is_set():
                return
            arrivals.put((at_s * 1000.0, tenant))

    def audit_poller() -> None:
        """Feed the gateway-side auditor from the worker status pushes the
        runtime already aggregates — replica-CRC joins + cross-push
        monotonicity accumulate while the fleet runs."""
        while not stop_streams.is_set():
            rows = dict(runtime._worker_status)
            with audit_lock:
                cluster_audit.ingest(rows)
            time.sleep(0.5)

    try:
        runtime.start()
        boot_deadline = time.monotonic() + 240.0
        while True:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                if time.monotonic() >= boot_deadline:
                    raise

        # ---- warm: deploy v1 + the storm pool -----------------------------
        drive_t0[0] = time.monotonic()
        tenant_names = [s.name for s in cfg.tenants]
        for tenant in tenant_names:
            op = execute(
                new_op(tenant, "deploy", 1, -1.0),
                command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
                    "resources": [{"resourceName": "fleet.bpmn",
                                   "resource": to_bpmn_xml(serve_model(0))}],
                    "tenantId": tenant}))
            if op.outcome != "ack":
                raise RuntimeError(f"deploy for {tenant} failed: {op.row()}")
        op = execute(
            new_op("t-storm", "deploy", 1, -1.0),
            command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
                "resources": [{"resourceName": "fleet_wait.bpmn",
                               "resource": to_bpmn_xml(storm_model)}],
                "tenantId": "t-storm"}))
        if op.outcome != "ack":
            raise RuntimeError(f"storm deploy failed: {op.row()}")
        for pid in range(1, cfg.partitions + 1):
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                probe = execute(new_op(tenant_names[0], "create", pid, -1.0),
                                create_cmd(tenant_names[0]))
                if probe.outcome == "ack":
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError(
                    f"partition {pid} never served a create: {probe.row()}")

        storm_keys = [f"fleet-ck-{i}" for i in range(cfg.parked_instances)]
        for ck in storm_keys:
            op = execute(
                new_op("t-storm", "create",
                       runtime.partition_for_new_instance(), -1.0),
                command(ValueType.PROCESS_INSTANCE_CREATION,
                        ProcessInstanceCreationIntent.CREATE,
                        {"bpmnProcessId": "fleet_wait", "version": -1,
                         "variables": {"ck": ck}, "tenantId": "t-storm"}))
            if op.outcome != "ack":
                violations.append(
                    f"storm pool create failed: {op.outcome} "
                    f"({op.rejection})")
        want_cold = int(cfg.parked_instances * cfg.park_fraction)
        park_deadline = time.monotonic() + cfg.park_wait_s
        while time.monotonic() < park_deadline:
            if parked_cold_total() >= want_cold:
                break
            time.sleep(0.5)
        parked_before = parked_cold_total()
        report["tieredState"] = {"instances": cfg.parked_instances,
                                 "parkedColdBeforeStorm": parked_before}
        if parked_before < want_cold:
            violations.append(
                f"storm pool never tiered cold: {parked_before} spilled "
                f"< {want_cold} wanted (tiering evidence missing)")

        # ---- the drive: everything at once --------------------------------
        drive_t0[0] = time.monotonic()
        threads = [threading.Thread(target=client_stream, daemon=True,
                                    name=f"stream-{i}")
                   for i in range(cfg.client_streams)]
        for t in threads:
            t.start()
        sched = threading.Thread(target=scheduler, daemon=True,
                                 name="fleetday-scheduler")
        sched.start()
        poller = threading.Thread(target=audit_poller, daemon=True,
                                  name="fleetday-audit-poller")
        poller.start()

        side_rng = random.Random(cfg.seed ^ 0xF1EE7)

        def churn() -> None:
            """Live definition churn: new serve-model versions deployed
            mid-traffic; version -1 creates pick each one up."""
            for i in range(cfg.churn_deploys):
                at = (0.2 + 0.6 * (i + 0.5) / cfg.churn_deploys) \
                    * cfg.drive_seconds
                delay = drive_t0[0] + at - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                if stop_streams.is_set():
                    return
                tenant = tenant_names[i % len(tenant_names)]
                op = execute(
                    new_op(tenant, "deploy", 1, at * 1000.0),
                    command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
                        "resources": [{
                            "resourceName": "fleet.bpmn",
                            "resource": to_bpmn_xml(serve_model(i + 1))}],
                        "tenantId": tenant}))
                events.append({"atMs": at * 1000.0, "action": "churn",
                               "tenant": tenant, "outcome": op.outcome})

        def storm() -> None:
            storm_at = sorted(
                (0.4 + side_rng.uniform(0.0, 0.4)) * cfg.drive_seconds
                for _ in range(min(cfg.storm_publishes, len(storm_keys))))
            targets = side_rng.sample(
                storm_keys, min(cfg.storm_publishes, len(storm_keys)))
            for at_s, ck in zip(storm_at, targets):
                delay = drive_t0[0] + at_s - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                if stop_streams.is_set():
                    return
                op = new_op("t-storm", "publish",
                            runtime.partition_for_correlation_key(ck),
                            at_s * 1000.0)
                execute(op, command(
                    ValueType.MESSAGE, MessageIntent.PUBLISH,
                    {"name": "fleet-msg", "correlationKey": ck,
                     "timeToLive": 120_000, "messageId": "",
                     "variables": {}, "tenantId": "t-storm"}))

        churn_thread = threading.Thread(target=churn, daemon=True,
                                        name="fleetday-churn")
        churn_thread.start()
        storm_thread = threading.Thread(target=storm, daemon=True,
                                        name="fleetday-storm")
        storm_thread.start()

        # rolling restarts: sequential kills through the middle of the
        # drive, each declaring an incident window on the drive clock
        for k in range(cfg.rolling_restarts):
            at = (0.35 + 0.4 * (k + 0.5) / cfg.rolling_restarts) \
                * cfg.drive_seconds
            delay = drive_t0[0] + at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            target = worker_names[k % len(worker_names)]
            logger.warning("fleetday: rolling restart of %s at t=%.1fs",
                           target, at)
            events.append({"atMs": drive_ms(), "action": "restart",
                           "target": target})
            supervisor.kill_worker(target)

        remaining = drive_t0[0] + cfg.drive_seconds - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        sched.join(timeout=10)
        churn_thread.join(timeout=10)
        storm_thread.join(timeout=10)
        drain_deadline = time.monotonic() + cfg.request_timeout_s + 10
        while time.monotonic() < drain_deadline and not arrivals.empty():
            time.sleep(0.2)
        for _ in threads:
            arrivals.put(None)
        stop_done = time.monotonic() + cfg.request_timeout_s + 10
        for t in threads:
            t.join(timeout=max(stop_done - time.monotonic(), 0.1))

        # disarm disk+device for a clean quiesce (tcp stays at its low
        # background rate — the consistency evidence must hold regardless)
        disk_disarm.write_text("disarm\n", encoding="utf-8")
        device_disarm.write_text("disarm\n", encoding="utf-8")
        quiesce_deadline = time.monotonic() + 90.0
        while time.monotonic() < quiesce_deadline:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                continue
        time.sleep(2.0)
        stop_streams.set()
        poller.join(timeout=5)

        # final audit ingest + snapshots (post-drive pushes included)
        with audit_lock:
            cluster_audit.ingest(dict(runtime._worker_status))
            report["onlineAudit"] = cluster_audit.snapshot()
        report["tieredState"]["parkedColdAfterStorm"] = parked_cold_total()
        report["workerRestarts"] = dict(supervisor.restarts)
        report["gatewayFlight"] = runtime.flight.snapshot()
    finally:
        stop_streams.set()
        try:
            runtime.stop()
        except Exception:  # noqa: BLE001 — teardown must reach evidence
            logger.exception("runtime stop failed")

    # ---- offline evidence + gates -----------------------------------------
    logs, log_violations = collect_logs(directory, worker_names,
                                        cfg.partitions)
    violations += log_violations
    violations += check_serving_history(history, logs)
    _, export_violations, re_exports = collect_exports(export_dir)
    violations += export_violations

    windows = incident_windows(events, cfg.incident_grace_s * 1000.0)
    slo_report, slo_violations = evaluate_fleet_slo(history, windows, cfg)
    violations += slo_violations
    report["slo"] = slo_report

    # chaos evidence: every plane must have LANDED at least one event
    plane_counts = {
        "tcp": sum_counts_files(
            sorted(directory.glob("*/chaos-counts-*.json"))),
        "disk": sum_counts_files(
            sorted(directory.glob("*/disk-chaos-counts-*.json"))),
        "device": sum_counts_files(
            sorted(directory.glob("*/device-chaos-counts-*.json"))),
    }
    report["chaosPlanes"] = plane_counts
    for plane, counts in plane_counts.items():
        if not sum(counts.values()):
            violations.append(
                f"chaos plane `{plane}` was armed but observed ZERO events "
                f"— the plane is not reaching its seam")

    # device corruption accounting (the PR 15 checker, death-waived for
    # restart-killed lives)
    corrupt_entries = read_jsonl_ledgers(
        sorted(directory.glob("*/device-corrupt-*.jsonl")))
    if corrupt_entries:
        surviving = {p for n in worker_names
                     if (p := supervisor.pid_of(n)) is not None}
        dead_pids = {e.get("pid") for e in corrupt_entries} - surviving
        corr_violations, corr_stats = check_corruption_accounting(
            corrupt_entries, dead_pids=dead_pids)
        violations += corr_violations
        report["corruptionAccounting"] = corr_stats

    # zero leak verdicts on the clean fleet
    worker_audits = report.get("onlineAudit", {}).get("workers", {})
    leak_verdicts = {w: a.get("leakVerdict") for w, a in
                     worker_audits.items()}
    report["leakVerdicts"] = leak_verdicts
    for worker, verdict in leak_verdicts.items():
        if verdict == "leak":
            violations.append(
                f"clean-fleet leak verdict on {worker}: the tree leaks, or "
                f"the detector's confidence gate is broken")

    # auditor recall: offline findings vs online flags — and precision the
    # other way: an online INVARIANT flag the offline evidence does not
    # corroborate is a false alarm (monitor bug), also a gate failure
    with audit_lock:
        flagged = cluster_audit.flagged_monitors()
    offline_snapshot = list(violations)
    recall_misses, recall_stats = check_auditor_recall(
        offline_snapshot, flagged)
    violations += recall_misses
    report["auditorRecall"] = recall_stats
    false_alarms = sorted((flagged & INVARIANT_MONITORS)
                          - offline_monitors(offline_snapshot))
    for monitor in false_alarms:
        violations.append(
            f"online invariant monitor `{monitor}` flagged during a run "
            f"the offline checker found clean — precision failure (false "
            f"alarm)")
    report["onlinePrecision"] = {"falseAlarms": false_alarms}

    # the leak-injection arm: the detector MUST fire with the same knobs
    leak_arm = run_leak_arm(cfg, directory / "leak-arm")
    report["leakArm"] = leak_arm
    if not leak_arm.get("fired"):
        violations.append(
            "leak-injection arm: the auditor never returned a leak verdict "
            "against a deliberately leaking worker — detector recall "
            "unproven")

    outcomes: dict[str, int] = {}
    for op in history:
        outcomes[op.outcome] = outcomes.get(op.outcome, 0) + 1
    churn_acked = sum(1 for e in events
                      if e["action"] == "churn" and e["outcome"] == "ack")
    restarts = sum(1 for e in events if e["action"] == "restart")
    if churn_acked < 1:
        violations.append("definition churn never landed (0 acked churn "
                          "deploys)")
    if restarts < 1:
        violations.append("no rolling restart was exercised")
    report.update({
        "workers": cfg.workers,
        "partitions": cfg.partitions,
        "replication": cfg.replication,
        "driveSeconds": cfg.drive_seconds,
        "requests": len(history),
        "outcomes": outcomes,
        "ackedCommands": outcomes.get("ack", 0),
        "definitionChurn": {"deploys": cfg.churn_deploys,
                            "acked": churn_acked},
        "rollingRestarts": restarts,
        "events": events,
        "reExportedRecords": re_exports,
        "logRecords": {str(p): len(r) for p, r in logs.items()},
        "violations": violations,
        "wallSeconds": round(time.monotonic() - started, 2),
    })
    return report


def main(argv: list[str] | None = None) -> int:  # pragma: no cover — manual
    from zeebe_tpu.testing.serving import gate_cli_main

    return gate_cli_main("zeebe-tpu-fleetday", FleetDayConfig(),
                         FULL_FLEETDAY, run_fleetday, argv)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
