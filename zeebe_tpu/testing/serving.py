"""Open-loop SLO'd serving gate (ISSUE 11, ROADMAP item 4).

Every earlier gate drives closed-loop bursts from one cooperative client —
exactly how overload failures hide, because a closed-loop driver slows down
when the server does and the p99 lies. This harness drives the REAL
multi-process cluster (supervised worker processes over TCP, PR 7) with
**open-loop Poisson arrivals**: the offered load is a seeded arrival
schedule fixed before the run, dispatched by hundreds of concurrent client
streams, and a request's latency is measured from its SCHEDULED arrival —
dispatch queueing is part of the number, never hidden.

The workload is shaped like a tenant fleet:

- several **well-behaved tenants** at a fixed offered rate inside their
  quotas (their p50/p99 ack latency is the SLO under test);
- one **hot tenant** whose rate ramps (a diurnal ramp) to ~5x its
  token-bucket quota — it must saturate its OWN share and collect typed,
  fast ``RESOURCE_EXHAUSTED`` sheds while the others keep their SLO;
- a **storm tenant** holding a pool of message-wait instances that park and
  spill to the PR 8 cold store, then a correlation storm mid-drive that
  wakes them from cold;
- a live **worker kill** (PR 9 chaos) in the final phase, with goodput
  gated against the no-chaos window.

Phases: ``warm`` (deploy per tenant, build + park the storm pool) →
``A`` calm (everyone in quota: the fairness/goodput reference) → ``B``
overload (hot ramp + correlation storm) → ``C`` overload + chaos (worker
kill). Offline, the workers' journals are read back and every acked
request must appear exactly once (the PR 9 consistency evidence reused).

``bench.py --serving [--quick]`` runs this and writes
``SERVING[_quick].json``; the CI ``serving-smoke`` job gates on it.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import random
import sys
import threading
import time
from pathlib import Path
from typing import Any, Callable

from zeebe_tpu.testing.evidence import percentile

logger = logging.getLogger("zeebe_tpu.testing.serving")


# ---------------------------------------------------------------------------
# configuration


@dataclasses.dataclass
class TenantSpec:
    name: str
    kind: str                 # "well" | "hot" | "storm"
    rate_a: float             # offered arrivals/s in phase A (calm)
    rate_bc: float            # offered arrivals/s in phases B/C
    quota_rate: float         # token-bucket quota (0 = unmetered)
    quota_burst: float = 0.0
    weight: float = 1.0


def _default_tenants() -> list[TenantSpec]:
    return [
        TenantSpec("t-well-0", "well", 8.0, 8.0, quota_rate=20.0),
        TenantSpec("t-well-1", "well", 8.0, 8.0, quota_rate=20.0),
        TenantSpec("t-well-2", "well", 8.0, 8.0, quota_rate=20.0),
        # the hot tenant ramps to 5x its quota at the A->B boundary
        TenantSpec("t-hot", "hot", 6.0, 40.0, quota_rate=8.0,
                   quota_burst=16.0),
    ]


@dataclasses.dataclass
class ServingConfig:
    seed: int = 0
    workers: int = 3
    partitions: int = 2
    replication: int = 3
    #: concurrent client streams dispatching the arrival schedule
    client_streams: int = 128
    phase_a_seconds: float = 8.0
    phase_b_seconds: float = 8.0
    phase_c_seconds: float = 10.0
    #: diurnal ramp length at the A->B boundary (rate_a -> rate_bc)
    ramp_seconds: float = 3.0
    request_timeout_s: float = 15.0
    tenants: list[TenantSpec] = dataclasses.field(
        default_factory=_default_tenants)
    #: storm pool: message-wait instances parked + spilled cold before the
    #: storm (state tiering, PR 8)
    parked_instances: int = 150
    storm_publishes: int = 60
    park_after_ms: int = 500
    spill_batch: int = 256
    park_wait_s: float = 25.0          # wait-for-spill ceiling in warm phase
    park_fraction: float = 0.3         # spilled fraction required pre-storm
    #: live chaos: worker kills in phase C
    kill_workers: int = 1
    # -- gates ----------------------------------------------------------------
    slo_p50_ms: float = 1000.0
    slo_p99_ms: float = 5000.0
    #: fairness: well-behaved p99 under overload+chaos may not exceed
    #: max(mult x calm p99, floor)
    fairness_mult: float = 4.0
    fairness_floor_ms: float = 2000.0
    #: goodput: well-behaved acked/s in the chaos phase vs the calm phase
    goodput_floor: float = 0.7
    #: sheds must be FAST (typed rejections, not queued timeouts): p95 bound
    shed_fast_ms: float = 1000.0
    kernel_backend: bool = False       # quick/CI: skip per-worker XLA warmup


FULL_CONFIG = ServingConfig(
    workers=4, partitions=4, client_streams=384,
    phase_a_seconds=30.0, phase_b_seconds=30.0, phase_c_seconds=40.0,
    parked_instances=1000, storm_publishes=400, kill_workers=2,
    tenants=[
        TenantSpec("t-well-0", "well", 20.0, 20.0, quota_rate=50.0),
        TenantSpec("t-well-1", "well", 20.0, 20.0, quota_rate=50.0),
        TenantSpec("t-well-2", "well", 20.0, 20.0, quota_rate=50.0),
        TenantSpec("t-well-3", "well", 20.0, 20.0, quota_rate=50.0),
        TenantSpec("t-hot", "hot", 10.0, 100.0, quota_rate=20.0,
                   quota_burst=40.0),
    ])


# ---------------------------------------------------------------------------
# open-loop arrival schedule (pure, seeded — unit-testable)


def poisson_schedule(rng: random.Random, duration_s: float,
                     rate_fn: Callable[[float], float],
                     max_rate: float) -> list[float]:
    """Non-homogeneous Poisson arrivals on [0, duration) by thinning: draw
    exponential gaps at ``max_rate``, keep each point with probability
    ``rate(t)/max_rate``. Deterministic for a given rng state."""
    if max_rate <= 0:
        return []
    out: list[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(max_rate)
        if t >= duration_s:
            return out
        if rng.random() * max_rate <= rate_fn(t):
            out.append(t)


def tenant_rate_fn(spec: TenantSpec, phase_a_s: float,
                   ramp_s: float) -> Callable[[float], float]:
    """Offered rate over the whole drive: flat ``rate_a`` through phase A,
    then a linear (diurnal-shoulder) ramp to ``rate_bc``."""

    def rate(t: float) -> float:
        if t < phase_a_s:
            return spec.rate_a
        if ramp_s > 0 and t < phase_a_s + ramp_s:
            frac = (t - phase_a_s) / ramp_s
            return spec.rate_a + (spec.rate_bc - spec.rate_a) * frac
        return spec.rate_bc

    return rate


def drain_arrival_queue(arrivals: "queue.Queue",
                        stop: threading.Event,
                        submit: Callable[[float, str], None]) -> None:
    """The client-stream body shared by the serving and fleet-day
    harnesses: drain due ``(at_ms, tenant)`` arrivals and submit each,
    never waiting on another stream's request. ``None`` drains the
    stream; ``stop`` abandons whatever is still queued."""
    while not stop.is_set():
        try:
            item = arrivals.get(timeout=0.2)
        except queue.Empty:
            continue
        if item is None:
            return
        at_ms, tenant = item
        submit(at_ms, tenant)


def build_schedule(cfg: ServingConfig) -> list[tuple[float, str]]:
    """The merged ``(at_s, tenant)`` arrival schedule for the whole drive,
    sorted by time; one independent seeded stream per tenant."""
    drive_s = cfg.phase_a_seconds + cfg.phase_b_seconds + cfg.phase_c_seconds
    merged: list[tuple[float, str]] = []
    for idx, spec in enumerate(cfg.tenants):
        rng = random.Random((cfg.seed << 8) ^ (idx + 1))
        rate = tenant_rate_fn(spec, cfg.phase_a_seconds, cfg.ramp_seconds)
        peak = max(spec.rate_a, spec.rate_bc)
        merged.extend((t, spec.name)
                      for t in poisson_schedule(rng, drive_s, rate, peak))
    merged.sort()
    return merged


# ---------------------------------------------------------------------------
# history + offline checks (pure — unit-testable without a cluster)


@dataclasses.dataclass
class ServingOp:
    """One open-loop request as the client fleet observed it."""

    index: int
    tenant: str
    kind: str                      # "create" | "publish" | "deploy"
    partition: int
    scheduled_ms: float            # offered arrival time (drive clock)
    started_ms: float = 0.0        # when a client stream picked it up
    done_ms: float = 0.0
    outcome: str = "pending"       # ack | rejected | shed | deadline
                                   # | no-leader | error
    request_id: int = -1
    position: int = -1
    shed_reason: str | None = None
    rejection: str | None = None
    resends: int = 0
    reroutes: int = 0

    @property
    def latency_ms(self) -> float:
        """Open-loop latency: scheduled arrival -> completion (dispatch
        queueing included — that is the point of open loop)."""
        return self.done_ms - self.scheduled_ms

    def row(self) -> dict:
        return dataclasses.asdict(self)


def check_serving_history(history: list["ServingOp"],
                          logs: dict[int, list[dict]]) -> list[str]:
    """Offline exactly-once evidence over the authoritative logs (the PR 9
    reader reused): every acked request appears as a command in its
    partition's committed log (no acked loss), and no request id owns more
    than one command position (no duplicate application). The per-partition
    monotone-ack check from the consistency gate does NOT apply — serving
    drivers are concurrent by design."""
    from zeebe_tpu.protocol import RecordType

    violations: list[str] = []
    command_rt = int(RecordType.COMMAND)
    cmd_positions: dict[int, dict[int, list[int]]] = {}
    for partition, records in logs.items():
        per = cmd_positions.setdefault(partition, {})
        for rec in records:
            rid = rec.get("rid", -1)
            if rid >= 0 and rec["rt"] == command_rt:
                per.setdefault(rid, []).append(rec["p"])
        for rid, positions in per.items():
            if len(positions) > 1:
                violations.append(
                    f"partition {partition}: request {rid} appended "
                    f"{len(positions)} times at {positions} (duplicate "
                    f"application)")
    for op in history:
        if op.outcome != "ack":
            continue
        positions = cmd_positions.get(op.partition, {}).get(op.request_id, [])
        if not positions:
            violations.append(
                f"partition {op.partition}: acked request {op.request_id} "
                f"(op #{op.index}, tenant {op.tenant}) has no command in "
                f"the log (acked loss)")
        elif op.position >= 0 and op.position not in positions:
            violations.append(
                f"partition {op.partition}: acked request {op.request_id} "
                f"acked position {op.position} but the log has it at "
                f"{positions}")
    return violations


def _phase_of(op: ServingOp, cfg: ServingConfig) -> str:
    if op.scheduled_ms < 0:
        return "warm"   # deploys/pool builds before the drive clock starts
    a_ms = cfg.phase_a_seconds * 1000.0
    b_ms = a_ms + cfg.phase_b_seconds * 1000.0
    if op.scheduled_ms < a_ms:
        return "A"
    return "B" if op.scheduled_ms < b_ms else "C"


def _latency_stats(latencies: list[float]) -> dict:
    if not latencies:
        return {"count": 0}
    ordered = sorted(latencies)
    return {
        "count": len(ordered),
        "p50Ms": round(percentile(ordered, 0.50), 1),
        "p95Ms": round(percentile(ordered, 0.95), 1),
        "p99Ms": round(percentile(ordered, 0.99), 1),
        "maxMs": round(ordered[-1], 1),
    }


def evaluate_gates(history: list[ServingOp], cfg: ServingConfig) -> tuple[
        dict, list[str]]:
    """The serving SLO/fairness/goodput/shed gates over a finished history.
    Pure — the unit tests drive it with synthetic histories."""
    violations: list[str] = []
    by_tenant: dict[str, list[ServingOp]] = {}
    for op in history:
        by_tenant.setdefault(op.tenant, []).append(op)
    kinds = {spec.name: spec.kind for spec in cfg.tenants}
    kinds.setdefault("t-storm", "storm")

    report: dict[str, Any] = {"tenants": {}}
    well_calm: list[float] = []
    well_overload: list[float] = []   # phase B: hot tenant at 5x, no chaos
    well_load: list[float] = []       # phases B+C: overload AND chaos
    calm_acked = 0
    chaos_acked = 0
    for tenant, ops in sorted(by_tenant.items()):
        acked = [op for op in ops if op.outcome == "ack"]
        sheds = [op for op in ops if op.outcome == "shed"]
        phases: dict[str, dict] = {}
        for phase in ("A", "B", "C"):
            phase_acked = [op.latency_ms for op in acked
                           if _phase_of(op, cfg) == phase]
            phases[phase] = _latency_stats(phase_acked)
        outcomes: dict[str, int] = {}
        for op in ops:
            outcomes[op.outcome] = outcomes.get(op.outcome, 0) + 1
        report["tenants"][tenant] = {
            "kind": kinds.get(tenant, "?"),
            "offered": len(ops),
            "outcomes": outcomes,
            "ackedByPhase": phases,
            "shedLatency": _latency_stats(
                [op.latency_ms for op in sheds]),
            "shedReasons": _count(op.shed_reason for op in sheds),
        }
        if kinds.get(tenant) == "well":
            for op in acked:
                phase = _phase_of(op, cfg)
                if phase == "A":
                    well_calm.append(op.latency_ms)
                elif phase == "B":
                    well_overload.append(op.latency_ms)
                    well_load.append(op.latency_ms)
                elif phase == "C":
                    well_load.append(op.latency_ms)
            calm_acked += sum(1 for op in acked if _phase_of(op, cfg) == "A")
            chaos_acked += sum(1 for op in acked if _phase_of(op, cfg) == "C")
        # no silent drops for ANY tenant — a hot-tenant op that never
        # reached a terminal outcome is as much a drop as a well-behaved one
        pending = outcomes.get("pending", 0)
        if pending:
            violations.append(
                f"tenant {tenant}: {pending} op(s) never completed "
                f"(silent drop)")

    # gate 1: absolute SLO for the well-behaved population under load
    load_stats = _latency_stats(well_load)
    calm_stats = _latency_stats(well_calm)
    report["wellBehaved"] = {"calm": calm_stats, "underLoad": load_stats}
    if load_stats.get("count"):
        if load_stats["p99Ms"] > cfg.slo_p99_ms:
            violations.append(
                f"well-behaved p99 under overload+chaos "
                f"{load_stats['p99Ms']}ms > SLO {cfg.slo_p99_ms}ms")
        if load_stats["p50Ms"] > cfg.slo_p50_ms:
            violations.append(
                f"well-behaved p50 under overload+chaos "
                f"{load_stats['p50Ms']}ms > SLO {cfg.slo_p50_ms}ms")
    else:
        violations.append("no well-behaved acks under load (no SLO evidence)")

    # gate 2: fairness — the hot tenant's overload (phase B: 5x quota, no
    # chaos yet) must not move the well-behaved p99 beyond the bound
    # relative to the calm reference. Phase C's kill is deliberately NOT in
    # this comparison — the chaos tail is the absolute-SLO and goodput
    # gates' business; folding it in here would blame re-election latency
    # on the hot tenant.
    overload_stats = _latency_stats(well_overload)
    if calm_stats.get("count") and overload_stats.get("count"):
        bound = max(cfg.fairness_mult * calm_stats["p99Ms"],
                    cfg.fairness_floor_ms)
        report["fairness"] = {"calmP99Ms": calm_stats["p99Ms"],
                              "overloadP99Ms": overload_stats["p99Ms"],
                              "boundMs": round(bound, 1)}
        if overload_stats["p99Ms"] > bound:
            violations.append(
                f"fairness: well-behaved p99 moved {calm_stats['p99Ms']}ms "
                f"-> {overload_stats['p99Ms']}ms under the hot tenant "
                f"(bound {bound:.0f}ms)")

    # gate 3: the hot tenant is shed — typed and fast — and cannot push its
    # acked volume materially past its quota
    hot = [spec for spec in cfg.tenants if spec.kind == "hot"]
    for spec in hot:
        ops = by_tenant.get(spec.name, [])
        sheds = [op for op in ops if op.outcome == "shed"]
        load_s = cfg.phase_b_seconds + cfg.phase_c_seconds
        hot_acked = [op for op in ops if op.outcome == "ack"
                     and _phase_of(op, cfg) != "A"]
        if not sheds:
            violations.append(
                f"hot tenant {spec.name} was never shed at "
                f"{max(spec.rate_bc, 0):.0f}/s against a "
                f"{spec.quota_rate:.0f}/s quota")
            continue
        shed_lat = sorted(op.latency_ms for op in sheds)
        p95 = percentile(shed_lat, 0.95)
        if p95 > cfg.shed_fast_ms:
            violations.append(
                f"hot tenant sheds are slow: p95 {p95:.0f}ms > "
                f"{cfg.shed_fast_ms:.0f}ms (sheds must be typed rejections, "
                f"not queued timeouts)")
        allowed = spec.quota_rate * load_s * 2.0 + spec.quota_burst
        if len(hot_acked) > allowed:
            violations.append(
                f"hot tenant acked {len(hot_acked)} commands under "
                f"overload — quota {spec.quota_rate}/s x {load_s:.0f}s not "
                f"enforced (allowed ~{allowed:.0f})")

    # gate 4: goodput — shed-instead-of-collapse: the well-behaved fleet's
    # acked/s with chaos live stays within a floor of the calm baseline
    if cfg.phase_a_seconds > 0 and cfg.phase_c_seconds > 0 and calm_acked:
        calm_rate = calm_acked / cfg.phase_a_seconds
        chaos_rate = chaos_acked / cfg.phase_c_seconds
        report["goodput"] = {
            "calmAckedPerSec": round(calm_rate, 2),
            "chaosAckedPerSec": round(chaos_rate, 2),
            "floor": cfg.goodput_floor,
        }
        if chaos_rate < cfg.goodput_floor * calm_rate:
            violations.append(
                f"goodput collapsed under chaos: {chaos_rate:.1f} acked/s "
                f"vs calm {calm_rate:.1f} (floor "
                f"{cfg.goodput_floor:.0%})")

    # no silent drops anywhere: every op reached a terminal outcome and
    # errors are typed
    untyped = [op for op in history if op.outcome == "error"]
    for op in untyped[:10]:
        violations.append(
            f"op #{op.index} (tenant {op.tenant}) failed untyped: "
            f"{op.rejection}")
    return report, violations


def _count(items) -> dict:
    out: dict[str, int] = {}
    for item in items:
        key = str(item)
        out[key] = out.get(key, 0) + 1
    return out


def execute_op(runtime, op: ServingOp, record, timeout_s: float,
               drive_ms: Callable[[], float]) -> ServingOp:
    """Submit one open-loop op and record its terminal outcome + routing
    meta — ONE submission protocol for every open-loop harness (the
    serving gate and the autotune A/B), so their latency/outcome taxonomy
    cannot drift."""
    from zeebe_tpu.gateway.broker_client import (
        DeadlineExceededError,
        NoLeaderError,
        ResourceExhaustedError,
    )

    op.started_ms = drive_ms()
    meta: dict = {}
    try:
        result = runtime.submit(op.partition, record, timeout_s=timeout_s,
                                meta=meta)
        op.outcome = "rejected" if result.is_rejection else "ack"
        if result.is_rejection:
            op.rejection = result.rejection_type.name
    except ResourceExhaustedError as exc:
        op.outcome = "shed"
        # gateway-side sheds carry the admission reason; worker-side
        # sheds arrive as typed resource-exhausted/backpressure frames
        op.shed_reason = meta.get("shed") or meta.get("error") or "typed"
        op.rejection = str(exc)[:160]
    except DeadlineExceededError:
        op.outcome = "deadline"
    except NoLeaderError:
        op.outcome = "no-leader"
    except Exception as exc:  # noqa: BLE001 — untyped = gate evidence
        op.outcome = "error"
        op.rejection = repr(exc)[:200]
    op.done_ms = drive_ms()
    op.request_id = meta.get("requestId", -1)
    op.position = meta.get("commandPosition", -1)
    op.resends = meta.get("resends", 0)
    op.reroutes = meta.get("reroutes", 0)
    return op


def gate_cli_main(prog: str, quick_cfg, full_cfg, run_fn,
                  argv: list[str] | None = None) -> int:
    """Shared manual entry point for the open-loop gates: parse
    --seed/--quick, run in a temp dir, dump the report, exit on
    violations."""
    import argparse
    import tempfile

    parser = argparse.ArgumentParser(prog=prog)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args(argv)
    cfg = dataclasses.replace(quick_cfg if args.quick else full_cfg,
                              seed=args.seed)
    with tempfile.TemporaryDirectory(prefix=f"{prog}-") as tmp:
        report = run_fn(cfg, tmp)
    json.dump(report, sys.stdout, indent=2)
    return 1 if report["violations"] else 0


# ---------------------------------------------------------------------------
# the harness


def run_serving(cfg: ServingConfig, directory: str | Path) -> dict:
    """Run the full serving gate; returns the report (violations inside)."""
    from zeebe_tpu.gateway.admission import AdmissionCfg, AdmissionController
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
    from zeebe_tpu.multiproc.runtime import MultiProcClusterRuntime
    from zeebe_tpu.multiproc.supervisor import (
        WorkerSpec,
        WorkerSupervisor,
        worker_cmd,
    )
    from zeebe_tpu.protocol import ValueType
    from zeebe_tpu.protocol.intent import (
        DeploymentIntent,
        MessageIntent,
        ProcessInstanceCreationIntent,
    )
    from zeebe_tpu.protocol.record import command
    from zeebe_tpu.standalone import _free_ports
    from zeebe_tpu.testing.consistency import collect_logs

    directory = Path(directory)
    started = time.monotonic()
    report: dict[str, Any] = {"seed": cfg.seed}
    violations: list[str] = []

    worker_names = [f"worker-{i}" for i in range(cfg.workers)]
    ports = _free_ports(cfg.workers + 1)
    contacts = {n: ("127.0.0.1", p) for n, p in zip(worker_names, ports)}
    contacts["gateway-0"] = ("127.0.0.1", ports[-1])
    contact_str = ",".join(
        f"{m}={h}:{p}" for m, (h, p) in sorted(contacts.items()))

    quota_spec = ",".join(
        f"{s.name}={s.quota_rate:g}"
        + (f":{s.quota_burst:g}" if s.quota_burst else "")
        for s in cfg.tenants if s.quota_rate > 0)
    repo = str(Path(__file__).resolve().parent.parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["JAX_PLATFORMS"] = "cpu"
    if not cfg.kernel_backend:
        env["ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND"] = "false"
    # workers run the SAME admission knobs as the gateway (a multi-gateway
    # fleet cannot rely on one gateway's buckets) + tiering for the storm
    env["ZEEBE_GATEWAY_TENANT_QUOTAS"] = quota_spec
    env["ZEEBE_BROKER_DATA_TIERING_ENABLED"] = "true"
    env["ZEEBE_BROKER_DATA_TIERING_PARKAFTERMS"] = str(cfg.park_after_ms)
    env["ZEEBE_BROKER_DATA_TIERING_SPILLBATCH"] = str(cfg.spill_batch)

    specs = [WorkerSpec(
        node_id=name,
        cmd=worker_cmd(name, f"127.0.0.1:{contacts[name][1]}", contact_str,
                       "gateway-0", cfg.partitions, cfg.replication,
                       data_dir=str(directory / name)),
        data_dir=str(directory / name)) for name in worker_names]
    supervisor = WorkerSupervisor(specs, env=env, restart_backoff_s=0.2)
    admission = AdmissionController(
        AdmissionCfg(
            quotas={s.name: (s.quota_rate, s.quota_burst)
                    for s in cfg.tenants if s.quota_rate > 0},
            weights={s.name: s.weight for s in cfg.tenants}),
        node_id="gateway-0")
    runtime = MultiProcClusterRuntime(
        "gateway-0",
        {m: a for m, a in contacts.items() if m != "gateway-0"},
        partition_count=cfg.partitions, replication_factor=cfg.replication,
        bind=contacts["gateway-0"], supervisor=supervisor,
        admission=admission)
    admission.flight = runtime.flight

    history: list[ServingOp] = []
    history_lock = threading.Lock()
    op_seq = [0]
    events: list[dict] = []
    drive_t0 = [0.0]   # monotonic anchor of the drive clock, set at phase A

    def drive_ms() -> float:
        return (time.monotonic() - drive_t0[0]) * 1000.0

    def new_op(tenant: str, kind: str, partition: int,
               scheduled_ms: float) -> ServingOp:
        with history_lock:
            op_seq[0] += 1
            op = ServingOp(index=op_seq[0], tenant=tenant, kind=kind,
                           partition=partition, scheduled_ms=scheduled_ms)
            history.append(op)
        return op

    def execute(op: ServingOp, record) -> ServingOp:
        return execute_op(runtime, op, record, cfg.request_timeout_s,
                          drive_ms)

    def create_cmd(tenant: str):
        return command(ValueType.PROCESS_INSTANCE_CREATION,
                       ProcessInstanceCreationIntent.CREATE,
                       {"bpmnProcessId": "serve", "version": -1,
                        "variables": {}, "tenantId": tenant})

    def publish_cmd(ck: str):
        return command(ValueType.MESSAGE, MessageIntent.PUBLISH,
                       {"name": "serve-msg", "correlationKey": ck,
                        "timeToLive": 120_000, "messageId": "",
                        "variables": {}, "tenantId": "t-storm"})

    def parked_cold_total() -> int:
        return sum(
            info.get("parkedCold", 0)
            for status in runtime._worker_status.values()
            for info in status.get("partitions", {}).values()
            if info.get("role") == "leader")

    serve_model = (Bpmn.create_executable_process("serve")
                   .start_event("s").end_event("e").done())
    storm_model = (Bpmn.create_executable_process("serve_wait")
                   .start_event("s")
                   .intermediate_catch_message("wait",
                                               message_name="serve-msg",
                                               correlation_key="=ck")
                   .end_event("e").done())

    schedule = build_schedule(cfg)
    report["offeredArrivals"] = len(schedule)
    arrivals: "queue.Queue[tuple[float, str] | None]" = queue.Queue()
    stop_streams = threading.Event()

    def submit_create(at_ms: float, tenant: str) -> None:
        op = new_op(tenant, "create",
                    runtime.partition_for_new_instance(), at_ms)
        execute(op, create_cmd(tenant))

    def client_stream() -> None:
        """One of the hundreds of concurrent client streams."""
        drain_arrival_queue(arrivals, stop_streams, submit_create)

    def scheduler() -> None:
        """The open-loop clock: release each arrival AT its scheduled time
        regardless of how the cluster is doing."""
        for at_s, tenant in schedule:
            delay = drive_t0[0] + at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if stop_streams.is_set():
                return
            arrivals.put((at_s * 1000.0, tenant))

    try:
        runtime.start()
        boot_deadline = time.monotonic() + 180.0
        while True:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                if time.monotonic() >= boot_deadline:
                    raise

        # ---- warm phase: per-tenant deployments + the storm pool ----------
        drive_t0[0] = time.monotonic()   # provisional clock for warm-up ops
        tenant_names = [s.name for s in cfg.tenants]
        for tenant in tenant_names + ["t-storm"]:
            model = storm_model if tenant == "t-storm" else serve_model
            name = "serve_wait" if tenant == "t-storm" else "serve"
            op = execute(
                new_op(tenant, "deploy", 1, -1.0),
                command(ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
                    "resources": [{"resourceName": f"{name}.bpmn",
                                   "resource": to_bpmn_xml(model)}],
                    "tenantId": tenant}))
            if op.outcome != "ack":
                raise RuntimeError(f"deploy for {tenant} failed: {op.row()}")
        # deployment distribution: every partition must serve every tenant
        for pid in range(1, cfg.partitions + 1):
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                probe = execute(new_op(tenant_names[0], "create", pid, -1.0),
                                create_cmd(tenant_names[0]))
                if probe.outcome == "ack":
                    break
                time.sleep(0.25)
            else:
                raise RuntimeError(
                    f"partition {pid} never served a create; last probe: "
                    f"{probe.row()}")

        storm_keys = [f"serve-ck-{i}" for i in range(cfg.parked_instances)]
        for ck in storm_keys:
            op = execute(
                new_op("t-storm", "create",
                       runtime.partition_for_new_instance(), -1.0),
                command(ValueType.PROCESS_INSTANCE_CREATION,
                        ProcessInstanceCreationIntent.CREATE,
                        {"bpmnProcessId": "serve_wait", "version": -1,
                         "variables": {"ck": ck}, "tenantId": "t-storm"}))
            if op.outcome != "ack":
                violations.append(
                    f"storm pool create failed: {op.outcome} ({op.rejection})")
        # wait for the pool to park AND spill to the cold store (tiering):
        # the storm must wake instances from COLD, not from hot state
        want_cold = int(cfg.parked_instances * cfg.park_fraction)
        park_deadline = time.monotonic() + cfg.park_wait_s
        while time.monotonic() < park_deadline:
            if parked_cold_total() >= want_cold:
                break
            time.sleep(0.5)
        parked_before = parked_cold_total()
        report["stormPool"] = {"instances": cfg.parked_instances,
                               "parkedColdBeforeStorm": parked_before}
        if parked_before < want_cold:
            violations.append(
                f"storm pool never tiered cold: {parked_before} spilled "
                f"< {want_cold} wanted (tiering evidence missing)")

        # ---- the open-loop drive -----------------------------------------
        drive_t0[0] = time.monotonic()   # the REAL drive clock
        streams = [threading.Thread(target=client_stream, daemon=True,
                                    name=f"stream-{i}")
                   for i in range(cfg.client_streams)]
        for t in streams:
            t.start()
        sched = threading.Thread(target=scheduler, daemon=True,
                                 name="serving-scheduler")
        sched.start()

        a_end = cfg.phase_a_seconds
        b_end = a_end + cfg.phase_b_seconds
        drive_end = b_end + cfg.phase_c_seconds

        # correlation storm: spread across phase B, each publish is an
        # open-loop op of the storm tenant riding its own client stream
        storm_rng = random.Random(cfg.seed ^ 0x5702)
        storm_at = sorted(
            a_end + storm_rng.uniform(0.05, 0.95) * cfg.phase_b_seconds
            for _ in range(min(cfg.storm_publishes, len(storm_keys))))
        storm_targets = storm_rng.sample(
            storm_keys, min(cfg.storm_publishes, len(storm_keys)))

        def storm() -> None:
            for at_s, ck in zip(storm_at, storm_targets):
                delay = drive_t0[0] + at_s - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                if stop_streams.is_set():
                    return
                op = new_op("t-storm", "publish",
                            runtime.partition_for_correlation_key(ck),
                            at_s * 1000.0)
                execute(op, publish_cmd(ck))

        storm_thread = threading.Thread(target=storm, daemon=True,
                                        name="serving-storm")
        storm_thread.start()

        # live chaos: kill leaders in phase C while the drive keeps offering
        kill_rng = random.Random(cfg.seed ^ 0xC4A0)
        for k in range(cfg.kill_workers):
            at = b_end + (k + 1) * cfg.phase_c_seconds / (cfg.kill_workers + 1)
            delay = drive_t0[0] + at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            target = runtime._leader_of(1 + k % cfg.partitions) or \
                worker_names[kill_rng.randrange(len(worker_names))]
            logger.warning("serving chaos: killing %s at t=%.1fs", target, at)
            events.append({"atMs": drive_ms(), "action": "kill",
                           "target": target})
            supervisor.kill_worker(target)

        remaining = drive_t0[0] + drive_end - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        sched.join(timeout=10)
        storm_thread.join(timeout=10)
        # let in-flight requests finish, then release the streams
        drain_deadline = time.monotonic() + cfg.request_timeout_s + 10
        while time.monotonic() < drain_deadline and not arrivals.empty():
            time.sleep(0.2)
        for _ in streams:
            arrivals.put(None)
        stop_done = time.monotonic() + cfg.request_timeout_s + 10
        for t in streams:
            t.join(timeout=max(stop_done - time.monotonic(), 0.1))
        stop_streams.set()

        # quiesce: leaders back after the kill, storm wake evidence settled
        quiesce_deadline = time.monotonic() + 90.0
        while time.monotonic() < quiesce_deadline:
            try:
                runtime.await_leaders(timeout_s=5.0)
                break
            except RuntimeError:
                continue
        time.sleep(2.0)
        parked_after = parked_cold_total()
        report["stormPool"]["parkedColdAfterStorm"] = parked_after
        storm_acked = sum(1 for op in history
                          if op.kind == "publish" and op.outcome == "ack")
        report["stormPool"]["publishesAcked"] = storm_acked
        if parked_before > 0 and storm_acked > 0 \
                and parked_after >= parked_before:
            violations.append(
                f"correlation storm acked {storm_acked} publishes but the "
                f"cold tier never shrank ({parked_before} -> {parked_after}"
                f") — no wake-from-cold evidence")
        report["admission"] = runtime.admission.snapshot()
        report["clusterStatus"] = {
            "routingEpochs": runtime.routing_epoch,
            "workerRestarts": dict(supervisor.restarts),
        }
        report["gatewayFlight"] = runtime.flight.snapshot()
    finally:
        stop_streams.set()
        try:
            runtime.stop()
        except Exception:  # noqa: BLE001 — teardown must reach evidence
            logger.exception("runtime stop failed")

    # ---- offline evidence + gates -----------------------------------------
    logs, log_violations = collect_logs(directory, worker_names,
                                        cfg.partitions)
    violations += log_violations
    violations += check_serving_history(history, logs)
    gates, gate_violations = evaluate_gates(history, cfg)
    violations += gate_violations
    report.update(gates)

    outcomes: dict[str, int] = {}
    for op in history:
        outcomes[op.outcome] = outcomes.get(op.outcome, 0) + 1
    report.update({
        "workers": cfg.workers,
        "partitions": cfg.partitions,
        "replication": cfg.replication,
        "clientStreams": cfg.client_streams,
        "phases": {"aSeconds": cfg.phase_a_seconds,
                   "bSeconds": cfg.phase_b_seconds,
                   "cSeconds": cfg.phase_c_seconds,
                   "rampSeconds": cfg.ramp_seconds},
        "requests": len(history),
        "outcomes": outcomes,
        "ackedCommands": outcomes.get("ack", 0),
        "shedCommands": outcomes.get("shed", 0),
        "kills": len(events),
        "events": events,
        "logRecords": {str(p): len(r) for p, r in logs.items()},
        "violations": violations,
        "wallSeconds": round(time.monotonic() - started, 2),
    })
    return report


def main(argv: list[str] | None = None) -> int:  # pragma: no cover — manual
    return gate_cli_main("zeebe-tpu-serving", ServingConfig(), FULL_CONFIG,
                         run_serving, argv)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
