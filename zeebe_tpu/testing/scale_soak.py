"""Million-instance scale soak: long-lived parked state as a gate (ISSUE 8).

ROADMAP item 4's acceptance harness: park a production-scale backlog of
process instances (waiting on messages, timers, and jobs) on a tiered-state
broker, keep traffic flowing (correlation storms that wake cold instances,
snapshots + log compaction under load), crash it mid-spill and mid-snapshot,
and assert after every restart:

- **bounded RSS** — peak resident memory stays under ``rss_bound_bytes``
  while the cold tier (state/tiering.py) holds the parked majority (the
  ``rss_watermark`` alert rule is armed at the same bound as a live
  monitor);
- **zero acked-record loss** — every client-acknowledged command reaches
  the export stream exactly once; the export ledger is CONTIGUITY-based
  (O(1) memory at a million instances: the stream assigns dense positions,
  so "no gap ever appeared" + "covered past every acked position" is
  completeness) with a bounded CRC window proving re-exports after restarts
  byte-identical;
- **recovery within budget** — every rebuild (including the one that finds
  a torn snapshot tip, and the one interrupted mid-spill) lands inside
  ``recovery_budget_ms`` with the flight recorder carrying the artifact;
- **wake-after-recovery** — messages published *after* a crash correlate
  into instances parked (and spilled) *before* it;
- **flat sweeps** — a due-date sweep over the fully-parked backlog is timed
  and reported (the slow test asserts 1k vs 100k within the 2× bound).

Bulk-park phases run with the raft journal's ``delayed`` flush policy (the
reference DelayedFlusher — a legitimate bulk-import posture); before any
crash the journal is fsynced and the policy returns to ``immediate``, so
the acked-loss invariant is never asserted against bytes that were
legitimately allowed to be volatile.

Built on the PR 1 chaos harness (seeded, deterministic), PR 4/5
observability (flight recorder, alert evaluator, RSS self-metrics), and
PR 6 recovery budgets (incremental snapshot chains, compaction guards).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
import zlib
from collections import deque
from pathlib import Path
from typing import Any

from zeebe_tpu.exporters import Exporter
from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
from zeebe_tpu.protocol import ValueType, command
from zeebe_tpu.protocol.intent import (
    DeploymentIntent,
    MessageIntent,
    ProcessInstanceCreationIntent,
)
from zeebe_tpu.testing.chaos import ChaosHarness, FaultPlan
from zeebe_tpu.utils.metrics import _read_rss_bytes


@dataclasses.dataclass
class ScaleSoakConfig:
    """Quick mode (CI smoke): ≥100k parked. Full mode: 1M+."""

    seed: int = 20260804
    target_parked: int = 100_000
    #: park mix: message-wait / long-timer / job-wait fractions
    msg_fraction: float = 0.55
    timer_fraction: float = 0.30
    batch_size: int = 1_000
    #: correlation storm: bursts × publishes per burst (wakes cold instances)
    storm_bursts: int = 3
    storm_size: int = 1_500
    #: post-crash wake probe: publishes against pre-crash parked keys
    wake_probe: int = 400
    snapshot_period_ms: int = 2_500
    recovery_budget_ms: int = 90_000
    snapshot_chain_length: int = 6
    park_after_ms: int = 1_500
    spill_batch: int = 8_192
    #: peak-RSS gate (and the rss_watermark alert threshold). The peak
    #: includes one full-hot recovery residency: a crash-restart loads the
    #: snapshot chain entirely hot before the manager re-spills.
    rss_bound_bytes: int = 3584 << 20
    #: the sharper bounded-RSS claim: while bulk-parking (phase B), resident
    #: growth per newly-parked instance must stay under this — cold-tier
    #: spilling is what keeps it far below the decoded-object footprint
    max_hot_growth_per_parked: int = 4096
    #: at the parked peak, at least this fraction of instances must be cold
    min_spilled_fraction: float = 0.5
    step_ms: int = 50
    #: park timers far beyond the soak's clock horizon
    timer_duration: str = "PT8H"
    partition_id: int = 1
    drain_ticks: int = 600
    #: replay≡live byte-parity oracle at the end (the "spilled instance
    #: survives crash-recovery byte-identically" receipt); O(state) — the
    #: 1M full config turns it off
    replay_parity_check: bool = True


FULL_CONFIG = ScaleSoakConfig(
    target_parked=1_000_000,
    storm_bursts=5, storm_size=10_000, wake_probe=2_000,
    snapshot_period_ms=10_000,
    rss_bound_bytes=8 << 30,
    recovery_budget_ms=300_000,
    replay_parity_check=False,
)


class ExportLedger:
    """Cross-lifetime export ledger in O(1) memory.

    The stream assigns dense positions, and within one exporter-container
    lifetime exports arrive in strictly increasing position order starting
    at or below the acked watermark — so completeness is contiguity:
    ``covered_upto`` advances record by record, any jump past
    ``covered_upto + 1`` is a lost-record violation, and every re-export
    (position ≤ ``covered_upto``) must match the CRC remembered for that
    position. The CRC window is bounded (restart catch-up replays only the
    un-acked-snapshot suffix, which is recent by the snapshot-cadence
    invariant); a re-export older than the window counts as unverified
    rather than guessed at."""

    def __init__(self, crc_window: int = 400_000) -> None:
        self.covered_upto = 0
        self.total = 0
        self.reexports = 0
        self.reexports_unverified = 0
        self.violations: list[str] = []
        self._crc: dict[int, int] = {}
        self._crc_order: deque[int] = deque()
        self._crc_window = crc_window

    def observe(self, position: int, data: bytes, lifetime: str) -> None:
        self.total += 1
        crc = zlib.crc32(data) & 0xFFFFFFFF
        if position <= self.covered_upto:
            self.reexports += 1
            seen = self._crc.get(position)
            if seen is None:
                self.reexports_unverified += 1
            elif seen != crc:
                self.violations.append(
                    f"divergent re-export at position {position} "
                    f"({lifetime}): content changed across restarts")
            return
        if position != self.covered_upto + 1:
            self.violations.append(
                f"export gap: position {position} after covered "
                f"{self.covered_upto} ({lifetime}) — records lost")
        self.covered_upto = max(self.covered_upto, position)
        self._crc[position] = crc
        self._crc_order.append(position)
        if len(self._crc_order) > self._crc_window:
            self._crc.pop(self._crc_order.popleft(), None)


class ScaleSoakExporter(Exporter):
    """Strict-ordering exporter over the shared ledger (one instance per
    container lifetime; the ledger survives the whole soak)."""

    _lifetimes = 0

    def __init__(self, ledger: ExportLedger) -> None:
        self.ledger = ledger
        ScaleSoakExporter._lifetimes += 1
        self._lifetime = f"life-{ScaleSoakExporter._lifetimes}"
        self._last = -1

    def export(self, record) -> None:
        pos = record.position
        if pos <= self._last:
            self.ledger.violations.append(
                f"duplicate export within container lifetime "
                f"{self._lifetime}: {pos} after {self._last}")
        self._last = pos
        self.ledger.observe(pos, record.record.to_bytes(), self._lifetime)
        self.controller.update_last_exported_position(pos)


def _models(timer_duration: str):
    msg = (Bpmn.create_executable_process("scale_msg")
           .start_event("s")
           .intermediate_catch_message("wait", message_name="scale-msg",
                                       correlation_key="=ck")
           .end_event("e").done())
    tmr = (Bpmn.create_executable_process("scale_tmr")
           .start_event("s")
           .intermediate_catch_timer("wait", duration=timer_duration)
           .end_event("e").done())
    job = (Bpmn.create_executable_process("scale_job")
           .start_event("s").service_task("t", job_type="scale-work")
           .end_event("e").done())
    return msg, tmr, job


class ScaleSoakHarness:
    def __init__(self, cfg: ScaleSoakConfig | None = None,
                 directory: str | Path | None = None) -> None:
        self.cfg = cfg or ScaleSoakConfig()
        # arm the RSS alert monitor at the soak's own bound (default_rules
        # reads the env at broker construction)
        os.environ["ZEEBE_ALERT_RSSWATERMARKBYTES"] = str(
            self.cfg.rss_bound_bytes)
        self.ledger = ExportLedger()
        self.rng = random.Random(self.cfg.seed)
        self.chaos = ChaosHarness(
            FaultPlan(seed=self.cfg.seed),
            broker_count=1, partition_count=1, replication_factor=1,
            directory=directory,
            exporters_factory=lambda: {"scale": ScaleSoakExporter(self.ledger)},
            step_ms=self.cfg.step_ms,
            snapshot_period_ms=self.cfg.snapshot_period_ms,
            recovery_budget_ms=self.cfg.recovery_budget_ms,
            snapshot_chain_length=self.cfg.snapshot_chain_length,
            tiering=True,
            tiering_park_after_ms=self.cfg.park_after_ms,
            tiering_spill_batch=self.cfg.spill_batch,
        )
        self.cluster = self.chaos.cluster
        self.violations: list[str] = []
        self.recoveries: list[dict] = []
        self.flight_dumps: list[str] = []
        self.acked_ranges: list[tuple[int, int]] = []
        self.created = 0
        self.parked_keys: list[str] = []     # live message correlation keys
        self.peak_spilled = 0
        self.peak_rss = 0
        self.sweep_probes: list[dict] = []
        self.timeline: list[dict] = []
        self._t0 = time.perf_counter()

    # -- plumbing --------------------------------------------------------------

    def _leader(self):
        return self.cluster.leader(self.cfg.partition_id)

    def _note(self, phase: str, **extra) -> None:
        self.timeline.append({
            "phase": phase,
            "wallS": round(time.perf_counter() - self._t0, 1),
            "rssBytes": self._sample_rss(),
            **extra})

    def _sample_rss(self) -> int:
        rss = int(_read_rss_bytes())
        self.peak_rss = max(self.peak_rss, rss)
        return rss

    def _write_batch(self, records: list) -> None:
        leader = self._leader()
        if leader is None:
            self.violations.append("lost the leader during traffic")
            return
        last = leader.write_commands(records)
        if last is None:
            return
        first = last - len(records) + 1
        self.chaos.run_ticks(1)
        leader = self._leader()
        if leader is not None and leader.stream.last_position >= last:
            # committed ⇒ acknowledged ⇒ covered by the durability pillar
            self.acked_ranges.append((first, last))

    def _observe_tiering(self) -> None:
        leader = self._leader()
        if leader is not None and leader.tiering is not None:
            self.peak_spilled = max(self.peak_spilled,
                                    leader.tiering.spilled_instances)
        self._sample_rss()

    # -- workload phases -------------------------------------------------------

    def _deploy(self) -> None:
        models = _models(self.cfg.timer_duration)
        self._write_batch([command(
            ValueType.DEPLOYMENT, DeploymentIntent.CREATE, {
                "resources": [
                    {"resourceName": f"scale-{m.process_id}.bpmn",
                     "resource": to_bpmn_xml(m)} for m in models]})])
        self.chaos.run_ticks(5)

    def _creation_batch(self, n: int) -> list:
        cfg = self.cfg
        out = []
        for _ in range(n):
            roll = self.rng.random()
            i = self.created
            self.created += 1
            if roll < cfg.msg_fraction:
                key = f"ck-{i}"
                self.parked_keys.append(key)
                out.append(command(
                    ValueType.PROCESS_INSTANCE_CREATION,
                    ProcessInstanceCreationIntent.CREATE,
                    {"bpmnProcessId": "scale_msg", "version": -1,
                     "variables": {"ck": key, "tag": i}}))
            elif roll < cfg.msg_fraction + cfg.timer_fraction:
                out.append(command(
                    ValueType.PROCESS_INSTANCE_CREATION,
                    ProcessInstanceCreationIntent.CREATE,
                    {"bpmnProcessId": "scale_tmr", "version": -1,
                     "variables": {"tag": i}}))
            else:
                out.append(command(
                    ValueType.PROCESS_INSTANCE_CREATION,
                    ProcessInstanceCreationIntent.CREATE,
                    {"bpmnProcessId": "scale_job", "version": -1,
                     "variables": {"tag": i}}))
        return out

    def _park_until(self, target: int, label: str) -> None:
        """Bulk-park up to ``target`` created instances. Runs under the
        delayed raft flush policy; ends with an fsync barrier back to
        ``immediate`` so every later crash only ever eats bytes the
        invariants never covered."""
        leader = self._leader()
        if leader is None:
            return
        leader.raft.flush_policy = "delayed"
        while self.created < target:
            n = min(self.cfg.batch_size, target - self.created)
            self._write_batch(self._creation_batch(n))
            self._observe_tiering()
        self._flush_barrier()
        self._note(label, created=self.created)

    def _flush_barrier(self) -> None:
        leader = self._leader()
        if leader is None:
            return
        leader.raft._flush_journal()
        leader.raft.flush_policy = "immediate"

    def _run_spill(self, ticks: int, until_spilled: int | None = None) -> None:
        for _ in range(ticks):
            self.chaos.run_ticks(1)
            self._observe_tiering()
            leader = self._leader()
            if (until_spilled is not None and leader is not None
                    and leader.tiering is not None
                    and leader.tiering.spilled_instances >= until_spilled):
                return

    def _correlation_storm(self) -> int:
        """Bursts of publishes against parked keys: each wakes a (usually
        cold) instance, completes it, and re-exercises spill afterwards."""
        woken = 0
        for _ in range(self.cfg.storm_bursts):
            burst = min(self.cfg.storm_size, len(self.parked_keys))
            picks = [self.parked_keys.pop(
                self.rng.randrange(len(self.parked_keys)))
                for _ in range(burst)]
            for i in range(0, len(picks), self.cfg.batch_size):
                self._write_batch([command(
                    ValueType.MESSAGE, MessageIntent.PUBLISH,
                    {"name": "scale-msg", "correlationKey": key,
                     "timeToLive": 60_000, "messageId": "", "variables": {}})
                    for key in picks[i:i + self.cfg.batch_size]])
            woken += burst
            self.chaos.run_ticks(5)
            self._observe_tiering()
        self._note("storm", woken=woken)
        return woken

    # -- crash / recovery ------------------------------------------------------

    def _crash_restart(self, label: str, tamper: bool = False) -> None:
        leader = self._leader()
        node_id = self.cluster.leader_broker(self.cfg.partition_id).cfg.node_id
        stats = (leader.db.tier_stats()
                 if hasattr(leader.db, "tier_stats") else {})
        self.cluster.hard_crash_broker(node_id)
        self.chaos.clear_exporter_watermarks(node_id)
        # drop our references to the dead broker's state and collect NOW:
        # without this the old life's hot dict and the restarted life's
        # recovered state are resident simultaneously, and the measured peak
        # reports the harness's GC laziness instead of the engine's footprint
        leader = None
        import gc

        gc.collect()
        tampered = None
        if tamper:
            from zeebe_tpu.testing.soak import tamper_newest_snapshot

            tampered = tamper_newest_snapshot(
                self.cluster.directory, node_id, self.cfg.partition_id)
        restart_ms = self.cluster.clock()
        restart_wall = time.perf_counter()
        self.cluster.restart_broker(node_id)
        self.chaos.clear_exporter_watermarks(node_id)
        leader = None
        for _ in range(self.cfg.drain_ticks):
            self.chaos.run_ticks(1)
            leader = self._leader()
            if leader is not None and leader.last_recovery is not None:
                break
        if leader is None:
            self.violations.append(
                f"{label}: no leader within {self.cfg.drain_ticks} ticks "
                f"(seed {self.cfg.seed})")
            return
        rec = dict(leader.last_recovery or {}, label=label,
                   tamperedSnapshot=tampered,
                   coldAtCrash=stats.get("coldKeys"),
                   restartWallS=round(time.perf_counter() - restart_wall, 2))
        self.recoveries.append(rec)
        if not rec.get("withinBudget", False):
            self.violations.append(
                f"{label}: recovery blew the budget "
                f"({rec.get('durationMs')}ms > {rec.get('budgetMs')}ms)")
        self._collect_flight_dumps(label, node_id, restart_ms)
        self._note(label, recoveryMs=rec.get("durationMs"))

    def _collect_flight_dumps(self, label: str, node_id: str,
                              since_ms: int) -> None:
        from zeebe_tpu.testing.evidence import collect_flight_dumps

        collect_flight_dumps(self.cluster.directory / node_id,
                             self.flight_dumps, since_ms, label,
                             self.violations)

    # -- probes ----------------------------------------------------------------

    def _sweep_probe(self, label: str) -> None:
        """Time one due-date sweep against the current parked backlog —
        the O(due)-not-O(parked) receipt (nothing is due: parked timers sit
        hours out, so the sweep should be microseconds regardless of
        backlog size)."""
        leader = self._leader()
        if leader is None or leader.checkers is None:
            return
        parked_timers = leader.db.key_counts_by_cf().get("TIMER_DUE_DATES", 0)
        t0 = time.perf_counter()
        leader.checkers._sweep()
        sweep_ms = (time.perf_counter() - t0) * 1000.0
        t0 = time.perf_counter()
        leader.checkers.reschedule()
        resched_ms = (time.perf_counter() - t0) * 1000.0
        self.sweep_probes.append({
            "label": label, "parkedTimers": parked_timers,
            "sweepMs": round(sweep_ms, 3),
            "rescheduleMs": round(resched_ms, 3)})

    def _wake_probe_after_recovery(self) -> None:
        """Messages published AFTER the crash must correlate into instances
        parked (and spilled) BEFORE it."""
        leader = self._leader()
        if leader is None:
            return
        n = min(self.cfg.wake_probe, len(self.parked_keys))
        if n == 0:
            return
        subs_before = leader.db.key_counts_by_cf().get(
            "MESSAGE_SUBSCRIPTION_BY_KEY", 0)
        picks = [self.parked_keys.pop() for _ in range(n)]
        for i in range(0, n, self.cfg.batch_size):
            self._write_batch([command(
                ValueType.MESSAGE, MessageIntent.PUBLISH,
                {"name": "scale-msg", "correlationKey": key,
                 "timeToLive": 60_000, "messageId": "", "variables": {}})
                for key in picks[i:i + self.cfg.batch_size]])
        self.chaos.run_ticks(10)
        leader = self._leader()
        subs_after = leader.db.key_counts_by_cf().get(
            "MESSAGE_SUBSCRIPTION_BY_KEY", 0)
        if subs_after > subs_before - n:
            self.violations.append(
                f"wake-after-recovery: only {subs_before - subs_after} of "
                f"{n} pre-crash parked instances completed on post-crash "
                f"correlation")
        self._note("wake-probe", woken=subs_before - subs_after)

    # -- final invariants ------------------------------------------------------

    def _final_checks(self) -> None:
        cfg = self.cfg
        # acked completeness: contiguity covered past every acked position
        acked_max = max((last for _, last in self.acked_ranges), default=0)
        if self.ledger.covered_upto < acked_max:
            self.violations.append(
                f"acked records lost: export coverage stops at "
                f"{self.ledger.covered_upto}, acked up to {acked_max}")
        self.violations.extend(self.ledger.violations)
        self.chaos.check_exactly_once_materialization(cfg.partition_id)
        if cfg.replay_parity_check:
            # replay the journal over the recovered chain and require the
            # result byte-equals the LIVE (partially cold) state — the
            # spilled-instances-survive-crash-recovery-byte-identically gate
            self.chaos.check_replay_equivalence(cfg.partition_id)
        self.violations.extend(self.chaos.violations)
        if self.created < cfg.target_parked:
            self.violations.append(
                f"only created {self.created} of {cfg.target_parked}")
        spill_fraction = self.peak_spilled / max(self.created, 1)
        if spill_fraction < cfg.min_spilled_fraction:
            self.violations.append(
                f"cold tier held only {self.peak_spilled} instances at peak "
                f"({spill_fraction:.0%} of {self.created}; gate "
                f"{cfg.min_spilled_fraction:.0%}) — tiering is not bounding "
                f"the hot set")
        if self.peak_rss > cfg.rss_bound_bytes:
            self.violations.append(
                f"peak RSS {self.peak_rss / (1 << 20):.0f} MiB exceeds the "
                f"bound {cfg.rss_bound_bytes / (1 << 20):.0f} MiB")
        broker = self.cluster.leader_broker(cfg.partition_id)
        if broker is not None and broker.alerts is not None:
            firing = broker.alerts.firing()
            self.firing_alerts = firing
            if any(a.get("rule") == "rss_watermark" for a in firing):
                self.violations.append("rss_watermark alert is firing")
        else:
            self.firing_alerts = []

    # -- the run ---------------------------------------------------------------

    def run(self) -> dict:
        cfg = self.cfg
        try:
            self.cluster.await_leaders()
            self._deploy()
            self._sweep_probe("empty")
            # phase A: park ~40%, crash MID-SPILL, recover
            self._park_until(int(cfg.target_parked * 0.4), "park-A")
            self._run_spill(
                ticks=cfg.drain_ticks,
                until_spilled=max(int(self.created * 0.2), 1))
            leader = self._leader()
            if leader is None or leader.tiering is None \
                    or leader.tiering.spilled_instances == 0:
                self.violations.append(
                    "phase A never spilled — cannot crash mid-spill")
            self._crash_restart("crash-mid-spill")
            # phase B: park the rest; snapshots keep landing under load.
            # RSS growth across this phase is the headline bounded-memory
            # gate: parked instances spill, so residency must grow by a
            # small stub per instance, not a decoded object tree.
            self._run_spill(ticks=cfg.drain_ticks // 2,
                            until_spilled=max(
                                int(self.created * 0.5), 1))
            rss_before_b = self._sample_rss()
            created_before_b = self.created
            self._park_until(cfg.target_parked, "park-B")
            self._run_spill(
                ticks=cfg.drain_ticks,
                until_spilled=int(self.created * cfg.min_spilled_fraction))
            self._observe_tiering()
            parked_in_b = self.created - created_before_b
            growth = self._sample_rss() - rss_before_b
            per_instance = growth / max(parked_in_b, 1)
            self._note("park-B-growth", rssGrowthBytes=growth,
                       perParkedInstanceBytes=round(per_instance, 1))
            if per_instance > cfg.max_hot_growth_per_parked:
                self.violations.append(
                    f"hot residency grew {per_instance:.0f} bytes per "
                    f"newly-parked instance over phase B (gate "
                    f"{cfg.max_hot_growth_per_parked}) — spilling is not "
                    f"bounding the hot set")
            self._sweep_probe("parked")
            # correlation storm wakes cold instances under sustained load
            self._correlation_storm()
            # settle spill again, then crash with a TORN newest snapshot
            self._run_spill(ticks=60)
            leader = self._leader()
            if leader is not None:
                leader.take_snapshot()  # one more snapshot under load
            self._crash_restart("crash-torn-snapshot", tamper=True)
            self._wake_probe_after_recovery()
            self._run_spill(ticks=40)
            self._sweep_probe("after-recovery")
            self.chaos.quiesce(40)
            self._final_checks()
            return self.report()
        finally:
            self.chaos.close()

    def report(self) -> dict:
        cfg = self.cfg
        durations = [r.get("durationMs", 0.0) for r in self.recoveries]
        return {
            "seed": cfg.seed,
            "targetParked": cfg.target_parked,
            "created": self.created,
            "peakSpilledInstances": self.peak_spilled,
            "peakSpilledFraction": round(
                self.peak_spilled / max(self.created, 1), 3),
            "rss": {
                "peakBytes": self.peak_rss,
                "peakMiB": round(self.peak_rss / (1 << 20), 1),
                "boundBytes": cfg.rss_bound_bytes,
                "withinBound": self.peak_rss <= cfg.rss_bound_bytes,
            },
            "exports": {
                "total": self.ledger.total,
                "coveredUpto": self.ledger.covered_upto,
                "reexports": self.ledger.reexports,
                "reexportsUnverified": self.ledger.reexports_unverified,
            },
            "ackedBatches": len(self.acked_ranges),
            "recoveries": self.recoveries,
            "recoveryMs": {
                "max": max(durations, default=0.0),
                "budget": cfg.recovery_budget_ms,
            },
            "withinBudget": all(
                r.get("withinBudget", False) for r in self.recoveries),
            "sweepProbes": self.sweep_probes,
            "firingAlerts": getattr(self, "firing_alerts", []),
            "flightDumps": self.flight_dumps,
            "timeline": self.timeline,
            "violations": self.violations,
        }


def run_scale_soak(cfg: ScaleSoakConfig | None = None,
                   directory: str | Path | None = None) -> dict:
    """One-call entry point (bench.py --scale-soak, tests)."""
    return ScaleSoakHarness(cfg, directory=directory).run()
