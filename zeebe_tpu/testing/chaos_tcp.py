"""TCP-layer chaos: seeded fault injection for the multi-process cluster.

PR 1's ``ChaosNetwork`` made the in-process loopback cluster chaos-testable;
the PR 7 TCP stack (gateway↔worker envelopes, worker↔worker Raft/SWIM) had
never been pointed at a fault injector at all. ``ChaosTcpMessagingService``
wraps any :class:`~zeebe_tpu.cluster.messaging.MessagingService` — in
practice each process's ``TcpMessagingService`` — and applies a seeded
:class:`~zeebe_tpu.testing.chaos.FaultPlan` to every outbound frame:

- **drop / duplicate / delay / reorder** with the plan's per-message
  probabilities (delay = ``1..max_delay_ticks`` × ``tick_ms``; reorder =
  held past the frames sent after it, released on the next pump poll);
- **scheduled link partitions**: ``LinkWindow`` entries block both
  directions of a member pair for a wall-clock window relative to a shared
  epoch — every process gets the same spec + epoch through the environment,
  so both ends of a link agree on when it is down.

Each process derives its RNG from ``seed ^ crc32(member id)``: a given
member's fault stream is reproducible for a fixed send sequence, and
distinct members don't mirror each other's decisions. (Unlike the loopback
harness this is *seeded*, not bit-reproducible — real TCP scheduling varies
between runs; the consistency checker's invariants are what must hold under
every interleaving.)

Environment wiring (the worker process entry and the consistency harness):

- ``ZEEBE_CHAOS_TCP``   — the spec, e.g.
  ``seed=7,drop=0.02,dup=0.02,delay=0.05,reorder=0.02,max_delay_ticks=3,
  tick_ms=50;partition=worker-0|worker-1@3000-6000;partition=worker-2|*@9000-10500``
- ``ZEEBE_CHAOS_EPOCH_MS`` — shared wall-clock epoch (unix millis) the
  partition windows are relative to.
- ``ZEEBE_CHAOS_TCP_WINDOWSFILE`` — path to a dynamically (re)loaded
  windows file (one ``a|b@start-end`` line per window, epoch-relative ms):
  the chaos controller (the consistency harness) writes it AFTER boot
  completes, so windows land mid-drive regardless of how long the worker
  fleet took to come up. Reloaded on mtime change, throttled.

The supervisor ``kill_worker`` storm rides next to this at the harness
level (testing/consistency.py): process kills are scheduled against the
same epoch, so one seed describes the whole fault scenario.
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import os
import threading
import time
from typing import Any

from zeebe_tpu.testing.chaos import FaultPlan
from zeebe_tpu.testing.chaos_common import (
    CountsSnapshot,
    member_rng,
    parse_spec_fields,
)

logger = logging.getLogger("zeebe_tpu.testing.chaos_tcp")


@dataclasses.dataclass(frozen=True)
class LinkWindow:
    """Both directions of the (a, b) link are down during
    [start_ms, end_ms) relative to the shared epoch; ``b == "*"`` isolates
    member ``a`` from everyone."""

    a: str
    b: str
    start_ms: int
    end_ms: int

    def matches(self, x: str, y: str) -> bool:
        if self.b == "*":
            return self.a in (x, y)
        return {self.a, self.b} == {x, y}


def format_spec(plan: FaultPlan, windows: list[LinkWindow] = (),
                tick_ms: int = 50) -> str:
    parts = [
        f"seed={plan.seed},drop={plan.drop_p},dup={plan.duplicate_p},"
        f"delay={plan.delay_p},reorder={plan.reorder_p},"
        f"max_delay_ticks={plan.max_delay_ticks},tick_ms={tick_ms}"
    ]
    for w in windows:
        parts.append(f"partition={w.a}|{w.b}@{w.start_ms}-{w.end_ms}")
    return ";".join(parts)


def parse_spec(spec: str) -> tuple[FaultPlan, list[LinkWindow], int]:
    """Inverse of :func:`format_spec`; returns (plan, windows, tick_ms)."""
    plan = FaultPlan()
    windows: list[LinkWindow] = []
    tick_ms = 50
    for section in spec.split(";"):
        section = section.strip()
        if not section:
            continue
        if section.startswith("partition="):
            link, _, span = section[len("partition="):].partition("@")
            a, _, b = link.partition("|")
            start, _, end = span.partition("-")
            windows.append(LinkWindow(a.strip(), b.strip() or "*",
                                      int(start), int(end)))
            continue
        tick_box: list[int] = []
        parse_spec_fields(section, {
            "seed": lambda v: setattr(plan, "seed", int(v)),
            "drop": lambda v: setattr(plan, "drop_p", float(v)),
            "dup": lambda v: setattr(plan, "duplicate_p", float(v)),
            "delay": lambda v: setattr(plan, "delay_p", float(v)),
            "reorder": lambda v: setattr(plan, "reorder_p", float(v)),
            "max_delay_ticks": lambda v: setattr(plan, "max_delay_ticks",
                                                 int(v)),
            "tick_ms": lambda v: tick_box.append(int(v)),
        })
        if tick_box:
            tick_ms = tick_box[-1]
    return plan, windows, tick_ms


class ChaosTcpMessagingService:
    """Fault-injecting wrapper around a started messaging service."""

    def __init__(self, inner, plan: FaultPlan,
                 windows: list[LinkWindow] = (),
                 epoch_ms: float | None = None,
                 tick_ms: int = 50) -> None:
        self.inner = inner
        self.plan = plan
        self.windows = list(windows)
        self.epoch_ms = time.time() * 1000.0 if epoch_ms is None else epoch_ms
        self.tick_ms = max(tick_ms, 1)
        # per-member stream: same seed ⇒ same decisions for the same send
        # sequence, but member A and member B never mirror each other
        self.rng = member_rng(plan.seed, inner.member_id)
        self.counts = {
            "sent": 0, "dropped": 0, "duplicated": 0, "delayed": 0,
            "reordered": 0, "link_blocked": 0,
        }
        self._lock = threading.Lock()
        self._held: list[tuple[float, int, tuple[str, str, Any]]] = []
        self._held_seq = 0
        # reordering holds a frame PER PEER until a later frame to that peer
        # actually overtakes it (released right after that send); poll()
        # flushes stragglers past this age so a quiet link never parks one
        self._reorder_held: dict[str, list[tuple[float, str, Any]]] = {}
        self._reorder_max_hold_s = 0.25
        # periodic counts evidence for the consistency report: a SIGKILLed
        # worker loses at most one dump interval of observations
        self._counts_snap = CountsSnapshot(inner.member_id)
        # dynamically-reloaded windows (the chaos controller writes the
        # file once the fleet is actually up): mtime-checked, throttled
        self.windows_file = None
        self._windows_mtime = -1.0
        self._last_windows_check = 0.0

    # -- delegation ------------------------------------------------------------

    @property
    def member_id(self) -> str:
        return self.inner.member_id

    def subscribe(self, topic: str, handler) -> None:
        self.inner.subscribe(topic, handler)

    def unsubscribe(self, topic: str) -> None:
        self.inner.unsubscribe(topic)

    def start(self) -> None:
        start = getattr(self.inner, "start", None)
        if start is not None:
            start()

    def stop(self) -> None:
        stop = getattr(self.inner, "stop", None)
        if stop is not None:
            stop()

    def poll(self, max_messages: int = 10_000) -> int:
        self._release_due()
        self._flush_stale_reorders()
        self._maybe_reload_windows()
        self._maybe_dump_counts()
        poll = getattr(self.inner, "poll", None)
        return poll(max_messages) if poll is not None else 0

    def _maybe_reload_windows(self) -> None:
        if self.windows_file is None:
            return
        now = time.time()
        if now - self._last_windows_check < 0.25:
            return
        self._last_windows_check = now
        try:
            mtime = os.stat(self.windows_file).st_mtime
        except OSError:
            return  # controller has not written it yet
        if mtime == self._windows_mtime:
            return
        self._windows_mtime = mtime
        try:
            lines = open(self.windows_file, encoding="utf-8").read()
        except OSError:
            return
        windows = []
        for line in lines.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                link, _, span = line.partition("@")
                a, _, b = link.partition("|")
                start, _, end = span.partition("-")
                windows.append(LinkWindow(a.strip(), b.strip() or "*",
                                          int(start), int(end)))
            except ValueError:
                logger.error("ignoring malformed chaos window line %r", line)
        self.windows = windows
        logger.warning("chaos windows reloaded for %s: %s",
                       self.inner.member_id, windows)

    # -- fault application -----------------------------------------------------

    def _link_blocked(self, member_id: str) -> bool:
        if not self.windows:
            return False
        rel = time.time() * 1000.0 - self.epoch_ms
        me = self.inner.member_id
        return any(w.start_ms <= rel < w.end_ms and w.matches(me, member_id)
                   for w in self.windows)

    def send(self, member_id: str, topic: str, payload: Any) -> None:
        self._release_due()
        if self._link_blocked(member_id):
            self.counts["link_blocked"] += 1
            return
        plan = self.plan
        r = self.rng.random()
        if r < plan.drop_p:
            self.counts["dropped"] += 1
            return
        r -= plan.drop_p
        if r < plan.duplicate_p:
            self.counts["duplicated"] += 1
            self.counts["sent"] += 2
            self.inner.send(member_id, topic, payload)
            self.inner.send(member_id, topic, payload)
            return
        r -= plan.duplicate_p
        if r < plan.delay_p:
            ticks = 1 + self.rng.randrange(max(plan.max_delay_ticks, 1))
            self.counts["delayed"] += 1
            self._hold(time.time() + ticks * self.tick_ms / 1000.0,
                       member_id, topic, payload)
            return
        r -= plan.delay_p
        if r < plan.reorder_p:
            # held until the NEXT frame to this peer goes out first — a real
            # overtake on the peer's otherwise-ordered TCP stream (released
            # below, right after that later send)
            self.counts["reordered"] += 1
            self._reorder_held.setdefault(member_id, []).append(
                (time.time(), topic, payload))
            return
        self.counts["sent"] += 1
        self.inner.send(member_id, topic, payload)
        held = self._reorder_held.pop(member_id, None)
        if held:
            for _t, held_topic, held_payload in held:
                self.counts["sent"] += 1
                self.inner.send(member_id, held_topic, held_payload)

    def _hold(self, due_s: float, member_id: str, topic: str,
              payload: Any) -> None:
        with self._lock:
            self._held_seq += 1
            heapq.heappush(self._held,
                           (due_s, self._held_seq, (member_id, topic, payload)))

    def _release_due(self) -> None:
        now = time.time()
        released = []
        with self._lock:
            while self._held and self._held[0][0] <= now:
                released.append(heapq.heappop(self._held)[2])
        for member_id, topic, payload in released:
            if self._link_blocked(member_id):
                self.counts["link_blocked"] += 1
                continue
            self.counts["sent"] += 1
            self.inner.send(member_id, topic, payload)

    def _flush_stale_reorders(self) -> None:
        """A held-for-reorder frame on a link with no later traffic must
        still go out eventually — flush past the max hold age."""
        if not self._reorder_held:
            return
        horizon = time.time() - self._reorder_max_hold_s
        for member_id in list(self._reorder_held):
            held = self._reorder_held[member_id]
            while held and held[0][0] <= horizon:
                _t, topic, payload = held.pop(0)
                self.counts["sent"] += 1
                self.inner.send(member_id, topic, payload)
            if not held:
                del self._reorder_held[member_id]

    @property
    def counts_file(self):
        return self._counts_snap.counts_file

    @counts_file.setter
    def counts_file(self, value) -> None:
        self._counts_snap.counts_file = value

    def _maybe_dump_counts(self) -> None:
        """Throttled counts snapshot to ``counts_file`` (set by the worker
        entry): the consistency report aggregates these as OBSERVED fault
        evidence — configured-but-never-applied chaos must be visible."""
        self._counts_snap.maybe_dump(self.counts)


class ZombiePeer:
    """Slow-client / zombie-client chaos seam (ISSUE 11): a listening TCP
    endpoint that ACCEPTS connections and never reads a byte — the shape of
    a client stream that wedged mid-download or a gateway whose process is
    SIGSTOPped. Register its address as a peer of a
    :class:`~zeebe_tpu.cluster.messaging.TcpMessagingService` and keep
    sending: the kernel receive window (shrunk via ``recv_buffer``) fills,
    the sender's transport buffer grows, and the sender's per-stream
    outbound bound must disconnect-on-overflow instead of blocking its pump
    or buffering without limit."""

    def __init__(self, host: str = "127.0.0.1", recv_buffer: int = 4096):
        import socket

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # tiny receive buffer BEFORE listen so accepted sockets inherit it:
        # the kernel-side window fills after a few frames instead of 100s
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, recv_buffer)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self.address: tuple[str, int] = self._sock.getsockname()
        self.accepted = 0
        self._conns: list = []
        self._closing = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True,
                                        name="zombie-peer")
        self._thread.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.2)
        while not self._closing.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                continue
            # never read: the connection stays open, the window stays shut
            self.accepted += 1
            self._conns.append(conn)

    def close(self) -> None:
        self._closing.set()
        self._thread.join(timeout=2)
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


def maybe_wrap_chaos(messaging, env: dict | None = None):
    """Wrap ``messaging`` in a :class:`ChaosTcpMessagingService` when
    ``ZEEBE_CHAOS_TCP`` is set; pass it through untouched otherwise."""
    env = os.environ if env is None else env
    spec = env.get("ZEEBE_CHAOS_TCP")
    if not spec:
        return messaging
    try:
        plan, windows, tick_ms = parse_spec(spec)
        epoch = float(env["ZEEBE_CHAOS_EPOCH_MS"]) \
            if env.get("ZEEBE_CHAOS_EPOCH_MS") else None
    except (ValueError, KeyError) as exc:
        logger.error("ignoring malformed ZEEBE_CHAOS_TCP %r: %s", spec, exc)
        return messaging
    wrapped = ChaosTcpMessagingService(messaging, plan, windows,
                                       epoch_ms=epoch, tick_ms=tick_ms)
    wrapped.windows_file = env.get("ZEEBE_CHAOS_TCP_WINDOWSFILE") or None
    logger.warning("TCP chaos ACTIVE for %s: %s", messaging.member_id, spec)
    return wrapped
