"""Deterministic chaos harness: seeded fault plans over the loopback cluster.

In the spirit of FoundationDB-style deterministic simulation testing and
Jepsen-style invariant checking: every fault decision (drop, duplicate,
reorder, delay, partition, crash-restart) is drawn from a single seeded RNG
over a deterministic cluster (ControlledClock + per-member deterministic raft
jitter), so a failing run is replayable bit-for-bit from its seed alone.

Pieces:

- ``FaultPlan``     — the seed + per-link fault probabilities + a scheduled
                      event list (partitions, isolations, heals, crashes,
                      restarts at fixed ticks).
- ``ChaosNetwork``  — a ``LoopbackNetwork`` whose enqueue path applies the
                      plan's faults and records every decision in ``trace``
                      (two runs with the same plan produce identical traces).
- ``ChaosHarness``  — drives an ``InProcessCluster`` over a ChaosNetwork tick
                      by tick, executes the plan's scheduled events, samples
                      exporter/commit positions each tick, and checks the
                      chaos invariants at the end.
- ``replay_state_of`` — rebuilds engine state from a partition's journal in a
                      fresh db (replay ≡ processing oracle).

The active seed is published module-globally so the test conftest can print it
on failure (reproduce with ``FaultPlan(seed=<printed seed>)``).
"""

from __future__ import annotations

import dataclasses
import random
from pathlib import Path
from typing import Any, Callable

from zeebe_tpu.cluster.messaging import LoopbackNetwork

_ACTIVE_SEED: int | None = None


def active_fault_seed() -> int | None:
    """Seed of the most recently constructed ChaosNetwork (conftest prints it
    when a chaos test fails, for reproduction)."""
    return _ACTIVE_SEED


@dataclasses.dataclass
class FaultPlan:
    """Seeded fault schedule. Probabilities apply per enqueued message; the
    event list maps a harness tick to a cluster-level fault action:
    ``("partition", a, b)``, ``("isolate", m)``, ``("heal",)``,
    ``("heal", m)``, ``("crash", m)``, ``("restart", m)``."""

    seed: int = 0
    drop_p: float = 0.0
    duplicate_p: float = 0.0
    reorder_p: float = 0.0
    delay_p: float = 0.0
    max_delay_ticks: int = 3
    # event kinds: ("partition", a, b), ("isolate", m), ("heal",) /
    # ("heal", m), ("crash", m) (clean close), ("hard-crash", m) (power
    # loss at the flush boundary: unflushed journal bytes are lost),
    # ("restart", m)
    events: dict[int, list[tuple]] = dataclasses.field(default_factory=dict)

    def at(self, tick: int, *event: Any) -> "FaultPlan":
        """Fluent event registration: ``plan.at(40, "crash", "broker-1")``."""
        self.events.setdefault(tick, []).append(tuple(event))
        return self


class ChaosNetwork(LoopbackNetwork):
    """LoopbackNetwork with seeded per-message fault injection.

    Fault decisions happen at *enqueue* time — message send order is
    deterministic under the controlled clock, so one RNG stream reproduces
    the exact same drop/duplicate/reorder/delay schedule for a given seed.
    Delayed messages are re-injected by ``advance_tick`` (driven once per
    harness tick)."""

    def __init__(self, plan: FaultPlan, lanes: int = 0) -> None:
        super().__init__(lanes=lanes)
        global _ACTIVE_SEED
        _ACTIVE_SEED = plan.seed
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.trace: list[str] = []
        self.delivered_log: list[tuple[str, str, str]] = []
        self.chaos_dropped = 0
        self.chaos_duplicated = 0
        self.chaos_reordered = 0
        self.chaos_delayed = 0
        self._tick = 0
        self._msg_seq = 0
        self._held: list[tuple[int, tuple[str, str, str, Any]]] = []

    # -- fault application -----------------------------------------------------

    def enqueue(self, sender: str, target: str, topic: str, payload: Any) -> None:
        plan = self.plan
        i = self._msg_seq
        self._msg_seq += 1
        r = self.rng.random()
        if r < plan.drop_p:
            self.chaos_dropped += 1
            self.trace.append(f"drop#{i} {sender}->{target} {topic}")
            return
        r -= plan.drop_p
        if r < plan.duplicate_p:
            self.chaos_duplicated += 1
            self.trace.append(f"dup#{i} {sender}->{target} {topic}")
            super().enqueue(sender, target, topic, payload)
            super().enqueue(sender, target, topic, payload)
            return
        r -= plan.duplicate_p
        if r < plan.delay_p:
            ticks = 1 + self.rng.randrange(max(plan.max_delay_ticks, 1))
            self.chaos_delayed += 1
            self.trace.append(f"delay#{i}+{ticks} {sender}->{target} {topic}")
            self._held.append((self._tick + ticks, (sender, target, topic, payload)))
            return
        r -= plan.delay_p
        if r < plan.reorder_p:
            q = self._queues[self.lane_of(topic)]
            pos = self.rng.randrange(len(q) + 1)
            self.chaos_reordered += 1
            self.trace.append(f"reorder#{i}@{pos} {sender}->{target} {topic}")
            q.insert(pos, (sender, target, topic, payload))
            return
        super().enqueue(sender, target, topic, payload)

    def advance_tick(self) -> None:
        """Release held (delayed) messages whose tick arrived. Re-injection
        goes through the base enqueue — a delayed message is not re-faulted,
        matching one decision per message."""
        self._tick += 1
        due = [m for t, m in self._held if t <= self._tick]
        self._held = [(t, m) for t, m in self._held if t > self._tick]
        for sender, target, topic, payload in due:
            super().enqueue(sender, target, topic, payload)

    def deliver_one(self, lane: int = 0) -> bool:
        q = self._queues[lane]
        if q:
            sender, target, topic, _ = q[0]
            self.delivered_log.append((sender, target, topic))
        return super().deliver_one(lane)


def replay_state_of(partition, partition_count: int | None = None):
    """Rebuild engine state by replaying a partition's committed journal into
    a fresh db (the replay ≡ processing oracle: the result must equal the
    partition's live db, reference: ReplayStateMachine / ClusteringRule's
    follower-state assertions).

    Recovery starts from the partition's latest snapshot when one exists —
    a replica that ever received a raft install-snapshot has a truncated
    stream journal, so position 1 is not necessarily on disk (exactly the
    recovery path a real restart takes)."""
    from zeebe_tpu.engine.engine import Engine
    from zeebe_tpu.state import ZbDb
    from zeebe_tpu.stream import StreamProcessor, StreamProcessorMode

    from zeebe_tpu.state.snapshot import STATE_FILE, load_chain_db

    chain = partition.snapshot_store.latest_valid_chain()
    if chain is not None and chain[0].has_file(STATE_FILE):
        db = load_chain_db(chain)
    else:
        db = ZbDb(consistency_checks=False)
    # migrations run between recovery and replay, exactly like _transition
    from zeebe_tpu.engine.migration import DbMigrator

    DbMigrator(db).run_migrations()
    engine = Engine(db, partition.partition_id,
                    clock_millis=partition.clock_millis,
                    partition_count=partition_count or partition.partition_count)
    processor = StreamProcessor(
        partition.stream, db, engine, mode=StreamProcessorMode.REPLAY,
        clock_millis=partition.clock_millis,
    )
    processor.start()
    processor.replay_available()
    return db


def engine_state_equals(a, b) -> bool:
    """Replay ≡ processing oracle comparison: all engine state EXCEPT the
    EXPORTER column family — exporter acks are runtime-local side effects of
    the export loop (each replica/restart re-acks at its own pace), not
    event-sourced state, so replay legitimately cannot reproduce them."""
    import struct

    from zeebe_tpu.state.db import ColumnFamilyCode

    prefix = struct.pack(">H", int(ColumnFamilyCode.EXPORTER))
    # tiered stores hold ColdRef stubs in _data: resolve to the logical
    # value so a partially-spilled partition compares byte-identically
    ra = getattr(a, "_resolve", lambda v: v)
    rb = getattr(b, "_resolve", lambda v: v)
    fa = {k: ra(v) for k, v in a._data.items() if not k.startswith(prefix)}
    fb = {k: rb(v) for k, v in b._data.items() if not k.startswith(prefix)}
    return fa == fb


class ChaosHarness:
    """Drives an InProcessCluster tick-by-tick under a FaultPlan, executing
    scheduled faults and sampling the per-tick invariant observables
    (exporter positions vs commit positions)."""

    def __init__(self, plan: FaultPlan, broker_count: int = 3,
                 partition_count: int = 1, replication_factor: int = 3,
                 directory: str | Path | None = None,
                 exporters_factory: Callable[[], dict[str, Any]] | None = None,
                 step_ms: int = 50,
                 snapshot_period_ms: int = 5 * 60 * 1000,
                 recovery_budget_ms: int = 60_000,
                 snapshot_chain_length: int = 8,
                 tiering: bool = False,
                 tiering_park_after_ms: int = 30_000,
                 tiering_spill_batch: int = 256) -> None:
        from zeebe_tpu.broker import InProcessCluster

        self.plan = plan
        self.net = ChaosNetwork(plan)
        self.cluster = InProcessCluster(
            broker_count=broker_count, partition_count=partition_count,
            replication_factor=replication_factor, directory=directory,
            exporters_factory=exporters_factory, network=self.net,
            snapshot_period_ms=snapshot_period_ms,
            recovery_budget_ms=recovery_budget_ms,
            snapshot_chain_length=snapshot_chain_length,
            tiering=tiering,
            tiering_park_after_ms=tiering_park_after_ms,
            tiering_spill_batch=tiering_spill_batch,
        )
        self.step_ms = step_ms
        self.tick = 0
        self.violations: list[str] = []
        # (node, partition, exporter_id) -> (container identity, last sampled
        # acked position) — identity scopes monotonicity to one director life
        self._exporter_watermarks: dict[tuple[str, int, str], tuple] = {}

    def close(self) -> None:
        # the active seed intentionally survives close(): the conftest
        # failure hook reads it AFTER the test's finally-block teardown, and
        # only chaos-marked tests report it
        self.cluster.close()

    # -- scheduled fault execution --------------------------------------------

    def _execute(self, event: tuple) -> None:
        kind, *args = event
        if kind == "partition":
            self.net.partition(args[0], args[1])
        elif kind == "isolate":
            self.net.isolate(args[0])
        elif kind == "heal":
            self.net.heal(*args)
        elif kind == "crash":
            self.cluster.stop_broker(args[0])
            self.clear_exporter_watermarks(args[0])
        elif kind == "hard-crash":
            # power loss at the flush boundary: journals keep only the
            # fsync-covered prefix (buffered group-commit appends are lost)
            self.cluster.hard_crash_broker(args[0])
            self.clear_exporter_watermarks(args[0])
        elif kind == "restart":
            self.cluster.restart_broker(args[0])
            self.clear_exporter_watermarks(args[0])
        else:
            raise ValueError(f"unknown chaos event {event!r}")

    def clear_exporter_watermarks(self, node_id: str) -> None:
        """A crash-restart recovers exporter positions from the last snapshot
        (at-least-once re-export) — the monotonicity invariant holds within a
        broker lifetime, so the node's watermarks reset across restarts."""
        for key in [k for k in self._exporter_watermarks if k[0] == node_id]:
            del self._exporter_watermarks[key]

    # -- tick loop -------------------------------------------------------------

    def run_ticks(self, ticks: int) -> None:
        """Advance the cluster ``ticks`` steps of ``step_ms`` each, executing
        scheduled events, releasing delayed traffic, and sampling exporter
        invariants after every step."""
        for _ in range(ticks):
            self.tick += 1
            for event in self.plan.events.get(self.tick, ()):  # faults first
                self._execute(event)
            self.net.advance_tick()
            self.cluster.run(self.step_ms)
            self._sample_exporters()

    def run_plan(self, extra_ticks: int = 0) -> None:
        """Run through every scheduled event, then ``extra_ticks`` more."""
        horizon = max(self.plan.events, default=0) + extra_ticks
        self.run_ticks(horizon)

    def quiesce(self, ticks: int = 40) -> None:
        """Heal-all then run until the cluster settles (single leader per
        partition, queues drained)."""
        self.net.heal()
        self.run_ticks(ticks)

    # -- invariants ------------------------------------------------------------

    def _sample_exporters(self) -> None:
        for node, broker in list(self.cluster.brokers.items()):
            for pid, part in broker.partitions.items():
                director = part.exporter_director
                if director is None:
                    continue
                # the materialized stream journal IS the committed prefix
                # (entries land there only on raft commit — see
                # broker/partition.py), so last_position is the commit
                # position the exporters must never pass
                commit = part.stream.last_position
                for container in director.containers:
                    key = (node, pid, container.exporter_id)
                    # monotonicity holds per container lifetime: a role
                    # transition rebuilds the director over a re-recovered db
                    # (positions fall back to the snapshot — at-least-once),
                    # so a new container starts a new watermark. The container
                    # OBJECT is the identity (an id() could be recycled by a
                    # successor at the same address)
                    prev_cont, prev = self._exporter_watermarks.get(key, (None, 0))
                    pos = container.position
                    if prev_cont is container and pos < prev:
                        self.violations.append(
                            f"tick {self.tick}: exporter {key} position "
                            f"regressed {prev} -> {pos}")
                    # only an ADVANCE past commit is a violation: right
                    # after a crash-restart the cursor RECOVERED from state
                    # legitimately sits ahead of a stream journal that has
                    # not re-materialized yet (exports can only come from
                    # stream reads, so a recovered cursor can never advance
                    # until the stream passes it again). The advance baseline
                    # is the previous sample for an observed container, or
                    # the position recovered at open for a FIRST observation
                    # — without the latter, an export past commit inside the
                    # container's first tick would go unflagged
                    baseline = (prev if prev_cont is container
                                else container.recovered_position)
                    if pos > baseline and pos > commit:
                        self.violations.append(
                            f"tick {self.tick}: exporter {key} position {pos} "
                            f"advanced ahead of commit {commit}")
                    self._exporter_watermarks[key] = (container, pos)

    def check_exactly_once_materialization(self, partition_id: int = 1) -> None:
        """Committed records materialize exactly once: strictly increasing
        positions, no duplicates, no gaps inside a batch run."""
        leader = self.cluster.leader(partition_id)
        assert leader is not None, "no leader to check"
        last = 0
        for logged in leader.stream.new_reader(1):
            if logged.position <= last:
                self.violations.append(
                    f"position {logged.position} not increasing after {last}")
            last = logged.position

    def check_replay_equivalence(self, partition_id: int = 1) -> None:
        leader = self.cluster.leader(partition_id)
        assert leader is not None, "no leader to check"
        replayed = replay_state_of(leader)
        if not engine_state_equals(replayed, leader.db):
            self.violations.append(
                f"replayed state of partition {partition_id} diverges from "
                f"the leader's live state")

    def assert_no_violations(self) -> None:
        assert not self.violations, (
            f"chaos invariants violated (seed {self.plan.seed}):\n  "
            + "\n  ".join(self.violations[:20]))
